"""Chunked fused LM-head + cross-entropy: the ``(rows, vocab)`` logits
never materialize.

The tied LM head is the dominant non-attention cost of GPT training:
at bench scale (16x1024 tokens, 32k vocab) the bf16 logits tensor is
~1.07 GB and its gradient another ~1.07 GB, both round-tripping HBM
every step even though no consumer ever needs them at full size — the
loss is a per-row reduction and the gradients contract straight back
into ``dx`` and ``dW``. This module is the Liger-kernel design
(arXiv 2410.10989) expressed as a `lax.scan` over row chunks that XLA
schedules like a Pallas grid: flatten hidden states to
``(rows, hidden)``, iterate row chunks; per chunk compute
``logits_c = x_c @ W^T``, run the per-tile loss semantics of
`ops/xentropy._loss_block` (fp32 upcast, max/lse/target-gather, label
smoothing, ``padding_idx`` masking), and either

* save only the O(rows) ``lse`` statistics and recompute the chunk's
  softmax in the backward (`linear_cross_entropy_loss` — per-row
  losses, arbitrary cotangents), or
* form ``dlogits_c = p_c - onehot`` while the chunk is live and
  immediately contract it into ``dx_c = dlogits_c @ W`` and an
  accumulated ``dW += dlogits_c^T @ x_c``
  (`linear_cross_entropy_mean` — the train-step fast path, where the
  mean reduction makes the loss cotangent a scalar so the gradients
  can be finished inside the forward pass, Liger's FLCE trick: no
  recompute matmul, 6*N*H*V head FLOPs total, same as the
  materialized path).

Only chunk-sized ``(chunk, vocab)`` tiles ever exist; peak HBM for the
loss stage drops from 2 full logits-sized buffers to two chunk tiles
plus the fp32 ``dW`` accumulator. `vocab_parallel_linear_cross_entropy`
is the tp>1 variant: per-chunk partial max / sum-exp / target-gather
are psum'd over the tensor axis, the reduction structure of
`transformer/tensor_parallel/cross_entropy.py` applied chunk-wise.

When to prefer the materialized `ops.xentropy.
softmax_cross_entropy_loss_fused` instead: rows*vocab small enough
that the logits fit comfortably (the chunked scan then only adds loop
and ``dW``-accumulator overhead) — see docs/perf.md for the math.
"""

import functools

import jax
import jax.numpy as jnp

from rocm_apex_tpu.ops._pallas import SUBLANE
from rocm_apex_tpu.ops._pallas import pad_rows as _pad_rows
from rocm_apex_tpu.ops.xentropy import _loss_block

__all__ = [
    "linear_cross_entropy_loss",
    "linear_cross_entropy_mean",
    "vocab_parallel_linear_cross_entropy",
]

# Default chunk sizing: chunk*vocab ~ 2^27 elements keeps the two live
# low-precision chunk tiles (logits_c, dlogits_c) at ~256 MB each at
# bf16 while bounding the backward's dW-accumulator round trips at
# rows/chunk ~ 4 on the bench config (docs/perf.md quantifies the
# chunk-size tradeoff: smaller chunks shrink peak HBM linearly but pay
# one fp32 (vocab, hidden) accumulator read+write per chunk).
_DEFAULT_CHUNK_ELEMENTS = 1 << 27


def _chunk_rows(rows: int, vocab: int, chunk_size) -> int:
    if chunk_size is None:
        chunk_size = max(SUBLANE, _DEFAULT_CHUNK_ELEMENTS // max(1, vocab))
    chunk_size = max(SUBLANE, (chunk_size // SUBLANE) * SUBLANE)
    return min(chunk_size, max(SUBLANE, (rows + SUBLANE - 1) // SUBLANE * SUBLANE))


def _to_chunks(chunk, *arrays):
    """Pad rows to a multiple of ``chunk`` and reshape each (rows, c)
    array to (k, chunk, c) scan inputs. Padded rows are all-zero: zero
    hidden rows produce finite logits/losses and are sliced off (fwd)
    or carry a zero cotangent (bwd), so they never contribute."""
    out = []
    for a in arrays:
        ap = _pad_rows(a, chunk)
        out.append(ap.reshape(ap.shape[0] // chunk, chunk, *a.shape[1:]))
    return out


def _scan_chunks(body, init, xs):
    """`lax.scan` over chunk rows — except single-chunk calls (rows ≤
    chunk, the common test/eval scale), which inline the body: no loop
    tracing/compile cost and XLA sees a straight-line head."""
    if xs[0].shape[0] == 1:
        carry, out = body(init, tuple(a[0] for a in xs))
        return carry, jax.tree_util.tree_map(lambda o: o[None], out)
    return jax.lax.scan(body, init, xs)


def _chunk_logits(x_c, w):
    """One chunk of the head projection, fp32 for the loss math. The
    matmul accumulates in the compute dtype (the materialized `attend`
    path's `preferred_element_type`), the upcast fuses into the
    consuming reductions — no fp32 chunk is written back."""
    logits = jnp.einsum(
        "ch,vh->cv", x_c, w, preferred_element_type=x_c.dtype
    )
    return logits.astype(jnp.float32)


def _target_block(col, lbl, smoothing, vocab):
    """The smoothed one-hot target row block (`_loss_block`'s gradient
    counterpart): (1-eps) at the label column + eps/vocab everywhere."""
    return (
        jnp.where(col == lbl, 1.0 - smoothing, 0.0) + smoothing / vocab
    )


# ---------------------------------------------------------------------------
# serial, per-row losses (general cotangents; backward recomputes the
# chunk softmax from the saved lse)
# ---------------------------------------------------------------------------


def _fwd_impl(hidden2d, weight, labels, smoothing, chunk_size):
    rows, _ = hidden2d.shape
    w = weight.astype(hidden2d.dtype)
    chunk = _chunk_rows(rows, w.shape[0], chunk_size)
    xs, ls = _to_chunks(chunk, hidden2d, labels.reshape(-1, 1))

    def body(_, xl):
        x_c, l_c = xl
        loss, lse, _, _, _ = _loss_block(smoothing, _chunk_logits(x_c, w), l_c)
        return None, (loss[:, 0], lse[:, 0])

    _, (loss, lse) = _scan_chunks(body, None, (xs, ls))
    return loss.reshape(-1)[:rows], lse.reshape(-1)[:rows]


def _bwd_impl(hidden2d, weight, labels, lse, dloss, smoothing, chunk_size):
    rows, hdim = hidden2d.shape
    vocab = weight.shape[0]
    cdt = hidden2d.dtype
    w = weight.astype(cdt)
    chunk = _chunk_rows(rows, vocab, chunk_size)
    xs, ls, lses, dls = _to_chunks(
        chunk,
        hidden2d,
        labels.reshape(-1, 1),
        lse.reshape(-1, 1),
        dloss.astype(jnp.float32).reshape(-1, 1),
    )

    def body(dw, inp):
        x_c, l_c, lse_c, dl_c = inp
        logits = _chunk_logits(x_c, w)
        # softmax from the SAVED lse: no second max/sum pass
        p = jnp.exp(logits - lse_c)
        col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        dlog = (dl_c * (p - _target_block(col, l_c, smoothing, vocab))).astype(
            cdt
        )
        dx_c = jnp.einsum("cv,vh->ch", dlog, w, preferred_element_type=cdt)
        dw = dw + jnp.einsum(
            "cv,ch->vh", dlog, x_c, preferred_element_type=jnp.float32
        )
        return dw, dx_c

    dw0 = jnp.zeros((vocab, hdim), jnp.float32)
    dw, dxs = _scan_chunks(body, dw0, (xs, ls, lses, dls))
    dx = dxs.reshape(-1, hdim)[:rows]
    return dx.astype(hidden2d.dtype), dw.astype(weight.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def linear_cross_entropy_loss(
    hidden, weight, labels, smoothing=0.0, padding_idx=None, chunk_size=None
):
    """Per-row smoothed CE of the fused head ``hidden @ weight^T``.

    Args:
      hidden: ``(..., hidden)`` activations (any leading shape).
      weight: ``(vocab, hidden)`` projection table (the tied embedding).
      labels: integer ``(...)`` target ids.
      smoothing: label-smoothing epsilon (`ops.xentropy` semantics).
      padding_idx: rows whose label equals it get zero loss and zero
        gradient (``None`` disables, every label contributes).
      chunk_size: rows per chunk (default targets ~2^27 chunk elements).

    Returns fp32 per-row losses shaped like ``labels``. Differentiable
    in ``hidden`` and ``weight`` under arbitrary per-row cotangents;
    the backward recomputes each chunk's softmax from the saved lse
    (one extra head matmul — the price of never storing logits).
    """
    losses, _ = _fwd_impl(
        hidden.reshape(-1, hidden.shape[-1]),
        weight,
        labels.reshape(-1).astype(jnp.int32),
        smoothing,
        chunk_size,
    )
    losses = losses.reshape(labels.shape)
    if padding_idx is None:
        return losses
    return jnp.where(labels == padding_idx, 0.0, losses)


def _lxe_vjp_fwd(hidden, weight, labels, smoothing, padding_idx, chunk_size):
    lbl = labels.reshape(-1).astype(jnp.int32)
    losses, lse = _fwd_impl(
        hidden.reshape(-1, hidden.shape[-1]), weight, lbl, smoothing,
        chunk_size,
    )
    losses = losses.reshape(labels.shape)
    if padding_idx is not None:
        losses = jnp.where(labels == padding_idx, 0.0, losses)
    return losses, (hidden, weight, lbl, lse)


def _lxe_vjp_bwd(smoothing, padding_idx, chunk_size, res, dloss):
    hidden, weight, lbl, lse = res
    dl = dloss.reshape(-1)
    if padding_idx is not None:
        dl = jnp.where(lbl == padding_idx, 0.0, dl)
    dx, dw = _bwd_impl(
        hidden.reshape(-1, hidden.shape[-1]), weight, lbl, lse, dl,
        smoothing, chunk_size,
    )
    return dx.reshape(hidden.shape), dw, None


linear_cross_entropy_loss.defvjp(_lxe_vjp_fwd, _lxe_vjp_bwd)


# ---------------------------------------------------------------------------
# serial, mean-reduced (the train-step fast path: scalar cotangent, so
# dx/dW finish inside the forward pass — no recompute matmul)
# ---------------------------------------------------------------------------


def _row_weights(labels, loss_mask, padding_idx):
    """fp32 per-row loss weights reproducing `gpt_loss_fn`:
    ``sum(mask*loss)/max(sum(mask),1)`` with a mask, plain mean
    without; ``padding_idx`` rows are zeroed from the numerator only
    (they still count in the unmasked denominator, exactly like the
    zeroed per-row losses the materialized path feeds to
    `gpt_loss_fn`)."""
    if loss_mask is not None:
        m = jax.lax.stop_gradient(loss_mask).reshape(-1).astype(jnp.float32)
        rw = m / jnp.maximum(jnp.sum(m), 1.0)
    else:
        rw = jnp.full(labels.shape, 1.0 / labels.size, jnp.float32)
        rw = rw.reshape(-1)
    if padding_idx is not None:
        rw = jnp.where(labels.reshape(-1) == padding_idx, 0.0, rw)
    return rw


def _mean_fwd_impl(hidden2d, weight, labels, row_w, smoothing, chunk_size,
                   with_grads):
    rows, hdim = hidden2d.shape
    vocab = weight.shape[0]
    cdt = hidden2d.dtype
    w = weight.astype(cdt)
    chunk = _chunk_rows(rows, vocab, chunk_size)
    xs, ls, rws = _to_chunks(
        chunk, hidden2d, labels.reshape(-1, 1), row_w.reshape(-1, 1)
    )

    def body(carry, inp):
        x_c, l_c, rw_c = inp
        logits = _chunk_logits(x_c, w)
        loss, _, col, p, ssum = _loss_block(smoothing, logits, l_c)
        partial = jnp.sum(rw_c * loss)
        if not with_grads:
            return carry + partial, None
        acc, dw = carry
        # dlogits while the chunk is live: p/ssum is the softmax
        # (one exp pass serves loss and gradient, the _fwd_dg_kernel
        # trick), rw_c folds the mean reduction + mask + padding into
        # the per-row scale
        dlog = (
            rw_c * (p * (1.0 / ssum) - _target_block(col, l_c, smoothing, vocab))
        ).astype(cdt)
        dx_c = jnp.einsum("cv,vh->ch", dlog, w, preferred_element_type=cdt)
        dw = dw + jnp.einsum(
            "cv,ch->vh", dlog, x_c, preferred_element_type=jnp.float32
        )
        return (acc + partial, dw), dx_c

    if not with_grads:
        total, _ = _scan_chunks(body, jnp.float32(0.0), (xs, ls, rws))
        return total
    carry0 = (jnp.float32(0.0), jnp.zeros((vocab, hdim), jnp.float32))
    (total, dw), dxs = _scan_chunks(body, carry0, (xs, ls, rws))
    dx = dxs.reshape(-1, hdim)[:rows].astype(hidden2d.dtype)
    return total, dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def linear_cross_entropy_mean(
    hidden, weight, labels, loss_mask=None,
    smoothing=0.0, padding_idx=None, chunk_size=None,
):
    """Scalar masked-mean CE of the fused head — the train-step path.

    Equals ``gpt_loss_fn(linear_cross_entropy_loss(...), loss_mask)``
    but because the reduction is inside the op the loss cotangent is a
    SCALAR: the forward pass forms each chunk's ``dlogits`` while the
    chunk is live and contracts it straight into ``dx`` and the
    accumulated ``dW`` (backward is two scalar multiplies). Total head
    cost is 3 matmuls (fwd/dx/dW) — the materialized path's FLOPs with
    none of its logits-sized HBM traffic. ``loss_mask`` is treated as
    a constant (stop_gradient).
    """
    return _mean_fwd_impl(
        hidden.reshape(-1, hidden.shape[-1]),
        weight,
        labels.reshape(-1).astype(jnp.int32),
        _row_weights(labels, loss_mask, padding_idx),
        smoothing,
        chunk_size,
        with_grads=False,
    )


def _mean_vjp_fwd(hidden, weight, labels, loss_mask, smoothing, padding_idx,
                  chunk_size):
    lbl = labels.reshape(-1).astype(jnp.int32)
    total, dx, dw = _mean_fwd_impl(
        hidden.reshape(-1, hidden.shape[-1]), weight, lbl,
        _row_weights(labels, loss_mask, padding_idx), smoothing, chunk_size,
        with_grads=True,
    )
    # zero-size marker carries the weight dtype through the residuals
    # (the fp32-accumulated dW must come back in the primal's dtype)
    proto = jnp.zeros((0,), weight.dtype)
    return total, (dx.reshape(hidden.shape), dw, proto)


def _mean_vjp_bwd(smoothing, padding_idx, chunk_size, res, g):
    dx, dw, proto = res
    g32 = g.astype(jnp.float32)
    return (
        (g32 * dx.astype(jnp.float32)).astype(dx.dtype),
        (g32 * dw).astype(proto.dtype),
        None,
        None,
    )


linear_cross_entropy_mean.defvjp(_mean_vjp_fwd, _mean_vjp_bwd)


# ---------------------------------------------------------------------------
# vocab-parallel (tp > 1): the chunked head over a LOCAL vocab shard,
# per-chunk partial max / sum-exp / target-gather psum'd over the
# tensor axis (the reduction structure of
# transformer/tensor_parallel/cross_entropy.py applied chunk-wise)
# ---------------------------------------------------------------------------


def _vp_fwd_impl(hidden2d, weight, labels, axis_name, smoothing, chunk_size):
    from rocm_apex_tpu.utils.compat import axis_size

    rows, _ = hidden2d.shape
    w = weight.astype(hidden2d.dtype)
    v_local = w.shape[0]
    tp = axis_size(axis_name)
    vocab = v_local * tp
    start = jax.lax.axis_index(axis_name) * v_local
    chunk = _chunk_rows(rows, v_local, chunk_size)
    xs, ls = _to_chunks(chunk, hidden2d, labels.reshape(-1, 1))

    def body(_, xl):
        x_c, l_c = xl
        logits = _chunk_logits(x_c, w)  # (chunk, vocab/tp) fp32
        # 1. global max for stability (reference cross_entropy.py:30-35)
        m = jax.lax.pmax(jnp.max(logits, axis=1), axis_name)[:, None]
        sh = jnp.exp(logits - m)
        # 2. global sum-exp (reference :58-63)
        sum_exp = jax.lax.psum(jnp.sum(sh, axis=1), axis_name)[:, None]
        lse = m + jnp.log(sum_exp)
        # 3. this rank's slice of the target logit, masked outside the
        # local vocab range (reference :37-56); the iota-vs-shifted-
        # label compare is range mask and gather in one
        col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        xt = jax.lax.psum(
            jnp.sum(jnp.where(col == l_c - start, logits, 0.0), axis=1),
            axis_name,
        )[:, None]
        loss = lse - (1.0 - smoothing) * xt
        if smoothing > 0.0:
            sum_x = jax.lax.psum(jnp.sum(logits, axis=1), axis_name)[:, None]
            loss = loss - (smoothing / vocab) * sum_x
        return None, (loss[:, 0], lse[:, 0])

    _, (loss, lse) = _scan_chunks(body, None, (xs, ls))
    return loss.reshape(-1)[:rows], lse.reshape(-1)[:rows]


def _vp_bwd_impl(hidden2d, weight, labels, lse, dloss, axis_name, smoothing,
                 chunk_size):
    from rocm_apex_tpu.utils.compat import axis_size

    rows, hdim = hidden2d.shape
    cdt = hidden2d.dtype
    w = weight.astype(cdt)
    v_local = w.shape[0]
    vocab = v_local * axis_size(axis_name)
    start = jax.lax.axis_index(axis_name) * v_local
    chunk = _chunk_rows(rows, v_local, chunk_size)
    xs, ls, lses, dls = _to_chunks(
        chunk,
        hidden2d,
        labels.reshape(-1, 1),
        lse.reshape(-1, 1),
        dloss.astype(jnp.float32).reshape(-1, 1),
    )

    def body(dw, inp):
        x_c, l_c, lse_c, dl_c = inp
        logits = _chunk_logits(x_c, w)
        p = jnp.exp(logits - lse_c)  # global softmax, local columns
        col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        # local slice of the smoothed one-hot: the col compare is False
        # everywhere when the target lives on another rank
        tgt = (
            jnp.where(col == l_c - start, 1.0 - smoothing, 0.0)
            + smoothing / vocab
        )
        dlog = (dl_c * (p - tgt)).astype(cdt)
        # dx contracts over the GLOBAL vocab: psum the per-rank partials
        # (hidden is replicated across the axis, so this psum IS the
        # copy_to_tensor_model_parallel_region backward)
        dx_c = jax.lax.psum(
            jnp.einsum("cv,vh->ch", dlog, w, preferred_element_type=cdt),
            axis_name,
        )
        dw = dw + jnp.einsum(
            "cv,ch->vh", dlog, x_c, preferred_element_type=jnp.float32
        )
        return dw, dx_c

    dw0 = jnp.zeros((v_local, hdim), jnp.float32)
    dw, dxs = _scan_chunks(body, dw0, (xs, ls, lses, dls))
    dx = dxs.reshape(-1, hdim)[:rows]
    return dx.astype(hidden2d.dtype), dw.astype(weight.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def vocab_parallel_linear_cross_entropy(
    hidden, weight, labels, axis_name,
    smoothing=0.0, padding_idx=None, chunk_size=None,
):
    """`linear_cross_entropy_loss` over a vocab-sharded head.

    Args:
      hidden: ``(..., hidden)`` activations, REPLICATED across the
        tensor axis (every rank passes the same values).
      weight: ``(vocab/tp, hidden)`` local shard of the projection.
      labels: integer ``(...)`` GLOBAL token ids.
      axis_name: bound tensor-parallel mesh axis (shard_map).

    Returns replicated fp32 per-row losses. The gradient of ``hidden``
    is psum'd over the axis internally (do NOT additionally wrap the
    input in ``copy_to_tensor_model_parallel_region``); the gradient
    of ``weight`` is the local shard's.
    """
    losses, _ = _vp_fwd_impl(
        hidden.reshape(-1, hidden.shape[-1]),
        weight,
        labels.reshape(-1).astype(jnp.int32),
        axis_name,
        smoothing,
        chunk_size,
    )
    losses = losses.reshape(labels.shape)
    if padding_idx is None:
        return losses
    return jnp.where(labels == padding_idx, 0.0, losses)


def _vp_vjp_fwd(hidden, weight, labels, axis_name, smoothing, padding_idx,
                chunk_size):
    lbl = labels.reshape(-1).astype(jnp.int32)
    losses, lse = _vp_fwd_impl(
        hidden.reshape(-1, hidden.shape[-1]), weight, lbl, axis_name,
        smoothing, chunk_size,
    )
    losses = losses.reshape(labels.shape)
    if padding_idx is not None:
        losses = jnp.where(labels == padding_idx, 0.0, losses)
    return losses, (hidden, weight, lbl, lse)


def _vp_vjp_bwd(axis_name, smoothing, padding_idx, chunk_size, res, dloss):
    hidden, weight, lbl, lse = res
    dl = dloss.reshape(-1)
    if padding_idx is not None:
        dl = jnp.where(lbl == padding_idx, 0.0, dl)
    dx, dw = _vp_bwd_impl(
        hidden.reshape(-1, hidden.shape[-1]), weight, lbl, lse, dl,
        axis_name, smoothing, chunk_size,
    )
    return dx.reshape(hidden.shape), dw, None


vocab_parallel_linear_cross_entropy.defvjp(_vp_vjp_fwd, _vp_vjp_bwd)
