"""Pallas flash attention (fwd + bwd), the framework's fused-attention core.

TPU-native replacement for the reference's pre-flash fused attention
kernels — FMHA (reference: apex/contrib/csrc/fmha/, packed varlen
seqs <= 512) and fast_multihead_attn (reference:
apex/contrib/csrc/multihead_attn/, fused QKV+softmax+dropout+outproj,
seqlen-bounded smem tiles) — and for the megatron scaled-masked softmax
path (reference: csrc/megatron/, seqlen <= 2048 ceiling). Flash
attention is the idiomatic TPU design (SURVEY.md §5 long-context): the
(s, s) score matrix never materializes, so there is no sequence-length
ceiling and HBM traffic is O(s·d) instead of O(s²).

Algorithm: FlashAttention-2 online softmax. Forward walks kv blocks
innermost, carrying (m, l, acc) in VMEM scratch across the sequential
TPU grid; backward recomputes probabilities blockwise from the saved
row log-sum-exp — one kernel for dk/dv (kv blocks outer), one for dq
(q blocks outer).

Layout: (batch*heads, seq, head_dim), head_dim <= 256. ``bias`` is an
optional additive (batch*heads | 1, sq, sk) tensor (-inf = masked) —
the general form of the reference's padding/additive masks; ``causal``
applies the upper-triangular mask in-kernel (no bias tensor needed).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from rocm_apex_tpu.ops._pallas import pallas_call

__all__ = [
    "flash_attention",
    "flash_attention_varlen",
    "flash_attention_decode",
    "flash_attention_decode_paged",
    "flash_attention_with_lse",
    "flash_attention_dropout",
    "flash_attention_qkv",
    "flash_attention_qkv_dropout",
    "flash_attention_qkv_bias",
    "flash_attention_qkv_bias_dropout",
]

# Large blocks keep the sequential TPU grid short (per-step overhead is
# the dominant cost at small blocks) while staying well inside VMEM:
# q/k/v (1024, d) + the (1024, 1024) fp32 score tile ~ 5.5 MiB at
# d=128. Swept on v5e (s=1024, d=128, fwd+bwd): (1024, 1024) beats
# (512, 1024) by 16% and (512, 512) by 30%.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30
# Scores are kept in BASE 2 inside every kernel: scale·log2(e) folds
# into the (block, head_dim) q tile before the MXU dot — one multiply
# over d columns instead of block_k — and the softmax runs on exp2
# (the VPU's native exponential; exp(x) would spend an extra full-tile
# multiply folding log2e back in). lse converts to natural log at the
# kernel boundary, so the public API (and the ring-attention lse
# combine) is unchanged.
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453
# Kernel dots PIN native MXU precision rather than inheriting
# jax_default_matmul_precision: Mosaic rejects non-native precisions on
# bf16 operands outright ("Bad lhs type" under 'highest'), so a global
# precision override would crash every bf16 training path. Like any
# hand-written kernel (cuDNN flash attention under torch's matmul
# flags), these kernels define their own numerics: bf16 operands on the
# MXU with fp32 accumulation.
_PREC = jax.lax.Precision.DEFAULT


def _round_up(x, m):
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _keep_mask(seed_ref, rate, b, qi, ki, shape):
    """Deterministic per-(batch, q-block, k-block) keep mask; the same
    seeding in forward and both backward kernels regenerates identical
    bits (the flash-dropout recompute trick — no mask is stored)."""
    # single combined scalar (multi-arg prng_seed does not lower on all
    # backends). The coordinates are folded through murmur3-style
    # multiply-rotate-xor rounds rather than an affine combination:
    # affine seeds collide across (b, qi, ki) triples at large grids
    # (e.g. qi ~ b-stride aliasing), which would correlate dropout
    # masks between blocks exactly in the long-context regime.
    def _mix(h, k):
        k = k * jnp.uint32(0xCC9E2D51)
        k = (k << 15) | (k >> 17)
        k = k * jnp.uint32(0x1B873593)
        h = h ^ k
        h = (h << 13) | (h >> 19)
        return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)

    h = seed_ref[0].astype(jnp.uint32)
    for coord in (b, qi, ki):
        h = _mix(h, coord.astype(jnp.uint32))
    # fmix32 avalanche so low-bit coordinate differences reach all bits
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    # mask to 31 bits first: u32->s32 conversion is only
    # defined-behavior in XLA's ConvertElementType for in-range values,
    # and a scalar bitcast is rejected by current Mosaic ('tpu.bitcast'
    # on non-vector operands); one seed bit of entropy is immaterial
    pltpu.prng_seed((h & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32))
    bits = pltpu.prng_random_bits(shape)
    thresh = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return bits.astype(jnp.uint32) >= thresh


def _masked_scores(
    causal, scale, sk_real, block_q, block_k,
    q, k, bias_ref, len_ref, b, qi, ki, seg=None,
):
    """The masked BASE-2 score block for grid point (b, qi, ki) —
    shared by ALL FOUR kernels (fwd, dkv, dq, dbias). Masking semantics
    live here and only here: a change applied to one kernel but not the
    others would silently desynchronize forward and backward
    probabilities.

    Returns log2-domain scores: `exp2(s - m)` reproduces the natural-
    domain softmax exactly (scale·log2e is folded into the q tile —
    the narrow operand — before the dot)."""
    # native-dtype MXU operands (bf16 in / fp32 accumulate); an
    # explicit fp32 upcast here would fall off the fast MXU path.
    # q·(scale·log2e) rounds in q's dtype — the same 2^-8-tier relative
    # rounding the bf16 operands already carry into the MXU
    s = jax.lax.dot_general(
        (q * jnp.asarray(scale * LOG2E, q.dtype)), k,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_PREC,
    )
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32) * LOG2E
    col = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    if sk_real % block_k != 0:
        s = jnp.where(col < sk_real, s, NEG_INF)
    if len_ref is not None:
        # per-row real key length (varlen): in-kernel bound, the
        # flash-grade replacement for a materialized (s, s) mask
        s = jnp.where(col < len_ref[b], s, NEG_INF)
    if seg is not None:
        # packed-stream segment masking: token i attends token j only
        # within the same segment (flash_attention_segments)
        sq_ids, sk_ids = seg
        s = jnp.where(
            sq_ids[...] == sk_ids[...].reshape(1, -1), s, NEG_INF
        )
    if causal:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        s = jnp.where(row >= col, s, NEG_INF)
    return s


def _fwd_kernel(
    causal, scale, sk_real, block_q, block_k, has_bias, dropout_rate,
    has_lengths, q_ref, k_ref, v_ref, *refs, has_qkv_bias=False,
):
    refs = list(refs)
    qb_ref = refs.pop(0) if has_qkv_bias else None
    kb_ref = refs.pop(0) if has_qkv_bias else None
    vb_ref = refs.pop(0) if has_qkv_bias else None
    bias_ref = refs.pop(0) if has_bias else None
    seed_ref = refs.pop(0) if dropout_rate > 0.0 else None
    len_ref = refs.pop(0) if has_lengths else None
    o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        if has_qkv_bias:
            # fused projection bias (same bf16 add the matmul epilogue
            # would have performed); (1, hd) row broadcasts over block
            q = q + qb_ref[0]
            k = k + kb_ref[0]
            v = v + vb_ref[0]
        s = _masked_scores(
            causal, scale, sk_real, block_q, block_k,
            q, k, bias_ref, len_ref, b, qi, ki,
        )

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        # the softmax normalizer uses the UNdropped probabilities;
        # dropout zeroes entries of the normalized matrix (torch order:
        # softmax -> dropout -> @v)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(
                seed_ref, dropout_rate, b, qi, ki, (block_q, block_k)
            )
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot(
            p.astype(v.dtype), v,
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        # natural-log lse at the boundary (base-2 internally)
        lse_ref[0] = (m_scr[:, :1] + jnp.log2(safe_l)) * LN2


def _fwd(q, k, v, bias, causal, scale, block_q, block_k,
         dropout_rate=0.0, dropout_seed=None, kv_lengths=None):
    bh, sq, d0 = q.shape
    sk = k.shape[1]
    # lane-align head_dim (zero feature columns are inert in q@k^T and
    # produce zero output columns, sliced away below)
    d = _round_up(d0, 128)
    block_q = min(block_q, _round_up(sq, 128))
    block_k = min(block_k, _round_up(sk, 128))
    sq_p, sk_p = _round_up(sq, block_q), _round_up(sk, block_k)
    qp = jnp.pad(q, ((0, 0), (0, sq_p - sq), (0, d - d0)))
    kp = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, d - d0)))
    vp = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, d - d0)))
    grid = (bh, sq_p // block_q, sk_p // block_k)

    ins = [qp, kp, vp]
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    has_bias = bias is not None
    if has_bias:
        # bias leading dim: 1 (shared), batch (shared across heads), or
        # batch*heads — all handled by integer-dividing the bh index
        nb = bias.shape[0]
        if bh % nb != 0:
            raise ValueError(f"bias batch {nb} must divide batch*heads {bh}")
        hp = bh // nb
        bp = jnp.pad(
            bias.astype(jnp.float32),
            ((0, 0), (0, sq_p - sq), (0, sk_p - sk)),
        )
        ins.append(bp)
        in_specs.append(
            pl.BlockSpec((1, block_q, block_k), lambda b, i, j: (b // hp, i, j))
        )
    if dropout_rate > 0.0:
        ins.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    has_lengths = kv_lengths is not None
    if has_lengths:
        ins.append(jnp.asarray(kv_lengths, jnp.int32))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    o, lse = pallas_call(
        functools.partial(
            _fwd_kernel, causal, scale, sk, block_q, block_k, has_bias,
            dropout_rate, has_lengths,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )(*ins)
    return o[:, :sq, :d0], lse[:, :sq, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(
    causal, scale, sk_real, block_q, block_k, has_bias, dropout_rate,
    has_lengths, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
):
    refs = list(refs)
    bias_ref = refs.pop(0) if has_bias else None
    seed_ref = refs.pop(0) if dropout_rate > 0.0 else None
    len_ref = refs.pop(0) if has_lengths else None
    (dk_ref, dv_ref, dk_scr, dv_scr) = refs
    b = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = _masked_scores(
            causal, scale, sk_real, block_q, block_k,
            q, k, bias_ref, len_ref, b, qi, ki,
        )
        p = jnp.exp2(s - lse * LOG2E)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        if dropout_rate > 0.0:
            # identical regeneration of the forward's keep mask
            keep = _keep_mask(
                seed_ref, dropout_rate, b, qi, ki, (block_q, block_k)
            )
            inv = 1.0 / (1.0 - dropout_rate)
            p_drop = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_drop = p
        dv_scr[...] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        # unscaled ds: the outer q·k scale is applied once to the
        # accumulated (block, d) result at finish, not per score tile
        ds = p * (dp - delta)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )

    if causal:
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    causal, scale, sk_real, block_q, block_k, has_bias, dropout_rate,
    has_lengths, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
):
    refs = list(refs)
    bias_ref = refs.pop(0) if has_bias else None
    seed_ref = refs.pop(0) if dropout_rate > 0.0 else None
    len_ref = refs.pop(0) if has_lengths else None
    (dq_ref, dq_scr) = refs
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = _masked_scores(
            causal, scale, sk_real, block_q, block_k,
            q, k, bias_ref, len_ref, b, qi, ki,
        )
        p = jnp.exp2(s - lse * LOG2E)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        if dropout_rate > 0.0:
            keep = _keep_mask(
                seed_ref, dropout_rate, b, qi, ki, (block_q, block_k)
            )
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        # unscaled ds; the scale lands on the accumulated dq at finish
        ds = p * (dp - delta)
        dq_scr[...] += jax.lax.dot(
            ds.astype(k.dtype), k,
            preferred_element_type=jnp.float32, precision=_PREC,
        )

    if causal:
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dbias_kernel(
    causal, scale, sk_real, block_q, block_k, hp, dropout_rate,
    has_lengths, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    bias_ref, *refs,
):
    """dbias[n] = sum over the hp heads sharing bias row n of ds.

    Grid (nb, q, kv, h) with the head-group dim INNERMOST: the output
    bias block (n, i, j) is revisited on consecutive grid steps, so the
    VMEM scratch accumulates across heads and writes back once — no
    O(bh·s²) intermediate ever reaches HBM (only the O(nb·s²) gradient
    the caller asked for).
    """
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_rate > 0.0 else None
    len_ref = refs.pop(0) if has_lengths else None
    dbias_ref, db_scr = refs
    n = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    h = pl.program_id(3)
    b = n * hp + h

    @pl.when(h == 0)
    def _init():
        db_scr[...] = jnp.zeros_like(db_scr)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = _masked_scores(
            causal, scale, sk_real, block_q, block_k,
            q, k, bias_ref, len_ref, b, qi, ki,
        )
        p = jnp.exp2(s - lse * LOG2E)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        if dropout_rate > 0.0:
            keep = _keep_mask(
                seed_ref, dropout_rate, b, qi, ki, (block_q, block_k)
            )
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        # d loss / d bias_block == d loss / d s == ds without the
        # outer scale (bias adds to s AFTER the q·k scaling)
        db_scr[...] += p * (dp - delta)

    if causal:
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(h == hp - 1)
    def _finish():
        dbias_ref[0] = db_scr[...].astype(dbias_ref.dtype)


def _bwd(causal, scale, block_q, block_k, res, do, dlse=None,
         dropout_rate=0.0, dropout_seed=None, kv_lengths=None,
         compute_dbias=True):
    q, k, v, bias, o, lse = res
    bh, sq, d0 = q.shape
    sk = k.shape[1]
    d = _round_up(d0, 128)
    block_q = min(block_q, _round_up(sq, 128))
    block_k = min(block_k, _round_up(sk, 128))
    sq_p, sk_p = _round_up(sq, block_q), _round_up(sk, block_k)

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (bh, sq)
    if dlse is not None:
        # lse cotangent: d lse / d s = p, so ds = p*(dp - delta + dlse)
        # — dlse folds into delta with opposite sign
        delta = delta - dlse.astype(jnp.float32)
    qp = jnp.pad(q, ((0, 0), (0, sq_p - sq), (0, d - d0)))
    kp = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, d - d0)))
    vp = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, d - d0)))
    dop = jnp.pad(do, ((0, 0), (0, sq_p - sq), (0, d - d0)))
    # padded q rows: lse = +inf would give p = exp(-inf)=0; NEG_INF keeps
    # exp(s - lse) = exp(finite - (-inf)) … use a large finite so p ~ 0
    lsep = jnp.pad(
        lse[..., None], ((0, 0), (0, sq_p - sq), (0, 0)),
        constant_values=-NEG_INF,
    )
    deltap = jnp.pad(delta[..., None], ((0, 0), (0, sq_p - sq), (0, 0)))

    common_ins = [qp, kp, vp, dop, lsep, deltap]
    has_bias = bias is not None
    if has_bias:
        nb = bias.shape[0]
        if bh % nb != 0:
            raise ValueError(f"bias batch {nb} must divide batch*heads {bh}")
        hp = bh // nb
        bp = jnp.pad(
            bias.astype(jnp.float32),
            ((0, 0), (0, sq_p - sq), (0, sk_p - sk)),
        )

    # dk/dv: grid (bh, kv, q) — q innermost
    def _kv_specs():
        specs = [
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ]
        if has_bias:
            specs.append(
                pl.BlockSpec(
                    (1, block_q, block_k), lambda b, j, i: (b // hp, i, j)
                )
            )
        if dropout_rate > 0.0:
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        if has_lengths:
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        return specs

    has_lengths = kv_lengths is not None
    ins = common_ins + ([bp] if has_bias else [])
    if dropout_rate > 0.0:
        ins.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
    if has_lengths:
        ins.append(jnp.asarray(kv_lengths, jnp.int32))
    dk, dv = pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal, scale, sk, block_q, block_k, has_bias,
            dropout_rate, has_lengths,
        ),
        grid=(bh, sk_p // block_k, sq_p // block_q),
        in_specs=_kv_specs(),
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_p, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )(*ins)

    # dq: grid (bh, q, kv) — kv innermost
    def _q_specs():
        specs = [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ]
        if has_bias:
            specs.append(
                pl.BlockSpec(
                    (1, block_q, block_k), lambda b, i, j: (b // hp, i, j)
                )
            )
        if dropout_rate > 0.0:
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        if has_lengths:
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        return specs

    dq = pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal, scale, sk, block_q, block_k, has_bias,
            dropout_rate, has_lengths,
        ),
        grid=(bh, sq_p // block_q, sk_p // block_k),
        in_specs=_q_specs(),
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )(*ins)

    dbias = None
    if has_bias and not compute_dbias:
        # constant-mask caller (compute_dbias=False): no kernel launch,
        # no O(nb·s²) gradient buffer — explicit, not DCE-dependent
        dbias = jnp.zeros_like(bias)
    elif has_bias:
        # dbias: grid (nb, q, kv, heads-per-bias-row), head dim
        # innermost so the output block accumulates in VMEM. XLA DCEs
        # this whole call when the caller does not differentiate bias.
        def _db_specs():
            specs = [
                pl.BlockSpec(
                    (1, block_q, d), lambda n, i, j, h: (n * hp + h, i, 0)
                ),
                pl.BlockSpec(
                    (1, block_k, d), lambda n, i, j, h: (n * hp + h, j, 0)
                ),
                pl.BlockSpec(
                    (1, block_k, d), lambda n, i, j, h: (n * hp + h, j, 0)
                ),
                pl.BlockSpec(
                    (1, block_q, d), lambda n, i, j, h: (n * hp + h, i, 0)
                ),
                pl.BlockSpec(
                    (1, block_q, 1), lambda n, i, j, h: (n * hp + h, i, 0)
                ),
                pl.BlockSpec(
                    (1, block_q, 1), lambda n, i, j, h: (n * hp + h, i, 0)
                ),
                pl.BlockSpec(
                    (1, block_q, block_k), lambda n, i, j, h: (n, i, j)
                ),
            ]
            if dropout_rate > 0.0:
                specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            if has_lengths:
                specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            return specs

        dbias_p = pallas_call(
            functools.partial(
                _bwd_dbias_kernel, causal, scale, sk, block_q, block_k,
                hp, dropout_rate, has_lengths,
            ),
            grid=(nb, sq_p // block_q, sk_p // block_k, hp),
            in_specs=_db_specs(),
            out_specs=pl.BlockSpec(
                (1, block_q, block_k), lambda n, i, j, h: (n, i, j)
            ),
            out_shape=jax.ShapeDtypeStruct((nb, sq_p, sk_p), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((block_q, block_k), jnp.float32)
            ],
        )(*ins)
        dbias = dbias_p[:, :sq, :sk].astype(bias.dtype)
    return (
        dq[:, :sq, :d0],
        dk[:, :sk, :d0],
        dv[:, :sk, :d0],
        dbias,
    )


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    compute_dbias: bool = False,
) -> jnp.ndarray:
    """Flash attention over (batch*heads, seq, head_dim) operands.

    ``bias`` additive (bh | 1, sq, sk); ``causal`` in-kernel triangular
    mask; ``scale`` defaults to 1/sqrt(head_dim). Differentiable in
    q/k/v, and in bias when ``compute_dbias=True``: learned additive
    biases (ALiBi slopes, relative position) train correctly — dbias is
    computed by a dedicated kernel summing ds over each bias row's head
    group.

    PERFORMANCE NOTE: ``compute_dbias`` defaults to False because the
    common bias is a constant mask (padding/causal combinations) whose
    gradient nobody reads — and the dbias kernel materializes an
    O(bh·sq·sk) fp32 buffer that an EAGER (non-jit) differentiated call
    pays for even when the cotangent is discarded. Under the default
    the bias cotangent is exact zeros with no kernel launch and no
    quadratic buffer. Training a LEARNED bias requires the explicit
    ``compute_dbias=True`` opt-in; forgetting it is loud (the bias
    never moves), not silently slow.
    """
    o, _ = _fwd(
        q, k, v, bias, causal,
        scale if scale is not None else 1.0 / np.sqrt(q.shape[-1]),
        block_q, block_k,
    )
    return o


def _fa_fwd(q, k, v, bias, causal, scale, block_q, block_k, compute_dbias):
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    o, lse = _fwd(q, k, v, bias, causal, s, block_q, block_k)
    return o, (q, k, v, bias, o, lse)


def _fa_bwd(causal, scale, block_q, block_k, compute_dbias, res, do):
    s = scale if scale is not None else 1.0 / np.sqrt(res[0].shape[-1])
    return _bwd(
        causal, s, block_q, block_k, res, do, compute_dbias=compute_dbias
    )


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention_varlen(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jnp.ndarray:
    """`flash_attention` with a per-row real key length.

    ``kv_lengths`` is (batch*heads,) int32: row b attends keys
    ``[0, kv_lengths[b])``. The bound is enforced in-kernel via an iota
    compare against an SMEM scalar — the flash-grade form of a padding
    mask, with no (sq, sk) bias tensor in HBM (reference capability:
    apex/contrib/fmha packed-varlen kernels, cu_seqlens semantics).
    Rows whose length is 0 produce unspecified output (callers drop
    padded rows). Differentiable in q/k/v.
    """
    o, _ = _fwd(
        q, k, v, None, causal,
        scale if scale is not None else 1.0 / np.sqrt(q.shape[-1]),
        block_q, block_k, kv_lengths=kv_lengths,
    )
    return o


def _fav_fwd(q, k, v, kv_lengths, causal, scale, block_q, block_k):
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    o, lse = _fwd(
        q, k, v, None, causal, s, block_q, block_k, kv_lengths=kv_lengths
    )
    return o, (q, k, v, o, lse, kv_lengths)


def _fav_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, o, lse, kv_lengths = res
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    dq, dk, dv, _ = _bwd(
        causal, s, block_q, block_k, (q, k, v, None, o, lse), do,
        kv_lengths=kv_lengths,
    )
    len_ct = np.zeros(kv_lengths.shape, jax.dtypes.float0)
    return (dq, dk, dv, len_ct)


flash_attention_varlen.defvjp(_fav_fwd, _fav_bwd)


# ---------------------------------------------------------------------------
# KV-cache decode: forward-only single-token attention
# ---------------------------------------------------------------------------


# Decode queries are one real token padded to ONE input tile of rows
# (16 covers the bf16 sublane minimum; fp32's 8 divides it) — 8x less
# MXU work per k block than riding the general forward's 128-row
# minimum q block.
DECODE_BLOCK_T = 16


def _decode_kernel(
    scale, sk_real, block_t, block_k, has_lse,
    q_ref, k_ref, v_ref, len_ref, o_ref, *rest,
):
    """Online-softmax decode step for grid point (b, ki). Mirrors
    `_fwd_kernel`'s accumulation exactly (same `_masked_scores`, same
    base-2 domain) minus everything decode never needs: causal
    masking, bias, dropout, and the backward. ``has_lse`` adds the
    natural-log lse output the chunked-prefill merge consumes
    (models/gpt.py combines the prefix piece with the intra-chunk
    piece by log-sum-exp weights)."""
    if has_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref = None
        m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = _masked_scores(
            False, scale, sk_real, block_t, block_k,
            q, k, None, len_ref, b, jnp.int32(0), ki,
        )
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot(
            p.astype(v.dtype), v,
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    # key blocks wholly past this row's live prefix are skipped — the
    # preallocated cache tail costs no MXU work for short sequences
    # (the block DMA still lands; skipping it too needs manual HBM
    # copies, left for a paged-cache PR)
    pl.when(ki * block_k < len_ref[b])(_body)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        if has_lse:
            # rows with an empty live prefix carry lse = -inf-tier so a
            # downstream log-sum-exp merge weighs them to exactly zero
            lse_ref[0] = jnp.where(
                l > 0.0,
                (m_scr[:, :1] + jnp.log2(safe_l)) * LN2,
                NEG_INF,
            )


def flash_attention_decode(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    block_k: int = DEFAULT_BLOCK_K,
    return_lse: bool = False,
):
    """Decode/chunk attention against a preallocated KV cache.

    ``q`` is (batch*heads, t, head_dim) — t == 1 is the single-token
    decode step; t > 1 is the chunked-prefill read, where every query
    row of a batch row shares that row's bound (the slot's prefix).
    ``k``/``v`` are (batch*heads, capacity, head_dim) cache buffers
    whose live prefix per row is ``kv_lengths`` (int32 — row b attends
    keys ``[0, kv_lengths[b])``; rows with length 0 emit zeros, and
    lse = -inf-tier so a log-sum-exp merge drops them). Forward only —
    inference never differentiates — so no vjp is defined.
    ``return_lse`` returns ``(o, lse)`` with lse (batch*heads, t) in
    natural log, the merge operand for combining this prefix piece
    with an intra-chunk piece (`flash_attention_segments_with_lse`).
    The q block is one tile of ``round_up(t, 16)`` rows instead of the
    general kernel's 128, and key blocks past a row's live prefix skip
    their MXU work entirely.
    """
    bh, t, d0 = q.shape
    sk = k.shape[1]
    s = scale if scale is not None else 1.0 / np.sqrt(d0)
    d = _round_up(d0, 128)
    block_t = _round_up(t, DECODE_BLOCK_T)
    block_k = min(block_k, _round_up(sk, 128))
    sk_p = _round_up(sk, block_k)
    qp = jnp.pad(q, ((0, 0), (0, block_t - t), (0, d - d0)))
    kp = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, d - d0)))
    vp = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, d - d0)))

    o, lse = pallas_call(
        functools.partial(_decode_kernel, s, sk, block_t, block_k, True),
        grid=(bh, sk_p // block_k),
        in_specs=[
            pl.BlockSpec((1, block_t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_t, 1), lambda b, j: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, block_t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, block_t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t, 128), jnp.float32),
            pltpu.VMEM((block_t, 128), jnp.float32),
            pltpu.VMEM((block_t, d), jnp.float32),
        ],
    )(qp, kp, vp, jnp.asarray(kv_lengths, jnp.int32))
    if return_lse:
        return o[:, :t, :d0], lse[:, :t, 0]
    return o[:, :t, :d0]


# ---------------------------------------------------------------------------
# paged KV-cache decode: page-table-gather read path
# ---------------------------------------------------------------------------


def _decode_paged_kernel(
    scale, nh, ps, num_pages, block_t, quantized,
    tab_ref, len_ref, q_ref, k_ref, v_ref, *rest,
):
    """Online-softmax decode against a PAGED cache for grid point
    (b, j): batch row b = slot·nh + head, j walks the slot's page
    list. The kv tile for (b, j) was fetched by the scalar-prefetch
    index maps through the page table, so the kernel sees exactly the
    pages the slot owns — the fixed-capacity dead tail the contiguous
    `_decode_kernel` still DMAs (its skip is compute-only) never
    leaves HBM here: past-the-prefix grid steps re-point their fetch
    at the last live page, and Pallas elides the DMA for a repeated
    block index. Same accumulation as `_decode_kernel` (base-2 online
    softmax, natural-log lse at the boundary).

    ``quantized`` adds per-(page, head) fp32 dequantization: int8
    tiles are scaled into the score/value dots from SMEM-resident
    scale tables (one scalar read per tile)."""
    if quantized:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    slot = b // nh
    head = b % nh

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _body():
        ln = len_ref[slot]
        q = q_ref[0]
        k = k_ref[0, 0]  # (ps, d)
        v = v_ref[0, 0]
        if quantized:
            # the page this grid step actually fetched (the index-map
            # clamp replayed in-body so tile and scale can't disagree)
            live = jnp.maximum((ln + ps - 1) // ps, 1)
            jeff = jnp.minimum(j, live - 1)
            page = jnp.minimum(tab_ref[slot, jeff], num_pages - 1)
            k = (k.astype(jnp.float32) * ks_ref[page, head]).astype(
                q.dtype
            )
            v = (v.astype(jnp.float32) * vs_ref[page, head]).astype(
                q.dtype
            )
        s = jax.lax.dot_general(
            (q * jnp.asarray(scale * LOG2E, q.dtype)), k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        col = j * ps + jax.lax.broadcasted_iota(
            jnp.int32, (block_t, ps), 1
        )
        s = jnp.where(col < ln, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot(
            p.astype(v.dtype), v,
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    # pages wholly past the live prefix: no compute AND no fetch (the
    # index map re-pointed their DMA at an already-resident page)
    pl.when(j * ps < len_ref[slot])(_body)

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(
            l > 0.0,
            (m_scr[:, :1] + jnp.log2(safe_l)) * LN2,
            NEG_INF,
        )


def flash_attention_decode_paged(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    return_lse: bool = False,
):
    """`flash_attention_decode` reading through a block table.

    ``q`` is (num_slots·heads, t, head_dim), slot-major (row
    ``s·heads + n`` holds slot s, head n — the layout the model's
    head-flatten produces). ``k_pool``/``v_pool`` are the shared page
    pools, (num_pages, heads, page_size, head_dim); ``page_table`` is
    (num_slots, pages_per_slot) int32 mapping each slot's page list
    into the pool (unmapped entries carry the ``num_pages`` sentinel
    and are never fetched within a live prefix); ``kv_lengths`` is
    (num_slots,) int32 — slot s attends cache positions
    ``[0, kv_lengths[s])``. The grid walks (slot·head, page): each kv
    tile is ONE page, fetched via a scalar-prefetch index map that
    resolves the table on the fly, so HBM reads are bounded by pages
    actually live — the paged answer to the contiguous kernel's
    fixed-capacity tail DMA.

    ``k_scale``/``v_scale`` ((num_pages, heads) fp32) switch the pools
    to int8 with per-(page, head) dequantization inside the kernel's
    inner loop (the cache-bytes half of the EQuARX trade). Forward
    only, like every decode read. ``return_lse`` as in
    `flash_attention_decode` (rows with an empty prefix carry
    -inf-tier lse so a log-sum-exp merge drops them).
    """
    bh, t, d0 = q.shape
    num_pages, nh, ps, dp = k_pool.shape
    num_slots, pages_per_slot = page_table.shape
    if dp != d0:
        raise ValueError(
            f"pool head_dim {dp} != query head_dim {d0}"
        )
    if bh != num_slots * nh:
        raise ValueError(
            f"q rows {bh} must equal num_slots {num_slots} * pool "
            f"heads {nh} (slot-major)"
        )
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    s = scale if scale is not None else 1.0 / np.sqrt(d0)
    d = _round_up(d0, 128)
    block_t = _round_up(t, DECODE_BLOCK_T)
    qp = jnp.pad(q, ((0, 0), (0, block_t - t), (0, d - d0)))
    kp = jnp.pad(k_pool, ((0, 0), (0, 0), (0, 0), (0, d - d0)))
    vp = jnp.pad(v_pool, ((0, 0), (0, 0), (0, 0), (0, d - d0)))

    def _page_map(b, j, tab, lens):
        # clamp dead/unmapped steps onto the last LIVE page: a repeated
        # block index is not refetched, so the dead tail costs no DMA
        slot = b // nh
        live = jnp.maximum((lens[slot] + ps - 1) // ps, 1)
        jeff = jnp.minimum(j, live - 1)
        return (jnp.minimum(tab[slot, jeff], num_pages - 1), b % nh, 0, 0)

    in_specs = [
        pl.BlockSpec((1, block_t, d), lambda b, j, tab, lens: (b, 0, 0)),
        pl.BlockSpec((1, 1, ps, d), _page_map),
        pl.BlockSpec((1, 1, ps, d), _page_map),
    ]
    ins = [qp, kp, vp]
    if quantized:
        smem = pl.BlockSpec(memory_space=pltpu.SMEM)
        in_specs += [smem, smem]
        ins += [
            jnp.asarray(k_scale, jnp.float32),
            jnp.asarray(v_scale, jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, pages_per_slot),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (1, block_t, d), lambda b, j, tab, lens: (b, 0, 0)
            ),
            pl.BlockSpec(
                (1, block_t, 1), lambda b, j, tab, lens: (b, 0, 0)
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t, 128), jnp.float32),
            pltpu.VMEM((block_t, 128), jnp.float32),
            pltpu.VMEM((block_t, d), jnp.float32),
        ],
    )
    o, lse = pallas_call(
        functools.partial(
            _decode_paged_kernel, s, nh, ps, num_pages, block_t,
            quantized,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, block_t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, block_t, 1), jnp.float32),
        ],
    )(
        jnp.asarray(page_table, jnp.int32),
        jnp.asarray(kv_lengths, jnp.int32),
        *ins,
    )
    if return_lse:
        return o[:, :t, :d0], lse[:, :t, 0]
    return o[:, :t, :d0]


# ---------------------------------------------------------------------------
# packed-QKV path: zero-relayout attention
# ---------------------------------------------------------------------------
#
# The (batch*heads, seq, head_dim) layout forces callers to transpose
# the fused QKV projection output (B, S, nh, 3·hd) into head-major
# form and back — on the 134M GPT bench those relayouts (split + 2
# transposes + context transpose, plus the non-contiguous residual
# adds they induce) cost ~8 ms/step. The packed path instead reads
# q/k/v tiles STRAIGHT OUT of the projection output via BlockSpec
# index maps — grid row b decomposes as (batch b//nh, head b%nh), and
# the head picks the (1, block, 1, hd) block column — and writes the
# context back in (B, S, nh, hd) layout, bitcast-compatible with the
# (B, S, H) input of the output projection. No transpose, no split,
# no concat appears anywhere in the forward graph.


def _fwd_single_kernel(
    causal, scale, sk_real, block_q, block_k, dropout_rate,
    q_ref, k_ref, v_ref, *refs, has_qkv_bias=False,
):
    """Single-block forward: the online-softmax carry (m/l scratch,
    correction multiplies, init/finish phases) degenerates when one
    (block_q, block_k) tile covers the whole sequence — this kernel
    just computes the row softmax directly. Same masking via
    `_masked_scores`, same dropout stream as the general kernel."""
    refs = list(refs)
    qb_ref = refs.pop(0) if has_qkv_bias else None
    kb_ref = refs.pop(0) if has_qkv_bias else None
    vb_ref = refs.pop(0) if has_qkv_bias else None
    seed_ref = refs.pop(0) if dropout_rate > 0.0 else None
    o_ref, lse_ref = refs
    b = pl.program_id(0)
    zero = jnp.int32(0)
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    if has_qkv_bias:
        q = q + qb_ref[0]
        k = k + kb_ref[0]
        v = v + vb_ref[0]
    s = _masked_scores(
        causal, scale, sk_real, block_q, block_k,
        q, k, None, None, b, zero, zero,
    )
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp2(s - m)
    l = jnp.sum(p, axis=1, keepdims=True)
    if dropout_rate > 0.0:
        keep = _keep_mask(
            seed_ref, dropout_rate, b, zero, zero, (block_q, block_k)
        )
        p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
    safe_l = jnp.where(l > 0.0, l, 1.0)
    acc = jax.lax.dot(
        p.astype(v.dtype), v,
        preferred_element_type=jnp.float32, precision=_PREC,
    )
    o_ref[0] = (acc / safe_l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log2(safe_l)) * LN2


def _fwd_packed(qkv, causal, scale, block_q, block_k,
                dropout_rate=0.0, dropout_seed=None, qkv_bias=None):
    B, S, nh, three_hd = qkv.shape
    hd = three_hd // 3
    if three_hd != 3 * hd or hd % 128 != 0:
        raise ValueError(
            f"packed path needs qkv (B, S, nh, 3*hd) with hd % 128 == 0, "
            f"got {qkv.shape}"
        )
    block_q = min(block_q, _round_up(S, 128))
    block_k = min(block_k, _round_up(S, 128))
    # each grid dim rounds against ITS OWN block size (a shared
    # round_up(max(bq,bk)) would silently drop tail blocks when the
    # other block size does not divide it); the single padded buffer
    # covers the larger of the two
    sq_p = _round_up(S, block_q)
    sk_p = _round_up(S, block_k)
    pad = max(sq_p, sk_p)
    # Pallas TPU tiles the LAST TWO dims, so the head lives in the flat
    # last axis: hd-sized block column (head*3 + {0,1,2}) of the
    # (B, S, nh*3*hd) view (free reshape of the projection output)
    qkv3 = qkv.reshape(B, S, nh * three_hd)
    qkv_p = jnp.pad(qkv3, ((0, 0), (0, pad - S), (0, 0)))
    grid = (B * nh, sq_p // block_q, sk_p // block_k)

    ins = [qkv_p, qkv_p, qkv_p]
    in_specs = [
        pl.BlockSpec(
            (1, block_q, hd), lambda b, i, j: (b // nh, i, (b % nh) * 3)
        ),
        pl.BlockSpec(
            (1, block_k, hd),
            lambda b, i, j: (b // nh, j, (b % nh) * 3 + 1),
        ),
        pl.BlockSpec(
            (1, block_k, hd),
            lambda b, i, j: (b // nh, j, (b % nh) * 3 + 2),
        ),
    ]
    has_qkv_bias = qkv_bias is not None
    if has_qkv_bias:
        # middle singleton dim so the (1, hd) tile equals the array's
        # last-two dims (Mosaic block divisibility rule)
        b2 = qkv_bias.reshape(nh * 3, 1, hd)
        ins += [b2, b2, b2]
        in_specs += [
            pl.BlockSpec((1, 1, hd), lambda b, i, j: ((b % nh) * 3, 0, 0)),
            pl.BlockSpec(
                (1, 1, hd), lambda b, i, j: ((b % nh) * 3 + 1, 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, hd), lambda b, i, j: ((b % nh) * 3 + 2, 0, 0)
            ),
        ]
    if dropout_rate > 0.0:
        ins.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    out_shape = [
        jax.ShapeDtypeStruct((B, sq_p, nh * hd), qkv.dtype),
        jax.ShapeDtypeStruct((B * nh, sq_p, 1), jnp.float32),
    ]
    if sq_p == block_q and sk_p == block_k and block_q == block_k:
        # one tile covers the sequence: direct softmax, no online carry
        def _one_d(spec):
            # re-key the 3-d (b, i, j) index maps to the 1-d (b,) grid
            if spec.index_map is None:  # the SMEM seed spec
                return spec
            f = spec.index_map
            return pl.BlockSpec(spec.block_shape, lambda b, f=f: f(b, 0, 0))

        o, lse = pallas_call(
            functools.partial(
                _fwd_single_kernel, causal, scale, S, block_q, block_k,
                dropout_rate, has_qkv_bias=has_qkv_bias,
            ),
            grid=(B * nh,),
            in_specs=[_one_d(spec) for spec in in_specs],
            out_specs=[
                pl.BlockSpec((1, block_q, hd), lambda b: (b // nh, 0, b % nh)),
                pl.BlockSpec((1, block_q, 1), lambda b: (b, 0, 0)),
            ],
            out_shape=out_shape,
        )(*ins)
        return o[:, :S], lse[:, :S]

    o, lse = pallas_call(
        functools.partial(
            _fwd_kernel, causal, scale, S, block_q, block_k, False,
            dropout_rate, False, has_qkv_bias=has_qkv_bias,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (1, block_q, hd), lambda b, i, j: (b // nh, i, b % nh)
            ),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, hd), jnp.float32),
        ],
    )(*ins)
    return o[:, :S], lse[:, :S]


def _bwd_merged_kernel(
    causal, scale, sk_real, block_q, block_k, hd, dropout_rate,
    q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref, *refs,
    has_qkv_bias=False,
):
    """Single-block fused backward: dq + dk + dv in ONE kernel pass.

    Used when one (block_q, block_k) tile covers the whole sequence
    (the common training regime, e.g. s=1024 blocks 1024²). The split
    dkv/dq kernels each recompute the score and dp matrices and each
    re-read q/k/v/do from HBM — 7 MXU matmuls and 2x input traffic.
    This kernel shares those intermediates (5 matmuls, one read) and
    writes the three cotangents STRAIGHT INTO the packed projection
    layout: dqkv_ref is the (1, block, 3*hd) per-head column of the
    (B, S, nh*3*hd) qkv-projection cotangent, so the 3-way concat the
    split path needs disappears entirely. delta = rowsum(do·o) is also
    computed here from the o tile (a few VPU ops on data already in
    VMEM) instead of as a separate XLA reduction pass over the full
    (B, S, nh, hd) product in HBM."""
    refs = list(refs)
    qb_ref = refs.pop(0) if has_qkv_bias else None
    kb_ref = refs.pop(0) if has_qkv_bias else None
    vb_ref = refs.pop(0) if has_qkv_bias else None
    seed_ref = refs.pop(0) if dropout_rate > 0.0 else None
    if has_qkv_bias:
        dqkv_ref, dbias_ref = refs
    else:
        (dqkv_ref,) = refs
    b = pl.program_id(0)
    zero = jnp.int32(0)  # qi = ki = 0: the single block
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    if has_qkv_bias:
        # the saved residual is the PRE-bias projection output; the
        # probability recompute needs the biased operands
        q = q + qb_ref[0]
        k = k + kb_ref[0]
        v = v + vb_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = jnp.sum(
        do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
        axis=-1, keepdims=True,
    )
    s = _masked_scores(
        causal, scale, sk_real, block_q, block_k,
        q, k, None, None, b, zero, zero,
    )
    p = jnp.exp2(s - lse * LOG2E)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_PREC,
    )
    if dropout_rate > 0.0:
        keep = _keep_mask(
            seed_ref, dropout_rate, b, zero, zero, (block_q, block_k)
        )
        inv = 1.0 / (1.0 - dropout_rate)
        p_drop = jnp.where(keep, p * inv, 0.0)
        dp = jnp.where(keep, dp * inv, 0.0)
    else:
        p_drop = p
    dv = jax.lax.dot_general(
        p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_PREC,
    )
    # unscaled ds: the q·k scale is applied to the (block, d) dq/dk
    # results, not the (block, block) score tile
    ds = (p * (dp - delta)).astype(q.dtype)
    dk = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_PREC,
    ) * scale
    dq = jax.lax.dot(
        ds, k, preferred_element_type=jnp.float32, precision=_PREC,
    ) * scale
    dqkv_ref[0, :, :hd] = dq.astype(dqkv_ref.dtype)
    dqkv_ref[0, :, hd:2 * hd] = dk.astype(dqkv_ref.dtype)
    dqkv_ref[0, :, 2 * hd:] = dv.astype(dqkv_ref.dtype)
    if has_qkv_bias:
        # fp32 per-(batch, head) bias-grad partials while the cotangent
        # tiles are still in VMEM — replaces a full XLA reduction pass
        # over the (B, S, nh, 3hd) dqkv buffer in HBM (whose producer is
        # this opaque kernel, so XLA cannot fuse it)
        dbias_ref[0, 0, :hd] = jnp.sum(dq, axis=0)
        dbias_ref[0, 0, hd:2 * hd] = jnp.sum(dk, axis=0)
        dbias_ref[0, 0, 2 * hd:] = jnp.sum(dv, axis=0)


def _bwd_packed_merged(causal, scale, block, res, do,
                       dropout_rate=0.0, dropout_seed=None,
                       qkv_bias=None):
    """Single-tile packed backward: see `_bwd_merged_kernel`.

    With ``qkv_bias`` also returns the (nh*3*hd,) fp32 bias cotangent
    (summed over batch from the kernel's per-(batch, head) partials)."""
    qkv, o, lse = res
    B, S, nh, three_hd = qkv.shape
    hd = three_hd // 3
    pad = block

    qkv_p = jnp.pad(
        qkv.reshape(B, S, nh * three_hd), ((0, 0), (0, pad - S), (0, 0))
    )
    do_p = jnp.pad(do, ((0, 0), (0, pad - S), (0, 0)))
    o_p = jnp.pad(o, ((0, 0), (0, pad - S), (0, 0)))
    lse_p = jnp.pad(
        lse, ((0, 0), (0, pad - S), (0, 0)), constant_values=-NEG_INF
    )

    ins = [qkv_p, qkv_p, qkv_p, do_p, lse_p, o_p]
    in_specs = [
        pl.BlockSpec((1, block, hd), lambda b: (b // nh, 0, (b % nh) * 3)),
        pl.BlockSpec(
            (1, block, hd), lambda b: (b // nh, 0, (b % nh) * 3 + 1)
        ),
        pl.BlockSpec(
            (1, block, hd), lambda b: (b // nh, 0, (b % nh) * 3 + 2)
        ),
        pl.BlockSpec((1, block, hd), lambda b: (b // nh, 0, b % nh)),
        pl.BlockSpec((1, block, 1), lambda b: (b, 0, 0)),
        pl.BlockSpec((1, block, hd), lambda b: (b // nh, 0, b % nh)),
    ]
    has_qkv_bias = qkv_bias is not None
    if has_qkv_bias:
        b2 = qkv_bias.reshape(nh * 3, 1, hd)
        ins += [b2, b2, b2]
        in_specs += [
            pl.BlockSpec((1, 1, hd), lambda b: ((b % nh) * 3, 0, 0)),
            pl.BlockSpec((1, 1, hd), lambda b: ((b % nh) * 3 + 1, 0, 0)),
            pl.BlockSpec((1, 1, hd), lambda b: ((b % nh) * 3 + 2, 0, 0)),
        ]
    if dropout_rate > 0.0:
        ins.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    out_specs = pl.BlockSpec(
        (1, block, three_hd), lambda b: (b // nh, 0, b % nh)
    )
    out_shape = jax.ShapeDtypeStruct((B, pad, nh * three_hd), qkv.dtype)
    if has_qkv_bias:
        out_specs = [
            out_specs,
            pl.BlockSpec((1, 1, three_hd), lambda b: (b, 0, 0)),
        ]
        out_shape = [
            out_shape,
            jax.ShapeDtypeStruct((B * nh, 1, three_hd), jnp.float32),
        ]

    out = pallas_call(
        functools.partial(
            _bwd_merged_kernel, causal, scale, S, block, block, hd,
            dropout_rate, has_qkv_bias=has_qkv_bias,
        ),
        grid=(B * nh,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
    )(*ins)
    if has_qkv_bias:
        dqkv, dbias_part = out
        dbias = jnp.sum(
            dbias_part.reshape(B, nh * three_hd), axis=0
        )
        return dqkv[:, :S].reshape(B, S, nh, three_hd), dbias
    return out[:, :S].reshape(B, S, nh, three_hd)


def _bwd_packed(causal, scale, block_q, block_k, res, do,
                dropout_rate=0.0, dropout_seed=None, qkv_bias=None):
    qkv, o, lse = res  # qkv (B,S,nh,3hd), o (B,S,nh*hd), lse (B*nh,S,1)
    B, S, nh, three_hd = qkv.shape
    hd = three_hd // 3
    block_q = min(block_q, _round_up(S, 128))
    block_k = min(block_k, _round_up(S, 128))
    sq_p = _round_up(S, block_q)
    sk_p = _round_up(S, block_k)
    if sq_p == block_q and sk_p == block_k and block_q == block_k:
        # one tile covers the sequence: fused dq+dk+dv kernel, no concat
        return _bwd_packed_merged(
            causal, scale, block_q, res, do,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            qkv_bias=qkv_bias,
        )
    if qkv_bias is not None:
        # multi-tile fallback: biased operands via the pre-add (the
        # kernels then see the same values), dbias via an XLA reduce.
        # PRECISION: the reduce sums dqkv AFTER it is rounded to the
        # qkv dtype (bf16), whereas the single-tile merged path
        # accumulates fp32 partials in VMEM before casting — bias-grad
        # error here grows ~sqrt(B*S)·2^-8 relative. Acceptable for a
        # fallback (bias grads are O(B*S) sums either way and feed an
        # fp32 master update); emit fp32 partials from the split
        # kernels if large-B*S bias fidelity ever matters.
        qkv = qkv + qkv_bias.reshape(nh, three_hd).astype(qkv.dtype)
        dqkv = _bwd_packed(
            causal, scale, block_q, block_k, (qkv, o, lse), do,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
        return dqkv, jnp.sum(
            dqkv.astype(jnp.float32), axis=(0, 1)
        ).reshape(-1)
    pad = max(sq_p, sk_p)

    # delta rows are keyed by flat (B*nh) like lse: (B,S,nh) -> (B*nh,S,1)
    do4 = do.reshape(B, S, nh, hd)
    o4 = o.reshape(B, S, nh, hd)
    delta = jnp.sum(
        do4.astype(jnp.float32) * o4.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1).reshape(B * nh, S, 1)

    qkv_p = jnp.pad(
        qkv.reshape(B, S, nh * three_hd), ((0, 0), (0, pad - S), (0, 0))
    )
    do_p = jnp.pad(do, ((0, 0), (0, pad - S), (0, 0)))
    lse_p = jnp.pad(
        lse, ((0, 0), (0, pad - S), (0, 0)), constant_values=-NEG_INF
    )
    delta_p = jnp.pad(delta, ((0, 0), (0, pad - S), (0, 0)))

    ins = [qkv_p, qkv_p, qkv_p, do_p, lse_p, delta_p]
    if dropout_rate > 0.0:
        ins.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))

    def _specs(q_of, k_of):
        # q_of/k_of: map grid point (b, a, c) -> q-block / k-block index
        specs = [
            pl.BlockSpec(
                (1, block_q, hd),
                lambda b, a, c: (b // nh, q_of(a, c), (b % nh) * 3),
            ),
            pl.BlockSpec(
                (1, block_k, hd),
                lambda b, a, c: (b // nh, k_of(a, c), (b % nh) * 3 + 1),
            ),
            pl.BlockSpec(
                (1, block_k, hd),
                lambda b, a, c: (b // nh, k_of(a, c), (b % nh) * 3 + 2),
            ),
            pl.BlockSpec(
                (1, block_q, hd),
                lambda b, a, c: (b // nh, q_of(a, c), b % nh),
            ),
            pl.BlockSpec(
                (1, block_q, 1), lambda b, a, c: (b, q_of(a, c), 0)
            ),
            pl.BlockSpec(
                (1, block_q, 1), lambda b, a, c: (b, q_of(a, c), 0)
            ),
        ]
        if dropout_rate > 0.0:
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        return specs

    # dk/dv: grid (bh, kv, q) — q innermost
    dk, dv = pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal, scale, S, block_q, block_k, False,
            dropout_rate, False,
        ),
        grid=(B * nh, sk_p // block_k, sq_p // block_q),
        in_specs=_specs(q_of=lambda j, i: i, k_of=lambda j, i: j),
        out_specs=[
            pl.BlockSpec(
                (1, block_k, hd), lambda b, j, i: (b // nh, j, b % nh)
            ),
            pl.BlockSpec(
                (1, block_k, hd), lambda b, j, i: (b // nh, j, b % nh)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, sk_p, nh * hd), qkv.dtype),
            jax.ShapeDtypeStruct((B, sk_p, nh * hd), qkv.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
    )(*ins)

    # dq: grid (bh, q, kv) — kv innermost
    dq = pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal, scale, S, block_q, block_k, False,
            dropout_rate, False,
        ),
        grid=(B * nh, sq_p // block_q, sk_p // block_k),
        in_specs=_specs(q_of=lambda i, j: i, k_of=lambda i, j: j),
        out_specs=pl.BlockSpec(
            (1, block_q, hd), lambda b, i, j: (b // nh, i, b % nh)
        ),
        out_shape=jax.ShapeDtypeStruct((B, sq_p, nh * hd), qkv.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
    )(*ins)

    # the only relayout in the whole path: one concat into the qkv
    # cotangent (the projection's own (B, S, nh, 3·hd) layout)
    dqkv = jnp.concatenate(
        [
            dq[:, :S].reshape(B, S, nh, hd),
            dk[:, :S].reshape(B, S, nh, hd),
            dv[:, :S].reshape(B, S, nh, hd),
        ],
        axis=-1,
    )
    return dqkv


def _qkv_scale(qkv, scale):
    return scale if scale is not None else 1.0 / np.sqrt(qkv.shape[-1] // 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def flash_attention_qkv(
    qkv: jnp.ndarray,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jnp.ndarray:
    """Zero-relayout self attention on a fused projection output.

    ``qkv`` is (B, S, nh, 3*hd) — exactly the reshape of a fused QKV
    projection, with q|k|v contiguous per head in the last dim and
    hd % 128 == 0. Returns the (B, S, nh*hd) context, laid out for the
    output projection. q/k/v tiles are read straight out of ``qkv`` by
    kernel index maps: no transpose, split, or concat materializes in
    forward (backward does one concat for the qkv cotangent).
    """
    o, _ = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k
    )
    return o


def _faq_fwd(qkv, causal, scale, block_q, block_k):
    o, lse = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k
    )
    return o, (qkv, o, lse)


def _faq_bwd(causal, scale, block_q, block_k, res, do):
    qkv = res[0]
    dqkv = _bwd_packed(
        causal, _qkv_scale(qkv, scale), block_q, block_k, res, do
    )
    return (dqkv,)


flash_attention_qkv.defvjp(_faq_fwd, _faq_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def flash_attention_qkv_dropout(
    qkv: jnp.ndarray,
    dropout_seed,
    dropout_rate: float,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jnp.ndarray:
    """`flash_attention_qkv` with in-kernel attention dropout (see
    `flash_attention_dropout` for the seeding/regeneration scheme)."""
    o, _ = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
    )
    return o


def _faqd_fwd(qkv, dropout_seed, dropout_rate, causal, scale,
              block_q, block_k):
    o, lse = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
    )
    return o, (qkv, o, lse, dropout_seed)


def _faqd_bwd(dropout_rate, causal, scale, block_q, block_k, res, do):
    qkv, o, lse, seed = res
    dqkv = _bwd_packed(
        causal, _qkv_scale(qkv, scale), block_q, block_k,
        (qkv, o, lse), do,
        dropout_rate=dropout_rate, dropout_seed=seed,
    )
    seed_ct = np.zeros((), jax.dtypes.float0)
    return (dqkv, seed_ct)


flash_attention_qkv_dropout.defvjp(_faqd_fwd, _faqd_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def flash_attention_qkv_bias(
    qkv: jnp.ndarray,
    qkv_bias: jnp.ndarray,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jnp.ndarray:
    """`flash_attention_qkv` with the QKV-projection BIAS fused in.

    ``qkv`` is the bias-free fused projection output (B, S, nh, 3*hd)
    (e.g. from `ColumnParallelLinear(skip_bias_add=True)`) and
    ``qkv_bias`` its (nh*3*hd,) bias. The add happens on tile load (the
    same bf16 add a matmul epilogue performs) and — the actual point —
    the backward emits fp32 bias-grad partials from VMEM, replacing the
    full-buffer XLA reduction over dqkv that cannot fuse with this
    kernel's opaque output. The reference fuses qkv biases into its
    attention kernels the same way
    (apex/contrib/csrc/multihead_attn/ *_bias variants)."""
    o, _ = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k,
        qkv_bias=qkv_bias,
    )
    return o


def _faqb_fwd(qkv, qkv_bias, causal, scale, block_q, block_k):
    o, lse = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k,
        qkv_bias=qkv_bias,
    )
    return o, (qkv, qkv_bias, o, lse)


def _faqb_bwd(causal, scale, block_q, block_k, res, do):
    qkv, qkv_bias, o, lse = res
    dqkv, dbias = _bwd_packed(
        causal, _qkv_scale(qkv, scale), block_q, block_k,
        (qkv, o, lse), do, qkv_bias=qkv_bias,
    )
    return (dqkv, dbias.astype(qkv_bias.dtype).reshape(qkv_bias.shape))


flash_attention_qkv_bias.defvjp(_faqb_fwd, _faqb_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_qkv_bias_dropout(
    qkv: jnp.ndarray,
    qkv_bias: jnp.ndarray,
    dropout_seed,
    dropout_rate: float,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jnp.ndarray:
    """`flash_attention_qkv_bias` with in-kernel attention dropout."""
    o, _ = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        qkv_bias=qkv_bias,
    )
    return o


def _faqbd_fwd(qkv, qkv_bias, dropout_seed, dropout_rate, causal, scale,
               block_q, block_k):
    o, lse = _fwd_packed(
        qkv, causal, _qkv_scale(qkv, scale), block_q, block_k,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        qkv_bias=qkv_bias,
    )
    return o, (qkv, qkv_bias, o, lse, dropout_seed)


def _faqbd_bwd(dropout_rate, causal, scale, block_q, block_k, res, do):
    qkv, qkv_bias, o, lse, seed = res
    dqkv, dbias = _bwd_packed(
        causal, _qkv_scale(qkv, scale), block_q, block_k,
        (qkv, o, lse), do,
        dropout_rate=dropout_rate, dropout_seed=seed, qkv_bias=qkv_bias,
    )
    seed_ct = np.zeros((), jax.dtypes.float0)
    return (
        dqkv,
        dbias.astype(qkv_bias.dtype).reshape(qkv_bias.shape),
        seed_ct,
    )


flash_attention_qkv_bias_dropout.defvjp(_faqbd_fwd, _faqbd_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    compute_dbias: bool = False,
):
    """`flash_attention` also returning the per-row log-sum-exp.

    The (o, lse) pair is the mergeable partial-attention form: two
    partials over disjoint key sets combine as

        lse = logaddexp(lse1, lse2)
        o   = o1 * exp(lse1 - lse) + o2 * exp(lse2 - lse)

    which is what ring/context-parallel attention reduces over
    (transformer/context_parallel.py). Differentiable in q/k/v with lse
    cotangents folded into the fused backward; like `flash_attention`,
    bias gradients are an explicit ``compute_dbias=True`` opt-in (the
    ring masks are constants).

    BEHAVIOR CHANGE (round 4): ``compute_dbias`` previously defaulted
    to True here. A caller differentiating a LEARNED bias must now
    pass ``compute_dbias=True`` or the bias cotangent is exact zero —
    silently, since the structure is unchanged. All in-repo callers
    pass constant masks (bias=None or padding masks).
    """
    return _fwd(
        q, k, v, bias, causal,
        scale if scale is not None else 1.0 / np.sqrt(q.shape[-1]),
        block_q, block_k,
    )


def _fal_fwd(q, k, v, bias, causal, scale, block_q, block_k,
             compute_dbias):
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    o, lse = _fwd(q, k, v, bias, causal, s, block_q, block_k)
    return (o, lse), (q, k, v, bias, o, lse)


def _fal_bwd(causal, scale, block_q, block_k, compute_dbias, res, cot):
    do, dlse = cot
    s = scale if scale is not None else 1.0 / np.sqrt(res[0].shape[-1])
    return _bwd(causal, s, block_q, block_k, res, do, dlse=dlse,
                compute_dbias=compute_dbias)


flash_attention_with_lse.defvjp(_fal_fwd, _fal_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def flash_attention_dropout(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    dropout_seed,
    dropout_rate: float,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    compute_dbias: bool = False,
) -> jnp.ndarray:
    """`flash_attention` with in-kernel attention dropout.

    Torch semantics (softmax -> dropout -> @v): the normalizer uses the
    undropped probabilities and kept entries scale by 1/(1-rate). The
    keep mask is never materialized — all three kernels regenerate it
    from ``dropout_seed`` and the (batch, q-block, k-block) grid
    coordinates via the TPU PRNG (reference: the fused dropout of
    apex/contrib/csrc/multihead_attn and fmha kernels). TPU-only:
    `pltpu.prng_*` has no interpret-mode lowering — callers off-TPU
    must use their materialized fallback (ops._pallas.on_tpu()).
    ``dropout_seed`` is a traced int32 scalar, so per-step seeds do not
    recompile.
    """
    o, _ = _fwd(
        q, k, v, bias, causal,
        scale if scale is not None else 1.0 / np.sqrt(q.shape[-1]),
        block_q, block_k,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
    )
    return o


def _fad_fwd(q, k, v, bias, dropout_seed, dropout_rate, causal, scale,
             block_q, block_k, compute_dbias):
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    o, lse = _fwd(
        q, k, v, bias, causal, s, block_q, block_k,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
    )
    return o, (q, k, v, bias, o, lse, dropout_seed)


def _fad_bwd(dropout_rate, causal, scale, block_q, block_k,
             compute_dbias, res, do):
    q, k, v, bias, o, lse, seed = res
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    dq, dk, dv, dbias = _bwd(
        causal, s, block_q, block_k, (q, k, v, bias, o, lse), do,
        dropout_rate=dropout_rate, dropout_seed=seed,
        compute_dbias=compute_dbias,
    )
    seed_ct = np.zeros((), jax.dtypes.float0)
    return (dq, dk, dv, dbias, seed_ct)


flash_attention_dropout.defvjp(_fad_fwd, _fad_bwd)
