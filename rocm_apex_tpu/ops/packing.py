"""Packed-pytree buffers: the TPU-native multi-tensor-apply substrate.

The reference chunks up-to-110-tensor address packs into repeated kernel
launches (reference: csrc/multi_tensor_apply.cuh:16-26 `TensorListMetadata`,
:44-147 chunking loop). On TPU the idiomatic equivalent is to flatten the
whole parameter pytree ONCE into a few dtype-segregated, lane-aligned 2-D
buffers and run every "multi-tensor" op as a single Pallas call over the
packed buffer — no chunk bookkeeping, no launch loop, and XLA sees one
fused program.

Layout invariants:
  * one buffer per parameter dtype (the analogue of the reference DDP's
    dtype-segregated grad buckets, apex/parallel/distributed.py:241-244);
  * each leaf starts on a fresh row of ``WIDTH = 8*128`` elements, so a
    row never straddles two tensors — which makes per-tensor quantities
    (LAMB trust ratios, per-tensor L2 norms,
    csrc/multi_tensor_l2norm_kernel.cu:29-114) expressible as segmented
    row reductions;
  * buffer row counts are padded to ``ALIGN_ROWS`` with zeros so every
    Pallas grid block is full (zero padding is harmless for every op in
    this layer: scales/axpby map 0→0, norms add 0, optimizer updates of
    zero-initialized zero-grad rows stay 0).

`PackSpec` is hashable static metadata (safe as a jit-static argument);
`PackedTree` is a registered pytree whose children are the buffers.
"""

import functools
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu.ops._pallas import LANE, SUBLANE

__all__ = [
    "WIDTH",
    "ALIGN_ROWS",
    "LeafSpec",
    "GroupSpec",
    "PackSpec",
    "PackedTree",
    "build_pack_spec",
    "pack_tree",
    "pack_like",
    "unpack_tree",
    "group_segment_ids",
    "respec",
]

WIDTH = SUBLANE * LANE  # 1024: one fp32 VREG worth of elements per row
ALIGN_ROWS = 64  # block-grid alignment (multiple of every dtype's sublane tile)


class LeafSpec(NamedTuple):
    """Static placement of one pytree leaf inside its dtype-group buffer."""

    shape: Tuple[int, ...]
    dtype: str
    row_start: int
    nrows: int
    numel: int


class GroupSpec(NamedTuple):
    """One dtype-segregated buffer: which leaves it holds and where."""

    dtype: str
    leaf_indices: Tuple[int, ...]  # indices into the flattened-tree leaf list
    leaf_specs: Tuple[LeafSpec, ...]
    rows: int  # padded to ALIGN_ROWS


class PackSpec(NamedTuple):
    treedef: Any
    groups: Tuple[GroupSpec, ...]
    n_leaves: int


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def build_pack_spec(tree: Any) -> PackSpec:
    """Compute the static packing layout for a pytree of floating arrays."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    leaves = [jnp.asarray(x) for x in leaves]
    by_dtype = {}
    for i, leaf in enumerate(leaves):
        dt = leaf.dtype
        if not jnp.issubdtype(dt, jnp.inexact):
            raise TypeError(
                f"pack_tree only packs floating leaves; leaf {i} has dtype {dt}"
            )
        by_dtype.setdefault(jnp.dtype(dt).name, []).append(i)

    groups = []
    for dtype_name in sorted(by_dtype):
        idxs = by_dtype[dtype_name]
        specs = []
        row = 0
        for i in idxs:
            leaf = leaves[i]
            numel = int(np.prod(leaf.shape)) if leaf.shape else 1
            nrows = max(1, -(-numel // WIDTH))
            specs.append(
                LeafSpec(
                    shape=tuple(leaf.shape),
                    dtype=dtype_name,
                    row_start=row,
                    nrows=nrows,
                    numel=numel,
                )
            )
            row += nrows
        groups.append(
            GroupSpec(
                dtype=dtype_name,
                leaf_indices=tuple(idxs),
                leaf_specs=tuple(specs),
                rows=_round_up(max(row, 1), ALIGN_ROWS),
            )
        )
    return PackSpec(treedef=treedef, groups=tuple(groups), n_leaves=len(leaves))


@jax.tree_util.register_pytree_node_class
class PackedTree:
    """A pytree packed into dtype-segregated (rows, WIDTH) buffers."""

    def __init__(self, buffers: Sequence[jnp.ndarray], spec: PackSpec):
        self.buffers = tuple(buffers)
        self.spec = spec

    def tree_flatten(self):
        return self.buffers, self.spec

    @classmethod
    def tree_unflatten(cls, spec, buffers):
        return cls(buffers, spec)

    def __repr__(self):
        shapes = ", ".join(
            f"{g.dtype}[{g.rows}x{WIDTH}]" for g in self.spec.groups
        )
        return f"PackedTree({shapes}, n_leaves={self.spec.n_leaves})"


def _pack_group(leaves, group: GroupSpec, cast: bool) -> jnp.ndarray:
    parts = []
    for i, ls in zip(group.leaf_indices, group.leaf_specs):
        flat = jnp.ravel(jnp.asarray(leaves[i]))
        if cast:
            flat = flat.astype(group.dtype)
        elif flat.dtype != jnp.dtype(group.dtype):
            raise TypeError(
                f"leaf {i} has dtype {flat.dtype} but the pack spec expects "
                f"{group.dtype}; use pack_like() to pack a tree whose dtypes "
                "differ from the spec's"
            )
        pad = ls.nrows * WIDTH - ls.numel
        if pad:
            flat = jnp.pad(flat, (0, pad))
        parts.append(flat)
    used_rows = sum(ls.nrows for ls in group.leaf_specs)
    tail = group.rows - used_rows
    if tail or not parts:
        parts.append(jnp.zeros((tail * WIDTH,), dtype=group.dtype))
    return jnp.concatenate(parts).reshape(group.rows, WIDTH)


def pack_tree(tree: Any, spec: Optional[PackSpec] = None) -> PackedTree:
    """Pack a pytree into lane-aligned buffers (layout from `spec` if given)."""
    if spec is None:
        spec = build_pack_spec(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != spec.n_leaves:
        raise ValueError(
            f"tree has {len(leaves)} leaves but spec describes {spec.n_leaves}"
        )
    buffers = [_pack_group(leaves, g, cast=False) for g in spec.groups]
    return PackedTree(buffers, spec)


def pack_like(spec: PackSpec, tree: Any) -> PackedTree:
    """Pack `tree` (same structure/shapes) into `spec`'s layout, casting each
    leaf to its group dtype.

    Used to align gradient pytrees with a parameter packing even when
    their dtypes differ (e.g. fp32 unscaled grads against bf16 params —
    the master-weight flow, reference: apex/amp/_process_optimizer.py:161-207).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != spec.n_leaves:
        raise ValueError(
            f"tree has {len(leaves)} leaves but spec describes {spec.n_leaves}"
        )
    buffers = [_pack_group(leaves, g, cast=True) for g in spec.groups]
    return PackedTree(buffers, spec)


def unpack_tree(packed: PackedTree) -> Any:
    """Invert `pack_tree`: slice each leaf back out of its group buffer."""
    spec = packed.spec
    leaves = [None] * spec.n_leaves
    for buf, group in zip(packed.buffers, spec.groups):
        flat = buf.reshape(-1)
        for i, ls in zip(group.leaf_indices, group.leaf_specs):
            start = ls.row_start * WIDTH
            leaf = jax.lax.dynamic_slice_in_dim(flat, start, ls.numel)
            leaves[i] = leaf.reshape(ls.shape)
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def respec(spec: PackSpec, dtype) -> PackSpec:
    """A PackSpec with identical layout but every group/leaf in `dtype`.

    Used to pack companion trees (fp32 grads, fp32 moments) row-aligned
    with a low-precision parameter packing — the packed analogue of the
    reference's separate fp32 master/moment tensor lists
    (reference: apex/amp/_process_optimizer.py:28-90).
    """
    if dtype is None:
        return spec
    name = jnp.dtype(dtype).name
    return spec._replace(
        groups=tuple(
            g._replace(
                dtype=name,
                leaf_specs=tuple(ls._replace(dtype=name) for ls in g.leaf_specs),
            )
            for g in spec.groups
        )
    )


@functools.lru_cache(maxsize=64)
def group_segment_ids(group: GroupSpec) -> np.ndarray:
    """row → local-tensor-index map for segmented per-tensor reductions.

    Padding tail rows map to segment `len(leaf_specs)` so they can be
    dropped from per-tensor results (their contribution is zero anyway).
    """
    ids = np.full((group.rows,), len(group.leaf_specs), dtype=np.int32)
    for j, ls in enumerate(group.leaf_specs):
        ids[ls.row_start : ls.row_start + ls.nrows] = j
    return ids
