"""Row-tiled fused LayerNorm forward/backward in Pallas.

TPU-native equivalent of the fused LayerNorm kernels
(reference: csrc/layer_norm_cuda.cpp:121-267 +
csrc/layer_norm_cuda_kernel.cu:875, and the fast_layer_norm contrib
variant apex/contrib/csrc/layer_norm/). The forward returns
``(y, mean, rsigma)`` with the row statistics saved for the backward —
the same contract as the reference's `FusedLayerNormAffineFunction`
(reference: apex/normalization/fused_layer_norm.py:15-82) — wired up as
a `jax.custom_vjp` so `jax.grad` uses the fused backward.

Gamma/beta gradients use the reference's two-stage scheme (per-block
partials in-kernel, final reduction outside —
layer_norm_cuda_kernel.cu's gamma/beta two-stage reduction).

One kernel pair serves both the plain and the fused-residual form
(``residual``/``ds`` flags): `layer_norm_residual_affine` computes
s = x + delta in-kernel, emits (LN(s), s), and folds the stream
cotangent ds into the dx pass — the transformer's residual adds are
otherwise standalone HBM round trips XLA cannot fuse into a custom
call. (No reference analogue; the CUDA build leaves the add to torch.)

`layer_norm_residual_dropout_affine` additionally applies DROPOUT to
the delta inside the same kernel (s = x + keep·delta/(1−p)), with the
keep mask drawn from the TPU hardware PRNG and REGENERATED in the
backward from the same seed — the flash-dropout recompute trick
(ops/flash_attention.py `_keep_mask`, shared so forward and backward
bits cannot desynchronize). No mask tensor ever reaches HBM: the
standalone rbg-dropout path costs ~3 ms/step on the 134M bench in
u32[b,s,h] mask saves for backward + generation passes (round-5
profile), all of which this kernel removes. TPU-only (the in-kernel
PRNG has no interpret-mode lowering); callers gate on `on_tpu()`.
The reference applies hidden dropout inside its fused kernels the
same way (apex/contrib/csrc/multihead_attn/dropout_add variants).

All math is fp32 in-register; output dtype follows the input (or the
weight dtype for the mixed variant, handled by the module layer).
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from rocm_apex_tpu.ops._pallas import kernel_dtype, pad_rows, pallas_call, row_block

__all__ = [
    "layer_norm_fwd",
    "layer_norm",
    "layer_norm_affine",
    "layer_norm_residual_affine",
    "layer_norm_residual_dropout_affine",
]


def _row_tiles(x2d):
    """Row tiling for a (rows, hidden) operand: (block, padded_x, grid).

    ONE definition shared by the forward AND backward pass builders.
    The in-kernel dropout keep mask is regenerated in the backward from
    (seed, row-block index) — `_keep_mask` seeded by `pl.program_id` —
    so a block-size or padding change applied to one pass but not the
    other would silently hand the backward different keep bits than
    the forward applied. Any retuning happens here or nowhere."""
    block = row_block(x2d.shape[1])
    x_p = pad_rows(x2d, block)
    return block, x_p, x_p.shape[0] // block


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _ln_fwd_kernel(affine, residual, rate, eps, x_ref, *refs):
    refs = list(refs)
    r_ref = refs.pop(0) if residual else None
    if affine:
        g_ref, b_ref = refs.pop(0), refs.pop(0)
    seed_ref = refs.pop(0) if rate > 0.0 else None
    y_ref = refs.pop(0)
    s_ref = refs.pop(0) if residual else None
    mu_ref, rs_ref = refs
    x = x_ref[...].astype(jnp.float32)
    if residual:
        d = r_ref[...].astype(jnp.float32)
        if rate > 0.0:
            # in-kernel dropout on the delta; the backward regenerates
            # the identical bits from (seed, row-block) — no mask in HBM
            from rocm_apex_tpu.ops.flash_attention import _keep_mask

            i = pl.program_id(0)
            zero = jnp.int32(0)
            keep = _keep_mask(seed_ref, rate, i, zero, zero, d.shape)
            d = jnp.where(keep, d * (1.0 / (1.0 - rate)), 0.0)
        x = x + d
        s_ref[...] = x.astype(s_ref.dtype)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rs = jax.lax.rsqrt(var + eps)
    y = xc * rs
    if affine:
        y = y * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mu
    rs_ref[...] = rs


def _ln_fwd_impl(x2d, delta2d, weight, bias, eps, out_dtype,
                 rate=0.0, seed=None):
    """Shared forward: plain LN when delta2d is None, fused residual
    form otherwise (extra s = x + delta output); rate > 0 adds
    in-kernel dropout on the delta (TPU only)."""
    rows0, hidden = x2d.shape
    out_dtype = out_dtype or x2d.dtype
    affine = weight is not None
    residual = delta2d is not None
    block, x_p, grid = _row_tiles(x2d)
    rows = x_p.shape[0]

    row_spec = pl.BlockSpec((block, hidden), lambda i: (i, 0))
    col_spec = pl.BlockSpec((block, 1), lambda i: (i, 0))
    gb_spec = pl.BlockSpec((1, hidden), lambda i: (0, 0))

    ins = [x_p.astype(kernel_dtype(x_p.dtype))]
    in_specs = [row_spec]
    if residual:
        r_p = pad_rows(delta2d, block)
        ins.append(r_p.astype(kernel_dtype(r_p.dtype)))
        in_specs.append(row_spec)
    if affine:
        ins += [
            weight.reshape(1, hidden).astype(kernel_dtype(weight.dtype)),
            bias.reshape(1, hidden).astype(kernel_dtype(bias.dtype)),
        ]
        in_specs += [gb_spec, gb_spec]
    if rate > 0.0:
        from jax.experimental.pallas import tpu as pltpu

        ins.append(jnp.asarray(seed, jnp.int32).reshape(1))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, hidden), kernel_dtype(out_dtype))]
    if residual:
        out_specs.append(row_spec)
        out_shape.append(
            jax.ShapeDtypeStruct((rows, hidden), kernel_dtype(x2d.dtype))
        )
    out_specs += [col_spec, col_spec]
    out_shape += [
        jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        jax.ShapeDtypeStruct((rows, 1), jnp.float32),
    ]

    outs = pallas_call(
        functools.partial(_ln_fwd_kernel, affine, residual, rate, eps),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
    )(*ins)
    if residual:
        y, s, mu, rs = outs
        s = s[:rows0].astype(x2d.dtype)
    else:
        y, mu, rs = outs
        s = None
    return (
        y[:rows0].astype(out_dtype),
        s,
        mu[:rows0, 0],
        rs[:rows0, 0],
    )


def layer_norm_fwd(
    x2d: jnp.ndarray,
    weight: Optional[jnp.ndarray],
    bias: Optional[jnp.ndarray],
    eps: float,
    out_dtype=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """LN forward on a (rows, hidden) view; returns (y, mean, rsigma).

    The (rows, hidden) restriction mirrors the fast LN contract
    (reference: apex/contrib/layer_norm/layer_norm.py:8-40); the module
    layer reshapes arbitrary normalized_shape to this view
    (reference: apex/normalization/fused_layer_norm.py).
    """
    y, _, mu, rs = _ln_fwd_impl(x2d, None, weight, bias, eps, out_dtype)
    return y, mu, rs


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _ln_bwd_kernel(affine, has_ds, rate, x_ref, dy_ref, *refs):
    refs = list(refs)
    ds_ref = refs.pop(0) if has_ds else None
    mu_ref, rs_ref = refs.pop(0), refs.pop(0)
    seed_ref = refs.pop(0) if rate > 0.0 else None
    if affine:
        g_ref = refs.pop(0)
    dx_ref = refs.pop(0)
    dd_ref = refs.pop(0) if rate > 0.0 else None
    if affine:
        dg_ref, db_ref = refs
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mu = mu_ref[...]
    rs = rs_ref[...]
    xhat = (x - mu) * rs
    if affine:
        g = g_ref[...].astype(jnp.float32)
        dyg = dy * g
        # per-block partials for the two-stage gamma/beta reduction,
        # padded to a full 8-sublane tile (row 0 holds the partial)
        pad = jnp.zeros((7, x.shape[1]), jnp.float32)
        dg_ref[...] = jnp.concatenate(
            [jnp.sum(dy * xhat, axis=0, keepdims=True), pad]
        )
        db_ref[...] = jnp.concatenate(
            [jnp.sum(dy, axis=0, keepdims=True), pad]
        )
    else:
        dyg = dy
    c1 = jnp.mean(dyg, axis=1, keepdims=True)
    c2 = jnp.mean(dyg * xhat, axis=1, keepdims=True)
    dx = rs * (dyg - c1 - xhat * c2)
    if has_ds:
        # the residual stream's cotangent rides the same pass
        dx = dx + ds_ref[...].astype(jnp.float32)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if rate > 0.0:
        # regenerate the forward's keep bits (same seed, same block
        # coords, same shared _keep_mask) and emit the delta cotangent
        from rocm_apex_tpu.ops.flash_attention import _keep_mask

        i = pl.program_id(0)
        zero = jnp.int32(0)
        keep = _keep_mask(seed_ref, rate, i, zero, zero, dx.shape)
        dd = jnp.where(keep, dx * (1.0 / (1.0 - rate)), 0.0)
        dd_ref[...] = dd.astype(dd_ref.dtype)


def _layer_norm_bwd(affine, eps, res, dy, ds=None, rate=0.0, seed=None):
    if rate > 0.0 and not affine:
        # the rate>0 unpacking below is affine-only; silently dropping
        # the dd output would lose the delta gradient
        raise NotImplementedError(
            "in-kernel dropout backward is only wired for the affine form"
        )
    x2d, weight, mu, rs = res
    rows0, hidden = x2d.shape
    has_ds = ds is not None
    # the SAME tiling as the forward (see _row_tiles: the dropout mask
    # regeneration depends on it)
    block, x_p, grid = _row_tiles(x2d)
    dy_p = pad_rows(dy, block)
    rows = x_p.shape[0]
    mu_p = jnp.pad(mu.reshape(-1, 1), ((0, rows - rows0), (0, 0)))
    rs_p = jnp.pad(rs.reshape(-1, 1), ((0, rows - rows0), (0, 0)))

    row_spec = pl.BlockSpec((block, hidden), lambda i: (i, 0))
    col_spec = pl.BlockSpec((block, 1), lambda i: (i, 0))
    ins = [
        x_p.astype(kernel_dtype(x_p.dtype)),
        dy_p.astype(kernel_dtype(dy_p.dtype)),
    ]
    in_specs = [row_spec, row_spec]
    if has_ds:
        ds_p = pad_rows(ds, block)
        ins.append(ds_p.astype(kernel_dtype(ds_p.dtype)))
        in_specs.append(row_spec)
    ins += [mu_p, rs_p]
    in_specs += [col_spec, col_spec]
    if rate > 0.0:
        from jax.experimental.pallas import tpu as pltpu

        ins.append(jnp.asarray(seed, jnp.int32).reshape(1))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, hidden), kernel_dtype(x2d.dtype))]
    if rate > 0.0:
        out_specs.append(row_spec)
        out_shape.append(
            jax.ShapeDtypeStruct((rows, hidden), kernel_dtype(x2d.dtype))
        )
    if affine:
        ins.append(weight.reshape(1, hidden).astype(kernel_dtype(weight.dtype)))
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0)))
        out_specs += [
            pl.BlockSpec((8, hidden), lambda i: (i, 0)),
            pl.BlockSpec((8, hidden), lambda i: (i, 0)),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((grid * 8, hidden), jnp.float32),
            jax.ShapeDtypeStruct((grid * 8, hidden), jnp.float32),
        ]

    outs = pallas_call(
        functools.partial(_ln_bwd_kernel, affine, has_ds, rate),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
    )(*ins)
    if affine and rate > 0.0:
        dx, dd, dg_part, db_part = outs
        dg = dg_part.sum(axis=0).astype(weight.dtype)
        db = db_part.sum(axis=0).astype(weight.dtype)
        return (
            dx[:rows0].astype(x2d.dtype),
            dd[:rows0].astype(x2d.dtype),
            dg,
            db,
        )
    if affine:
        dx, dg_part, db_part = outs
        dg = dg_part.sum(axis=0).astype(weight.dtype)
        db = db_part.sum(axis=0).astype(weight.dtype)
        return dx[:rows0].astype(x2d.dtype), dg, db
    dx = outs if not isinstance(outs, (tuple, list)) else outs[0]
    return (dx[:rows0].astype(x2d.dtype),)


# ---------------------------------------------------------------------------
# custom_vjp wrappers
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm_affine(x2d, weight, bias, eps):
    """Affine LN on (rows, hidden) with the fused backward."""
    y, _, _ = layer_norm_fwd(x2d, weight, bias, eps)
    return y


def _lna_fwd(x2d, weight, bias, eps):
    y, mu, rs = layer_norm_fwd(x2d, weight, bias, eps)
    return y, (x2d, weight, mu, rs)


def _lna_bwd(eps, res, dy):
    dx, dg, db = _layer_norm_bwd(True, eps, res, dy)
    return dx, dg, db


layer_norm_affine.defvjp(_lna_fwd, _lna_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def layer_norm(x2d, eps):
    """Non-affine LN on (rows, hidden) with the fused backward."""
    y, _, _ = layer_norm_fwd(x2d, None, None, eps)
    return y


def _ln_fwd_rule(x2d, eps):
    y, mu, rs = layer_norm_fwd(x2d, None, None, eps)
    return y, (x2d, None, mu, rs)


def _ln_bwd_rule(eps, res, dy):
    (dx,) = _layer_norm_bwd(False, eps, res, dy)
    return (dx,)


layer_norm.defvjp(_ln_fwd_rule, _ln_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def layer_norm_residual_affine(
    x2d, delta2d, weight, bias, eps, out_dtype=None
):
    """(LN(x+delta), x+delta) in ONE kernel on (rows, hidden) views.

    Returns ``(y, s)``: ``s`` is the new residual stream, ``y`` its
    affine layer norm (dtype ``out_dtype``, default x's). The backward
    folds the stream cotangent into the dx pass, so the standalone
    residual-add disappears from both directions; dx == ddelta up to
    each input's own dtype (the add fans out).
    """
    y, s, _, _ = _ln_fwd_impl(x2d, delta2d, weight, bias, eps, out_dtype)
    return y, s


def _lnr_fwd(x2d, delta2d, weight, bias, eps, out_dtype):
    y, s, mu, rs = _ln_fwd_impl(x2d, delta2d, weight, bias, eps, out_dtype)
    # s carries x2d's dtype; a zero-size witness carries delta2d's
    # (residuals must be JAX values, not dtype objects)
    d_witness = jnp.zeros((0,), delta2d.dtype)
    return (y, s), (s, weight, mu, rs, d_witness)


def _lnr_bwd(eps, out_dtype, res, cts):
    dy, ds = cts
    s, weight, mu, rs, d_witness = res
    dx, dg, db = _layer_norm_bwd(
        True, eps, (s, weight, mu, rs), dy, ds=ds
    )
    return dx.astype(s.dtype), dx.astype(d_witness.dtype), dg, db


layer_norm_residual_affine.defvjp(_lnr_fwd, _lnr_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def layer_norm_residual_dropout_affine(
    x2d, delta2d, weight, bias, seed, rate, eps, out_dtype=None
):
    """(LN(x + dropout(delta)), x + dropout(delta)) in ONE kernel.

    Like `layer_norm_residual_affine` but the delta passes through
    dropout (keep prob 1−rate, scaled 1/(1−rate)) INSIDE the kernel:
    the keep mask comes from the TPU hardware PRNG seeded by
    (``seed``, row-block) and is regenerated bit-identically in the
    backward — no mask tensor is stored (see module docstring).
    ``seed`` is an int32 scalar; draw one per dropout site.
    TPU-only: the in-kernel PRNG has no interpret-mode lowering.
    """
    y, s, _, _ = _ln_fwd_impl(
        x2d, delta2d, weight, bias, eps, out_dtype, rate=rate, seed=seed
    )
    return y, s


def _lnrd_fwd(x2d, delta2d, weight, bias, seed, rate, eps, out_dtype):
    y, s, mu, rs = _ln_fwd_impl(
        x2d, delta2d, weight, bias, eps, out_dtype, rate=rate, seed=seed
    )
    d_witness = jnp.zeros((0,), delta2d.dtype)
    return (y, s), (s, weight, mu, rs, seed, d_witness)


def _lnrd_bwd(rate, eps, out_dtype, res, cts):
    dy, ds = cts
    s, weight, mu, rs, seed, d_witness = res
    dx, dd, dg, db = _layer_norm_bwd(
        True, eps, (s, weight, mu, rs), dy, ds=ds, rate=rate, seed=seed
    )
    seed_ct = np.zeros((), jax.dtypes.float0)
    return (
        dx.astype(s.dtype),
        dd.astype(d_witness.dtype),
        dg,
        db,
        seed_ct,
    )


layer_norm_residual_dropout_affine.defvjp(_lnrd_fwd, _lnrd_bwd)
