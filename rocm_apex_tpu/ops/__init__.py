"""Pallas/Mosaic kernel layer shared by the whole framework.

TPU-native replacement for the reference's native kernel tier
(reference: csrc/ and apex/contrib/csrc/, SURVEY.md §2.7-2.8). Device
code is Pallas; there is no CUDA/HIP anywhere. The multi-tensor-apply
design (reference: csrc/multi_tensor_apply.cuh:16-147) becomes a
*packed-pytree* design: parameter pytrees are flattened into a handful of
dtype-segregated, lane-aligned flat buffers, and each "multi-tensor op"
is ONE pallas_call over the packed buffer instead of a chunked launch
over up-to-110-tensor argument packs.

Modules:
    packing       PackedTree: dtype-bucketed (rows, 128*8) buffers
    multi_tensor  scale / axpby / l2norm (+per-tensor) fused ops
    optim_kernels adam / sgd / adagrad / novograd / lamb update kernels
    layer_norm    row-tiled LN fwd/bwd
    softmax       scaled masked / causal softmax
    xentropy      label-smoothing softmax cross-entropy
    linear_xentropy  chunked fused LM-head + CE (logits never materialize)
    flash_attention  fused attention (contrib fmha/mha superseder)
    collective_matmul  ppermute-ring all-gather/reduce-scatter matmuls
                  (latency-hiding TP boundaries, arXiv 2305.06942)
"""

from rocm_apex_tpu.ops.packing import PackedTree, pack_tree, unpack_tree  # noqa: F401
from rocm_apex_tpu.ops import multi_tensor  # noqa: F401
