"""Fused ResNet-bottleneck kernels: BN-apply prologue + conv + BN-stats
epilogue, forward and backward.

The TPU counterpart of the reference's cudnn-frontend fused bottleneck
(reference: apex/contrib/bottleneck/bottleneck.py:112 runs the
1x1/3x3/1x1 conv-bn-relu chain on fused kernels built in
apex/contrib/csrc/bottleneck/bottleneck.cpp). The reason the kernels
exist is identical on both architectures: training-mode BatchNorm
otherwise forces each feature map through conv-write -> normalize-read
-> normalized-write -> conv-read, and the framework's own RN50 roofline
(BASELINE.md) shows XLA cannot fold the normalize into the *consuming*
conv's prologue — the step is pinned at ~93-97% of HBM peak moving
~36 GB. These kernels restore the once-in-once-out structure:

* forward: each conv reads the PREVIOUS conv's raw output, applies the
  BN scale/bias + ReLU per input channel while the tile is in VMEM
  (prologue), runs the conv on the MXU, and accumulates the per-channel
  sum/sum-of-squares of its own raw output (epilogue) so the next BN's
  statistics are free. Feature maps are written once (raw) and read
  once.
* backward: one kernel per conv fuses the dgrad matmul, the wgrad
  matmul, the BN-backward "finalize" of the incoming cotangent (a
  per-channel affine in y and the masked partial), the ReLU mask, and
  the two BN reductions (sum e, sum e*x_hat) the upstream finalize
  needs. The standalone elementwise+reduce passes of the autodiff
  graph disappear into prologues/epilogues.

1x1 convs are matmuls over the flattened pixel stream; the 3x3
(stride 1, SAME) runs nine shifted MXU dots per pixel chunk over an
overlapping window (chunk plus 8-aligned halo slivers assembled from
three Blocked specs), with validity masks covering image boundaries,
the W edges, and the flattened image-to-image seam. Stride-2 convs
(3 of 16 RN50 blocks) stay on the XLA path (models/resnet.py keeps
those blocks unfused).

BN backward math used throughout (batch statistics, as in training):
  out = g * x_hat + b,  x_hat = (y - mu) * rs
  e   = dL/dout (post-ReLU-mask where applicable)
  dg = sum(e * x_hat),  db = sum(e)
  dy = g*rs * (e - db/M - x_hat * dg/M)
     = k1*e + k2*y + k0   with k1 = g*rs, k2 = -g*rs^2*dg/M,
       k0 = -k1*db/M - k2*mu
so a finalize is three per-channel coefficient vectors applied while
the tile is already in VMEM for the matmul.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from rocm_apex_tpu.ops._pallas import pallas_call

__all__ = [
    "conv1x1_bn_act",
    "conv3x3_bn_act",
    "bn_coeffs",
    "bn_finalize_coeffs",
    "bottleneck_fused",
]

# Tunable block/VMEM knobs (module-level so the dev tuner can sweep
# them; the defaults are the measured-best on v5e). `vmem_limit`
# raises Mosaic's 16 MiB scoped-VMEM ceiling — v5e cores have far more
# physical VMEM and the conservative per-temp accounting of the 3x3
# kernels needs the headroom at useful chunk sizes.
config = {
    "mm_target": 4 * 1024 * 1024,    # (rows, width) tile budget, 1x1
    "mm_cap": 4096,
    "c3_fwd_target": 2 * 1024 * 1024,  # f32 accumulator budget, 3x3 fwd
    "c3_bwd_target": 1024 * 1024,      # f32 accumulator budget, 3x3 bwd
    "vmem_limit": 100 * 1024 * 1024,
}


def _compiler_params():
    if config["vmem_limit"] is None:
        return None
    from rocm_apex_tpu.utils.compat import tpu_compiler_params

    return tpu_compiler_params(vmem_limit_bytes=config["vmem_limit"])


def _row_block(m: int, k: int, n: int, itemsize: int = 2,
               cap: int = 0) -> int:
    """Pixel-row block for the 1x1 kernels: the largest divisor of M
    that keeps the widest (rows, max(K,N)) tile around ~1 MiB, so the
    full working set (x, y, dz f32, g f32, w, dw accumulator) stays
    well under VMEM. A divisor — not a pad — because zero-padded rows
    would pass through the ReLU prologue as relu(bias) != 0 and pollute
    the statistics epilogue."""
    width = max(k, n)
    cap = cap or config["mm_cap"]
    target = max(
        8, min(cap, config["mm_target"] // max(1, width * itemsize))
    )
    for bm in range((target // 8) * 8, 7, -8):
        if m % bm == 0:
            return bm
    if m <= 4096:
        return m
    raise ValueError(f"no row block divides M={m}")


# ---------------------------------------------------------------------------
# forward kernels
# ---------------------------------------------------------------------------


def _mm_fwd_kernel(prologue, stats, x_ref, *refs):
    refs = list(refs)
    if prologue:
        a_ref, b_ref = refs.pop(0), refs.pop(0)
    w_ref = refs.pop(0)
    y_ref = refs.pop(0)
    if stats:
        s1_ref, s2_ref = refs

    x = x_ref[...]
    if prologue:
        # bf16 apply (XLA-baseline-equivalent normalize numerics)
        x = jnp.maximum(x * a_ref[...].astype(x.dtype)
                        + b_ref[...].astype(x.dtype),
                        jnp.zeros((), x.dtype))
    acc = jax.lax.dot_general(
        x, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y_ref[...] = acc.astype(y_ref.dtype)
    if stats:
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        s1_ref[...] += jnp.sum(acc, axis=0, keepdims=True)
        s2_ref[...] += jnp.sum(acc * acc, axis=0, keepdims=True)


def conv1x1_bn_act(
    x2d: jnp.ndarray,
    w: jnp.ndarray,
    scale: Optional[jnp.ndarray] = None,
    bias: Optional[jnp.ndarray] = None,
    stats: bool = True,
) -> Tuple[jnp.ndarray, Optional[Tuple[jnp.ndarray, jnp.ndarray]]]:
    """y = relu(x*scale + bias) @ w over the flattened pixel stream.

    x2d: (M, K) raw upstream conv output (or the block input, in which
    case scale/bias are None and no activation is applied); w: (K, N).
    Returns y (M, N) in x's dtype plus, when `stats`, the per-channel
    (sum, sum_sq) of y in fp32 — the consumer derives BN statistics
    from these instead of re-reading y.
    """
    m, k = x2d.shape
    n = w.shape[1]
    prologue = scale is not None
    bm = _row_block(m, k, n)
    grid = m // bm

    row_x = pl.BlockSpec((bm, k), lambda i: (i, 0))
    row_y = pl.BlockSpec((bm, n), lambda i: (i, 0))
    vec_k = pl.BlockSpec((1, k), lambda i: (0, 0))
    vec_n = pl.BlockSpec((1, n), lambda i: (0, 0))
    full_w = pl.BlockSpec((k, n), lambda i: (0, 0))

    ins = [x2d]
    in_specs = [row_x]
    if prologue:
        ins += [scale.reshape(1, k).astype(jnp.float32),
                bias.reshape(1, k).astype(jnp.float32)]
        in_specs += [vec_k, vec_k]
    ins.append(w.astype(x2d.dtype))
    in_specs.append(full_w)

    out_specs = [row_y]
    out_shape = [jax.ShapeDtypeStruct((m, n), x2d.dtype)]
    if stats:
        out_specs += [vec_n, vec_n]
        out_shape += [jax.ShapeDtypeStruct((1, n), jnp.float32)] * 2

    outs = pallas_call(
        functools.partial(_mm_fwd_kernel, prologue, stats),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(),
    )(*ins)
    if stats:
        y, s1, s2 = outs
        return y, (s1[0], s2[0])
    return outs[0], None


def _offsets(w: int):
    return [dy * w + dx for dy in (-1, 0, 1) for dx in (-1, 0, 1)]


def _halo(w: int) -> int:
    # lowest multiple of 8 covering the w+1 pixel reach of a 3x3 tap
    # (halo slivers are sublane-dim blocks and must stay 8-aligned)
    return ((w + 1 + 7) // 8) * 8


def _pix_block(ptot: int, lo: int, c: int, cout: int,
               target_bytes: int = 256 * 1024) -> int:
    """Pixel chunk for the 3x3 kernels over the flattened (N*H*W, C)
    stream: the largest divisor of the total pixel count that is a
    multiple of the halo sliver `lo` and keeps the f32 accumulator and
    windows a few hundred KiB (whole 56x56 images OOM the 16 MiB
    scoped VMEM in backward). Falls back to the whole stream (grid of
    one, where the sliver alignment is moot) for tiny inputs."""
    width = max(c, cout)
    target = max(lo, min(ptot, target_bytes // max(1, width * 4)))
    for bp in range((target // lo) * lo, lo - 1, -lo):
        if ptot % bp == 0:
            return bp
    return ptot


def _win_specs(bp: int, lo: int, ptot: int, c: int):
    """Three Blocked specs assembling an overlapping window
    [j*bp - lo, j*bp + bp + lo) without Element low padding (Mosaic
    rejects it): a halo sliver before, the chunk, a sliver after.
    Edge chunks clamp the sliver index into range and read real-but-
    wrong rows — every tap that could touch them is masked with
    `where`, so the values never matter."""
    if bp % lo != 0 and bp != ptot:
        # the sliver index maps below assume bp is a multiple of lo
        # whenever grid > 1 (guaranteed by _pix_block's lo-stepped
        # search); a silent k=0 here would make BOTH slivers index
        # block 0 for every chunk — wrong windows, no error
        raise ValueError(
            f"_win_specs: chunk {bp} is neither a multiple of the halo "
            f"row-group {lo} nor the whole stream {ptot}"
        )
    k = bp // lo if bp % lo == 0 else 0
    last = max(0, -(-ptot // lo) - 1)

    def prev_ix(j):
        return (jnp.maximum(j * k - 1, 0), 0)

    def next_ix(j):
        return (jnp.minimum((j + 1) * k, last), 0)

    return [
        pl.BlockSpec((lo, c), prev_ix),
        pl.BlockSpec((bp, c), lambda j: (j, 0)),
        pl.BlockSpec((lo, c), next_ix),
    ]


def _window(prev_ref, main_ref, next_ref):
    return jnp.concatenate(
        [prev_ref[...], main_ref[...], next_ref[...]], axis=0
    )


def _tap_bits(ptot: int, hw: int, wid: int, bwd: bool) -> jnp.ndarray:
    """(ptot, 1) int32 constant: bit t set iff flat pixel p has a valid
    source at p+off_t — same image (no leakage across the flattened
    image seam), in range, and no W wraparound for the dx component.
    With `bwd`, bits 9..17 additionally carry the mirrored (dgrad)
    validity: a valid source at p-off_t seen through column -dx.

    Computed with jnp ops at trace time, so under jit it constant-folds
    into a stored buffer. This replaces per-tap integer div/mod inside
    the kernel — int division vectorizes catastrophically on the VPU
    (measured 2.7 of 3.5 ms in the layer1 forward kernel)."""
    p = jnp.arange(ptot, dtype=jnp.int32)
    r = p % hw           # position within the image
    col = p % wid
    bits = jnp.zeros((ptot,), jnp.int32)
    for t, off in enumerate(_offsets(wid)):
        dx = (t % 3) - 1
        v = (r + off >= 0) & (r + off < hw)
        if dx < 0:
            v &= col >= 1
        elif dx > 0:
            v &= col <= wid - 2
        bits = bits | (v.astype(jnp.int32) << t)
        if bwd:
            vd = (r - off >= 0) & (r - off < hw)
            if dx > 0:
                vd &= col >= 1
            elif dx < 0:
                vd &= col <= wid - 2
            bits = bits | (vd.astype(jnp.int32) << (9 + t))
    return bits.reshape(ptot, 1)


def _bit_mask(bits, t: int):
    return jax.lax.bitwise_and(bits, jnp.int32(1 << t)) > 0


def _conv3_fwd_kernel(
    prologue, stats, hw, wid, bp, lo,
    xp_ref, xm_ref, xn_ref, bits_ref, *refs
):
    refs = list(refs)
    if prologue:
        a_ref, b_ref = refs.pop(0), refs.pop(0)
    w_ref = refs.pop(0)
    y_ref = refs.pop(0)
    if stats:
        s1_ref, s2_ref = refs.pop(0), refs.pop(0)

    j = pl.program_id(0)
    # window rows [p0 - lo, p0 + bp + lo) of the flat pixel stream;
    # the edge slivers may hold clamped (wrong) rows and every tap
    # carries a precomputed validity bit applied with `where`
    u = _window(xp_ref, xm_ref, xn_ref)
    if prologue:
        # bf16 apply: same numerics as the XLA baseline's bf16
        # normalize; avoids f32 window temporaries (VPU-bound kernel)
        u = jnp.maximum(u * a_ref[...].astype(u.dtype)
                        + b_ref[...].astype(u.dtype),
                        jnp.zeros((), u.dtype))
    bits = bits_ref[...]

    acc = None
    for t, off in enumerate(_offsets(wid)):
        tap = u[lo + off: lo + off + bp]
        tap = jnp.where(_bit_mask(bits, t), tap, jnp.zeros_like(tap))
        d = jax.lax.dot_general(
            tap, w_ref[t], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = d if acc is None else acc + d
    y_ref[...] = acc.astype(y_ref.dtype)
    if stats:
        @pl.when(j == 0)
        def _():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        s1_ref[...] += jnp.sum(acc, axis=0, keepdims=True)
        s2_ref[...] += jnp.sum(acc * acc, axis=0, keepdims=True)


def conv3x3_bn_act(
    x: jnp.ndarray,
    w: jnp.ndarray,
    scale: Optional[jnp.ndarray] = None,
    bias: Optional[jnp.ndarray] = None,
    stats: bool = True,
) -> Tuple[jnp.ndarray, Optional[Tuple[jnp.ndarray, jnp.ndarray]]]:
    """3x3 stride-1 SAME conv with BN-apply+ReLU prologue and stats
    epilogue. x: (N, H, W, C) raw upstream output; w: (3, 3, C, Cout).

    Chunked over the flattened (N*H*W) pixel stream: each grid step
    assembles an overlapping window (halo slivers + chunk) and runs
    the nine taps as shifted (bp, C) @ (C, Cout) MXU dots; validity
    masks give SAME zero padding at image edges and stop leakage
    across the flattened image seam.
    """
    nimg, hgt, wid, cin = x.shape
    cout = w.shape[-1]
    hw = hgt * wid
    ptot = nimg * hw
    lo = _halo(wid)
    prologue = scale is not None
    bp = _pix_block(ptot, lo, cin, cout,
                    target_bytes=config["c3_fwd_target"])
    x2 = x.reshape(ptot, cin)

    chunk_y = pl.BlockSpec((bp, cout), lambda j: (j, 0))
    vec_k = pl.BlockSpec((1, cin), lambda j: (0, 0))
    vec_n = pl.BlockSpec((1, cout), lambda j: (0, 0))
    full_w = pl.BlockSpec((9, cin, cout), lambda j: (0, 0, 0))

    ins = [x2, x2, x2, _tap_bits(ptot, hw, wid, bwd=False)]
    in_specs = list(_win_specs(bp, lo, ptot, cin))
    in_specs.append(pl.BlockSpec((bp, 1), lambda j: (j, 0)))
    if prologue:
        ins += [scale.reshape(1, cin).astype(jnp.float32),
                bias.reshape(1, cin).astype(jnp.float32)]
        in_specs += [vec_k, vec_k]
    ins.append(w.reshape(9, cin, cout).astype(x.dtype))
    in_specs.append(full_w)

    out_specs = [chunk_y]
    out_shape = [jax.ShapeDtypeStruct((ptot, cout), x.dtype)]
    if stats:
        out_specs += [vec_n, vec_n]
        out_shape += [jax.ShapeDtypeStruct((1, cout), jnp.float32)] * 2

    outs = pallas_call(
        functools.partial(
            _conv3_fwd_kernel, prologue, stats, hw, wid, bp, lo
        ),
        grid=(ptot // bp,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(),
    )(*ins)
    y = outs[0].reshape(nimg, hgt, wid, cout)
    if stats:
        return y, (outs[1][0], outs[2][0])
    return y, None


# ---------------------------------------------------------------------------
# BN coefficient plumbing (tiny per-channel XLA math between kernels)
# ---------------------------------------------------------------------------


def bn_coeffs(sums, count, gamma, beta, eps):
    """(mean, rs, scale, bias) from a kernel's (sum, sum_sq) epilogue:
    the prologue form u = relu(y*scale + bias) of gamma*x_hat + beta."""
    s1, s2 = sums
    mean = s1 / count
    var = jnp.maximum(s2 / count - mean * mean, 0.0)
    rs = jax.lax.rsqrt(var + eps)
    scale = gamma * rs
    bias = beta - mean * scale
    return mean, rs, scale, bias


def bn_finalize_coeffs(r1, r2, mean, rs, gamma, count):
    """(k1, k2, k0) of dy = k1*e + k2*y + k0 (see module docstring)."""
    k1 = gamma * rs
    k2 = -k1 * rs * r2 / count
    k0 = -k1 * r1 / count - k2 * mean
    return k1, k2, k0


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _need_x(prologue: bool, reduce_out: bool, wgrad: bool) -> bool:
    # x feeds the prologue (u and the s>0 mask), the wgrad operand, and
    # the x_hat of the reduction epilogue; plain dgrad never reads it
    return prologue or reduce_out or wgrad


def _mm_bwd_kernel(
    premask, finalize, prologue, reduce_out, wgrad, dgrad,
    *refs,
):
    """Merged backward for a 1x1 conv y = w . u(x).

    In grid order the refs are:
      e      (bm, N)  incoming cotangent (masked partial, or raw dz
                      when `premask`/`finalize` are off)
      z      (bm, N)  [premask]  block output for the ReLU mask
      y      (bm, N)  [finalize] this conv's raw output
      k1/k2/k0 (1,N)  [finalize] BN-backward coefficients
      x      (bm, K)  [prologue or reduce_out or dgrad-mask] upstream raw
      a/b    (1, K)   [prologue] BN apply for u(x) and the s>0 mask
      mu/rs  (1, K)   [reduce_out] x_hat of the upstream BN
      w      (K, N)
    outputs:
      g      (bm, K)  [dgrad] masked upstream cotangent (or plain dx)
      dw     (K, N)   [wgrad] accumulated
      r1/r2  (1, K)   [reduce_out] accumulated BN reductions
    """
    refs = list(refs)
    e_ref = refs.pop(0)
    z_ref = refs.pop(0) if premask else None
    if finalize:
        y_ref = refs.pop(0)
        k1_ref, k2_ref, k0_ref = refs.pop(0), refs.pop(0), refs.pop(0)
    x_ref = refs.pop(0) if _need_x(prologue, reduce_out, wgrad) else None
    if prologue:
        a_ref, b_ref = refs.pop(0), refs.pop(0)
    if reduce_out:
        mu_ref, rs_ref = refs.pop(0), refs.pop(0)
    w_ref = refs.pop(0)
    g_ref = refs.pop(0) if dgrad else None
    dw_ref = refs.pop(0) if wgrad else None
    if reduce_out:
        r1_ref, r2_ref = refs.pop(0), refs.pop(0)

    i = pl.program_id(0)
    dt = e_ref.dtype
    e = e_ref[...]
    if premask:
        # f32 compare: Mosaic has no bf16 cmpf
        e = jnp.where(
            z_ref[...].astype(jnp.float32) > 0, e, jnp.zeros((), dt)
        )
    if finalize:
        dzc = (
            k1_ref[...].astype(dt) * e
            + k2_ref[...].astype(dt) * y_ref[...]
            + k0_ref[...].astype(dt)
        )
    else:
        dzc = e

    if prologue:
        s = (
            x_ref[...].astype(jnp.float32) * a_ref[...] + b_ref[...]
        )
        u = jnp.maximum(s, 0.0).astype(dt)
    elif wgrad or dgrad:
        u = x_ref[...] if x_ref is not None else None

    if wgrad:
        @pl.when(i == 0)
        def _():
            dw_ref[...] = jnp.zeros_like(dw_ref)

        dw_ref[...] += jax.lax.dot_general(
            u, dzc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if dgrad:
        g = jax.lax.dot_general(
            dzc, w_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if prologue:
            g = jnp.where(s > 0, g, 0.0)
        g_ref[...] = g.astype(g_ref.dtype)
        if reduce_out:
            @pl.when(i == 0)
            def _():
                r1_ref[...] = jnp.zeros_like(r1_ref)
                r2_ref[...] = jnp.zeros_like(r2_ref)

            xhat = (
                x_ref[...].astype(jnp.float32) - mu_ref[...]
            ) * rs_ref[...]
            r1_ref[...] += jnp.sum(g, axis=0, keepdims=True)
            r2_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)


def conv1x1_bn_act_bwd(
    e: jnp.ndarray,
    w: jnp.ndarray,
    x: Optional[jnp.ndarray],
    z: Optional[jnp.ndarray] = None,
    y_fin: Optional[Tuple] = None,
    prologue: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    reduce_stats: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    wgrad: bool = True,
    dgrad: bool = True,
):
    """One fused backward pass for a 1x1 conv (see _mm_bwd_kernel).

    e: (M, N); w: (K, N); x: (M, K) upstream raw output (prologue
    recomputes u and the ReLU mask from it); z: (M, N) block output for
    the pre-mask; y_fin: (y_raw, k1, k2, k0) finalize inputs;
    reduce_stats: (mu, rs) of the upstream BN, enabling the r1/r2
    epilogue. Returns (g, dw, r1, r2) with None for disabled outputs.
    """
    m, n = e.shape
    k = w.shape[0]
    premask = z is not None
    finalize = y_fin is not None
    pro = prologue is not None
    red = reduce_stats is not None
    bm = _row_block(m, k, n)
    grid = m // bm

    row_e = pl.BlockSpec((bm, n), lambda i: (i, 0))
    row_x = pl.BlockSpec((bm, k), lambda i: (i, 0))
    vec_n = pl.BlockSpec((1, n), lambda i: (0, 0))
    vec_k = pl.BlockSpec((1, k), lambda i: (0, 0))
    full_w = pl.BlockSpec((k, n), lambda i: (0, 0))

    ins, in_specs = [e], [row_e]
    if premask:
        ins.append(z)
        in_specs.append(row_e)
    if finalize:
        y_raw, k1, k2, k0 = y_fin
        ins += [y_raw, k1.reshape(1, n), k2.reshape(1, n), k0.reshape(1, n)]
        in_specs += [row_e, vec_n, vec_n, vec_n]
    if _need_x(pro, red, wgrad):
        ins.append(x)
        in_specs.append(row_x)
    if pro:
        a, b = prologue
        ins += [a.reshape(1, k).astype(jnp.float32),
                b.reshape(1, k).astype(jnp.float32)]
        in_specs += [vec_k, vec_k]
    if red:
        mu, rs = reduce_stats
        ins += [mu.reshape(1, k), rs.reshape(1, k)]
        in_specs += [vec_k, vec_k]
    ins.append(w.astype(e.dtype))
    in_specs.append(full_w)

    out_specs, out_shape = [], []
    if dgrad:
        out_specs.append(row_x)
        out_shape.append(jax.ShapeDtypeStruct((m, k), e.dtype))
    if wgrad:
        out_specs.append(full_w)
        out_shape.append(jax.ShapeDtypeStruct((k, n), jnp.float32))
    if red:
        out_specs += [vec_k, vec_k]
        out_shape += [jax.ShapeDtypeStruct((1, k), jnp.float32)] * 2

    outs = list(pallas_call(
        functools.partial(
            _mm_bwd_kernel, premask, finalize, pro, red, wgrad, dgrad
        ),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(),
    )(*ins))
    g = outs.pop(0) if dgrad else None
    dw = outs.pop(0) if wgrad else None
    r1 = outs.pop(0)[0] if red else None
    r2 = outs.pop(0)[0] if red else None
    return g, dw, r1, r2


def _conv3_bwd_kernel(
    finalize, hw, wid, bp, lo, *refs
):
    """Merged backward for the stride-1 3x3: finalize prologue, 9-tap
    wgrad + 9-tap dgrad (conv with flipped taps), ReLU mask and BN
    reductions for the upstream cotangent. All big inputs arrive as
    overlapping windows (sliver + chunk + sliver) — the finalize and
    prologue recompute on the halo rows is a few rows of VPU work per
    chunk."""
    refs = list(refs)
    e_win = [refs.pop(0), refs.pop(0), refs.pop(0)]
    bits_ref = refs.pop(0)
    if finalize:
        y_win = [refs.pop(0), refs.pop(0), refs.pop(0)]
        k1_ref, k2_ref, k0_ref = refs.pop(0), refs.pop(0), refs.pop(0)
    x_win = [refs.pop(0), refs.pop(0), refs.pop(0)]
    a_ref, b_ref = refs.pop(0), refs.pop(0)
    mu_ref, rs_ref = refs.pop(0), refs.pop(0)
    w_ref = refs.pop(0)
    g_ref = refs.pop(0)
    dw_ref = refs.pop(0)
    r1_ref, r2_ref = refs.pop(0), refs.pop(0)

    j = pl.program_id(0)
    bits = bits_ref[...]

    # finalized cotangent over the whole window (halo rows included:
    # the wgrad taps need dz at p, the dgrad taps at p - off)
    dt = e_win[0].dtype
    e = _window(*e_win)
    if finalize:
        dzw = (
            k1_ref[...].astype(dt) * e
            + k2_ref[...].astype(dt) * _window(*y_win)
            + k0_ref[...].astype(dt)
        )
    else:
        dzw = e
    dzc = dzw[lo:lo + bp]

    xw = _window(*x_win)
    uw = jnp.maximum(xw * a_ref[...].astype(dt)
                     + b_ref[...].astype(dt), jnp.zeros((), dt))

    @pl.when(j == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        r1_ref[...] = jnp.zeros_like(r1_ref)
        r2_ref[...] = jnp.zeros_like(r2_ref)

    g = None
    for t, off in enumerate(_offsets(wid)):
        # wgrad tap: dw[t] = sum_p u[p + off] * dz[p] over own rows p
        tap_u = uw[lo + off: lo + off + bp]
        tap_u = jnp.where(
            _bit_mask(bits, t), tap_u, jnp.zeros_like(tap_u)
        )
        dw_ref[t] += jax.lax.dot_general(
            tap_u, dzc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dgrad tap: g[q] += dz[q - off] @ w[t]^T for own rows q; the
        # pair (q-off, q) is the fwd pair (p, p+off), so validity is
        # the mirrored bit (source in-image, columns seen through -dx)
        tap_d = dzw[lo - off: lo - off + bp]
        tap_d = jnp.where(
            _bit_mask(bits, 9 + t), tap_d, jnp.zeros_like(tap_d)
        )
        d = jax.lax.dot_general(
            tap_d, w_ref[t], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        g = d if g is None else g + d

    # centre-slice ReLU mask from the bf16 u (u > 0 iff s > 0 away
    # from the measure-zero s == 0 boundary, where relu' := 0 anyway)
    uc = uw[lo:lo + bp].astype(jnp.float32)
    g = jnp.where(uc > 0, g, 0.0)
    g_ref[...] = g.astype(g_ref.dtype)
    x = xw[lo:lo + bp].astype(jnp.float32)
    xhat = (x - mu_ref[...]) * rs_ref[...]
    r1_ref[...] += jnp.sum(g, axis=0, keepdims=True)
    r2_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)


def conv3x3_bn_act_bwd(
    e: jnp.ndarray,
    w: jnp.ndarray,
    x: jnp.ndarray,
    y_fin: Optional[Tuple],
    prologue: Tuple[jnp.ndarray, jnp.ndarray],
    reduce_stats: Tuple[jnp.ndarray, jnp.ndarray],
):
    """Fused backward of conv3x3_bn_act. e: (N,H,W,Cout) masked partial
    (finalized in-kernel when y_fin=(y_raw,k1,k2,k0) given); x: the
    upstream raw (N,H,W,Cin). Returns (g, dw, r1, r2)."""
    nimg, hgt, wid, cout = e.shape
    cin = w.shape[2]
    hw = hgt * wid
    ptot = nimg * hw
    lo = _halo(wid)
    finalize = y_fin is not None
    bp = _pix_block(ptot, lo, cin, cout,
                    target_bytes=config["c3_bwd_target"])

    chunk_g = pl.BlockSpec((bp, cin), lambda j: (j, 0))
    vec_n = pl.BlockSpec((1, cout), lambda j: (0, 0))
    vec_k = pl.BlockSpec((1, cin), lambda j: (0, 0))
    full_w = pl.BlockSpec((9, cin, cout), lambda j: (0, 0, 0))

    e2 = e.reshape(ptot, cout)
    ins = [e2, e2, e2, _tap_bits(ptot, hw, wid, bwd=True)]
    in_specs = list(_win_specs(bp, lo, ptot, cout))
    in_specs.append(pl.BlockSpec((bp, 1), lambda j: (j, 0)))
    if finalize:
        y_raw, k1, k2, k0 = y_fin
        y2 = y_raw.reshape(ptot, cout)
        ins += [
            y2, y2, y2,
            k1.reshape(1, cout), k2.reshape(1, cout), k0.reshape(1, cout),
        ]
        in_specs += list(_win_specs(bp, lo, ptot, cout))
        in_specs += [vec_n, vec_n, vec_n]
    a, b = prologue
    mu, rs = reduce_stats
    x2 = x.reshape(ptot, cin)
    ins += [
        x2, x2, x2,
        a.reshape(1, cin).astype(jnp.float32),
        b.reshape(1, cin).astype(jnp.float32),
        mu.reshape(1, cin), rs.reshape(1, cin),
        w.reshape(9, cin, cout).astype(e.dtype),
    ]
    in_specs += list(_win_specs(bp, lo, ptot, cin))
    in_specs += [vec_k, vec_k, vec_k, vec_k, full_w]

    outs = pallas_call(
        functools.partial(_conv3_bwd_kernel, finalize, hw, wid, bp, lo),
        grid=(ptot // bp,),
        in_specs=in_specs,
        compiler_params=_compiler_params(),
        out_specs=[chunk_g, full_w, vec_k, vec_k],
        out_shape=[
            jax.ShapeDtypeStruct((ptot, cin), e.dtype),
            jax.ShapeDtypeStruct((9, cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cin), jnp.float32),
            jax.ShapeDtypeStruct((1, cin), jnp.float32),
        ],
    )(*ins)
    g, dw, r1, r2 = outs
    return (
        g.reshape(nimg, hgt, wid, cin),
        dw.reshape(3, 3, cin, cout),
        r1[0],
        r2[0],
    )


# ---------------------------------------------------------------------------
# whole-block orchestration (custom_vjp)
# ---------------------------------------------------------------------------
#
# The fused block is one differentiable op: forward chains the three
# conv kernels with BN coefficients threaded between them (plus the
# optional 1x1 downsample branch) and a single XLA elementwise tail for
# bn3 + residual + ReLU; backward hand-chains the merged kernels with
# the finalize coefficients computed from each kernel's reduction
# epilogue. Batch (mean, var) per BN are returned for running-stat
# updates and carry no gradient (matching torch BN semantics, where
# running statistics are buffers).


def _bneck_fwd_impl(eps, downsample, x, w1, g1, b1, w2, g2, b2,
                    w3, g3, b3, wd, gd, bd):
    nimg, hgt, wid, cin = x.shape
    m = nimg * hgt * wid
    cmid = w1.shape[-1]
    cout = w3.shape[-1]
    x2 = x.reshape(m, cin)

    y1, s1 = conv1x1_bn_act(x2, w1, stats=True)
    mu1, rs1, a1, c1 = bn_coeffs(s1, m, g1, b1, eps)
    y2, s2 = conv3x3_bn_act(
        y1.reshape(nimg, hgt, wid, cmid), w2, a1, c1, stats=True
    )
    mu2, rs2, a2, c2 = bn_coeffs(s2, m, g2, b2, eps)
    y2f = y2.reshape(m, cmid)
    y3, s3 = conv1x1_bn_act(y2f, w3, a2, c2, stats=True)
    mu3, rs3, a3, c3 = bn_coeffs(s3, m, g3, b3, eps)

    if downsample:
        yd, sd = conv1x1_bn_act(x2, wd, stats=True)
        mud, rsd, ad, cd = bn_coeffs(sd, m, gd, bd, eps)
        r = yd.astype(jnp.float32) * ad + cd
    else:
        yd = mud = rsd = None
        r = x2.astype(jnp.float32)

    z = jnp.maximum(
        y3.astype(jnp.float32) * a3 + c3 + r, 0.0
    ).astype(x.dtype)

    var = lambda s, mu: jnp.maximum(s[1] / m - mu * mu, 0.0)
    batch_stats = (
        (mu1, var(s1, mu1)),
        (mu2, var(s2, mu2)),
        (mu3, var(s3, mu3)),
        (mud, var(sd, mud)) if downsample else None,
    )
    saved = (
        x2, y1, y2f, y3, yd, z,
        (mu1, rs1), (mu2, rs2), (mu3, rs3),
        (mud, rsd) if downsample else None,
        (a1, c1), (a2, c2),
        w1, g1, w2, g2, w3, g3, wd, gd,
        (nimg, hgt, wid),
    )
    out = z.reshape(nimg, hgt, wid, cout)
    return (out, batch_stats), saved


def _bneck_bwd_impl(eps, downsample, saved, cts):
    dz_out, _ = cts  # batch_stats carry no gradient (running buffers)
    (x2, y1, y2f, y3, yd, z,
     st1, st2, st3, std,
     pro1, pro2,
     w1, g1, w2, g2, w3, g3, wd, gd,
     (nimg, hgt, wid)) = saved
    m = x2.shape[0]
    mu3, rs3 = st3

    dzz = dz_out.reshape(m, -1)
    # bn3 (and bn_d) reductions over the masked cotangent: one fused
    # XLA read of (dzz, z, y3[, yd]) — per-channel sums only
    p = jnp.where(z > 0, dzz.astype(jnp.float32), 0.0)
    r1_3 = jnp.sum(p, axis=0)
    xhat3 = (y3.astype(jnp.float32) - mu3) * rs3
    r2_3 = jnp.sum(p * xhat3, axis=0)
    k3 = bn_finalize_coeffs(r1_3, r2_3, mu3, rs3, g3, m)

    e2, dw3, r1_2, r2_2 = conv1x1_bn_act_bwd(
        dzz, w3, y2f, z=z, y_fin=(y3, *k3),
        prologue=pro2, reduce_stats=st2,
    )
    k2 = bn_finalize_coeffs(r1_2, r2_2, *st2, g2, m)

    cmid = w1.shape[-1]
    e1, dw2, r1_1, r2_1 = conv3x3_bn_act_bwd(
        e2.reshape(nimg, hgt, wid, cmid), w2,
        y1.reshape(nimg, hgt, wid, cmid),
        y_fin=(y2f.reshape(nimg, hgt, wid, cmid), *k2),
        prologue=pro1, reduce_stats=st1,
    )
    k1 = bn_finalize_coeffs(r1_1, r2_1, *st1, g1, m)

    dx_main, dw1, _, _ = conv1x1_bn_act_bwd(
        e1.reshape(m, cmid), w1, x2, y_fin=(y1, *k1),
    )

    if downsample:
        mud, rsd = std
        xhatd = (yd.astype(jnp.float32) - mud) * rsd
        r2_d = jnp.sum(p * xhatd, axis=0)
        kd = bn_finalize_coeffs(r1_3, r2_d, mud, rsd, gd, m)
        dx_res, dwd, _, _ = conv1x1_bn_act_bwd(
            dzz, wd, x2, z=z, y_fin=(yd, *kd),
        )
        dgd, dbd = r2_d, r1_3
    else:
        dx_res = p.astype(dx_main.dtype)
        dwd = dgd = dbd = None

    dx = (dx_main.astype(jnp.float32) + dx_res.astype(jnp.float32))
    dx = dx.reshape(nimg, hgt, wid, -1).astype(dz_out.dtype)
    return (
        dx,
        dw1, r2_1, r1_1,
        dw2, r2_2, r1_2,
        dw3, r2_3, r1_3,
        dwd, dgd, dbd,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def bottleneck_fused(eps, downsample, x, w1, g1, b1, w2, g2, b2,
                     w3, g3, b3, wd=None, gd=None, bd=None):
    """Training-mode fused bottleneck: z = relu(bn3(conv3(relu(bn2(
    conv2(relu(bn1(conv1(x)))))))) + residual), all convs stride 1,
    computed by the fused Pallas kernels above.

    x: (N, H, W, Cin) NHWC; w1 (Cin, Cmid), w2 (3, 3, Cmid, Cmid),
    w3 (Cmid, Cout); g*/b* the BN scale/offset vectors; (wd, gd, bd)
    the optional 1x1 downsample projection. Returns (z, batch_stats)
    where batch_stats is ((mean, var) per BN, biased var) for running
    average updates — no gradient flows through it.
    """
    out, _ = _bneck_fwd_impl(eps, downsample, x, w1, g1, b1, w2, g2,
                             b2, w3, g3, b3, wd, gd, bd)
    return out


def _bneck_vjp_fwd(eps, downsample, x, w1, g1, b1, w2, g2, b2,
                   w3, g3, b3, wd, gd, bd):
    return _bneck_fwd_impl(eps, downsample, x, w1, g1, b1, w2, g2, b2,
                           w3, g3, b3, wd, gd, bd)


bottleneck_fused.defvjp(_bneck_vjp_fwd, _bneck_bwd_impl)
