"""Device-side paged KV-cache primitives: block-table indirection math.

The paged cache (rocm_apex_tpu/inference/paging.py) replaces the
contiguous per-slot ``(num_slots, capacity, heads, head_dim)`` buffers
with fixed-size PAGES drawn from one shared pool — vLLM's
PagedAttention layout (arXiv 2309.06180) — so HBM scales with LIVE
tokens instead of ``slots × capacity``. This module owns the pure-jnp
transforms every consumer shares:

* ``paged_scatter`` / ``quantized_paged_scatter`` — the write path:
  tokens land at host-resolved ``(slot, position)`` destinations,
  routed through the ``(num_slots, pages_per_slot)`` page table to
  ``(page, offset)`` pool rows. Invalid destinations (padding slots,
  positions at/past capacity, unmapped table entries) carry the
  out-of-range page sentinel and are DROPPED by the scatter — a paged
  write can never clamp into a live (possibly SHARED) page the way the
  contiguous cache's dynamic_update_slice clamped at capacity.
* ``paged_view`` — the reference read path: gather the pool through
  the table back into the contiguous ``(num_slots, capacity, …)``
  layout (+ dequantization). The jnp attention fallback reads this
  view, which makes paged-vs-contiguous parity BIT-exact there; the
  flash path instead gathers page tiles in-kernel
  (`flash_attention_decode_paged`) and never materializes it.
* ``paged_fork`` — the copy-on-write primitive: duplicate one page's
  rows (pool + scales) so a prefix-sharing slot can diverge without
  touching its sharers' bytes.

int8 quantization is per-(page, head): one fp32 scale covers a page's
``page_size`` tokens per head (the EQuARX per-chunk-scale design,
arXiv 2506.17615, applied to cache bytes — halves both HBM and the
decode DMA). Scales only GROW; when a write raises a page's scale the
page's existing int8 rows are requantized in the same scatter
(``q' = round(q · old/new)``, ratio ≤ 1 so no overflow), so every row
of a page is always consistent with the page's current scale.

Pool layout is ``(num_pages, heads, page_size, head_dim)`` — heads
AHEAD of the page rows (the ISSUE sketch writes (num_pages, page_size,
heads, head_dim)) so a single (page, head) tile is the pool's LAST TWO
dims: the Pallas paged-decode kernel fetches ``(1, 1, page_size,
head_dim)`` blocks, which Mosaic tiles natively, instead of a
sublane-degenerate ``(1, page_size, 1, head_dim)`` slice.

This module lives in ``ops`` (not ``inference``) so the model layer
can share it: models/gpt.py consumes any cache pytree without
importing the inference package (the PR-1 layering rule), but both
sides must agree byte-for-byte on the scatter/view math.
"""

from typing import Optional, Tuple

import jax.numpy as jnp

__all__ = [
    "paged_destinations",
    "paged_scatter",
    "quantized_paged_scatter",
    "paged_view",
    "paged_fork",
]


def paged_destinations(
    page_table: jnp.ndarray,
    slots: jnp.ndarray,
    positions: jnp.ndarray,
    page_size: int,
    num_pages: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Resolve per-token ``(slot, position)`` to ``(page, offset)``.

    Invalid tokens — slot outside ``[0, num_slots)``, position outside
    ``[0, capacity)``, or an unmapped table entry (the host fills
    unallocated entries with ``num_pages``) — come back with
    ``page == num_pages``: the scatter sentinel ``mode="drop"``
    discards. Valid ``page`` values are clamped into range only via
    the table contents themselves (the host owns the mapping).
    """
    num_slots, pages_per_slot = page_table.shape
    capacity = pages_per_slot * page_size
    valid = (
        (slots >= 0)
        & (slots < num_slots)
        & (positions >= 0)
        & (positions < capacity)
    )
    sl = jnp.clip(slots, 0, num_slots - 1)
    pos = jnp.clip(positions, 0, capacity - 1)
    pages = jnp.where(valid, page_table[sl, pos // page_size], num_pages)
    return pages, pos % page_size


def paged_scatter(
    pool: jnp.ndarray,
    page_table: jnp.ndarray,
    slots: jnp.ndarray,
    positions: jnp.ndarray,
    x: jnp.ndarray,
) -> jnp.ndarray:
    """Scatter ``x`` (tokens, heads, head_dim) into the pool at the
    table-resolved destinations. Exact (no quantization): the stored
    bytes equal the contiguous cache's ``.at[slot, pos].set`` bytes,
    which is what makes paged-vs-contiguous greedy parity exact."""
    num_pages, _, page_size, _ = pool.shape
    pages, offs = paged_destinations(
        page_table, slots, positions, page_size, num_pages
    )
    return pool.at[pages, :, offs].set(x.astype(pool.dtype), mode="drop")


def quantized_paged_scatter(
    pool: jnp.ndarray,
    scale: jnp.ndarray,
    page_table: jnp.ndarray,
    slots: jnp.ndarray,
    positions: jnp.ndarray,
    x: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """int8 write with per-(page, head) fp32 scales.

    ``pool`` int8 ``(num_pages, heads, page_size, head_dim)``;
    ``scale`` fp32 ``(num_pages, heads)``; ``x`` float
    ``(tokens, heads, head_dim)``. Three phases, all one fused scatter
    chain under jit:

    1. scatter-max the incoming per-token absmax into the touched
       pages' scales (scales never shrink — a page's scale is the max
       absmax it has ever held);
    2. requantize the touched pages' EXISTING rows by
       ``old_scale / new_scale`` (1.0 exactly for untouched pages and
       for touched pages whose scale did not move, so the common
       steady-state write rewrites bytes unchanged);
    3. quantize the new tokens with the new scale and scatter them.

    Duplicate destination pages (several chunk tokens in one page) are
    safe: every duplicate writes the identical requantized content.
    Invalid tokens are dropped by the same sentinel as `paged_scatter`.
    """
    num_pages, _, page_size, _ = pool.shape
    pages, offs = paged_destinations(
        page_table, slots, positions, page_size, num_pages
    )
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)  # (tokens, heads)
    contrib = jnp.zeros_like(scale).at[pages].max(absmax, mode="drop")
    new_scale = jnp.maximum(scale, contrib / 127.0)
    safe = jnp.where(new_scale > 0.0, new_scale, 1.0)
    ratio = jnp.where(new_scale > 0.0, scale / safe, 1.0)

    pg = jnp.clip(pages, 0, num_pages - 1)
    old_rows = pool[pg].astype(jnp.float32)  # (tokens, heads, ps, hd)
    resc = jnp.round(old_rows * ratio[pg][:, :, None, None])
    pool = pool.at[pages].set(resc.astype(pool.dtype), mode="drop")
    q = jnp.clip(jnp.round(xf / safe[pg][:, :, None]), -127.0, 127.0)
    pool = pool.at[pages, :, offs].set(q.astype(pool.dtype), mode="drop")
    return pool, new_scale


def paged_view(
    pool: jnp.ndarray,
    page_table: jnp.ndarray,
    scale: Optional[jnp.ndarray] = None,
    out_dtype=None,
) -> jnp.ndarray:
    """Gather the pool through the table into the CONTIGUOUS layout:
    ``(num_slots, pages_per_slot · page_size, heads, head_dim)``.

    The jnp reference attention reads this (bit-identical to the
    contiguous cache when unquantized; dequantized to fp32 when
    ``scale`` is given). Unmapped entries (sentinel ``num_pages``)
    clamp onto the last pool page — harmless garbage, because every
    attention read is bounded by the slot's live length. This
    materializes O(slots·capacity) — the FLASH path must not call it
    (`flash_attention_decode_paged` gathers page tiles in-kernel);
    it exists for the jnp fallback and for tests/debug dumps.
    """
    num_pages, heads, page_size, head_dim = pool.shape
    num_slots, pages_per_slot = page_table.shape
    tab = jnp.clip(page_table, 0, num_pages - 1)
    g = pool[tab]  # (slots, P, heads, ps, hd)
    if scale is not None:
        g = g.astype(jnp.float32) * scale[tab][:, :, :, None, None]
    g = g.transpose(0, 1, 3, 2, 4).reshape(
        num_slots, pages_per_slot * page_size, heads, head_dim
    )
    if out_dtype is not None:
        g = g.astype(out_dtype)
    return g


def paged_fork(
    pool: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
) -> jnp.ndarray:
    """Copy page ``src``'s rows onto page ``dst`` — the device half of
    copy-on-write (the host remaps the forking slot's table entry and
    the ref counts). ``src``/``dst`` may be traced scalars: one
    compiled program serves every fork."""
    return pool.at[dst].set(pool[src])
