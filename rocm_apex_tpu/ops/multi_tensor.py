"""Fused multi-tensor ops over packed buffers: scale, axpby, L2 norm.

TPU-native equivalents of the amp_C multi-tensor kernels
(reference: csrc/multi_tensor_scale_kernel.cu:30-136 `ScaleFunctor`,
csrc/multi_tensor_axpby_kernel.cu, csrc/multi_tensor_l2norm_kernel.cu:29-370).
Each op is one Pallas call per dtype-group buffer; the reference's
device-side ``noop_flag`` overflow buffer becomes a per-grid-block flag
array OR-reduced on the outside — the whole thing stays inside jit, so
there is no D2H sync (the reference syncs at scaler.py:206-209).

Tree-level wrappers (`scale`, `axpby`, `l2norm`) pack/unpack around the
packed primitives; the optimizer layer calls the packed forms directly
to avoid re-packing.
"""

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from rocm_apex_tpu.ops._pallas import (
    DirectOutRef,
    DirectRef,
    kernel_dtype,
    on_tpu,
    pallas_call,
)
from rocm_apex_tpu.ops.packing import (
    WIDTH,
    PackedTree,
    group_segment_ids,
    pack_tree,
    respec,
    unpack_tree,
)

__all__ = [
    "scale_packed",
    "scale",
    "scale_sumsq_packed",
    "axpby_packed",
    "axpby",
    "l2norm_packed",
    "l2norm",
    "row_sumsq",
]

BLOCK_ROWS = 64  # 64x1024 fp32 = 256 KiB per buffer block in VMEM


def _grid(rows: int) -> int:
    assert rows % BLOCK_ROWS == 0, f"packed rows {rows} not {BLOCK_ROWS}-aligned"
    return rows // BLOCK_ROWS


def _vmem_spec():
    return pl.BlockSpec((BLOCK_ROWS, WIDTH), lambda i: (i, 0))


def _smem_scalar_spec():
    return pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)


def _flag_out_spec():
    return pl.BlockSpec((1, 1), lambda i: (i, 0), memory_space=pltpu.SMEM)




# ---------------------------------------------------------------------------
# scale: out = in * scale, with fused non-finite probe
# ---------------------------------------------------------------------------


def _scale_kernel(x_ref, s_ref, out_ref, flag_ref):
    x = x_ref[...].astype(jnp.float32) * s_ref[0, 0]
    flag_ref[0, 0] = jnp.logical_not(jnp.isfinite(x).all()).astype(jnp.int32)
    out_ref[...] = x.astype(out_ref.dtype)


def _scale_buffer(buf, s, out_dtype):
    rows = buf.shape[0]
    grid = _grid(rows)
    buf = buf.astype(kernel_dtype(buf.dtype))
    kd_out = kernel_dtype(out_dtype)
    if not on_tpu():
        # direct whole-buffer run of the same kernel body (the grid is
        # a row partition; see DirectRef) — skips the interpreter's
        # per-block slicing on the CPU harness
        o, f = DirectOutRef(kd_out), DirectOutRef(jnp.int32)
        _scale_kernel(DirectRef(buf), DirectRef(s), o, f)
        return o.value.astype(out_dtype), f.value > 0
    out, flags = pallas_call(
        _scale_kernel,
        grid=(grid,),
        in_specs=[_vmem_spec(), _smem_scalar_spec()],
        out_specs=[_vmem_spec(), _flag_out_spec()],
        out_shape=[
            jax.ShapeDtypeStruct((rows, WIDTH), kd_out),
            jax.ShapeDtypeStruct((grid, 1), jnp.int32),
        ],
    )(buf, s)
    return out.astype(out_dtype), flags.sum() > 0


def scale_packed(
    packed: PackedTree, scale_val, out_dtype=None
) -> Tuple[PackedTree, jnp.ndarray]:
    """out = packed * scale; returns (out, found_inf).

    Semantics of `multi_tensor_scale` + noop_flag
    (reference: csrc/multi_tensor_scale_kernel.cu:30-136): the flag trips
    on any non-finite produced value and the caller decides whether to
    discard the result (a `lax.cond`/`where` instead of the reference's
    kernel-side early-out).
    """
    s = jnp.asarray(scale_val, jnp.float32).reshape(1, 1)
    outs, infs = [], []
    for buf, g in zip(packed.buffers, packed.spec.groups):
        od = jnp.dtype(out_dtype).name if out_dtype is not None else g.dtype
        out, inf = _scale_buffer(buf, s, od)
        outs.append(out)
        infs.append(inf)
    found_inf = jnp.stack(infs).any() if infs else jnp.asarray(False)
    return PackedTree(outs, respec(packed.spec, out_dtype)), found_inf


def scale(tree: Any, scale_val, out_dtype=None) -> Tuple[Any, jnp.ndarray]:
    """Tree-level `multi_tensor_scale`: returns (scaled_tree, found_inf)."""
    packed, found_inf = scale_packed(pack_tree(tree), scale_val, out_dtype)
    return unpack_tree(packed), found_inf


# ---------------------------------------------------------------------------
# scale + sumsq: out = in * scale, fused non-finite probe AND per-row sum of
# squares of the scaled values — the unscale/probe/grad-norm phase of the
# packed optimizer step in ONE read of each buffer.
# ---------------------------------------------------------------------------


def _scale_sumsq_kernel(x_ref, s_ref, out_ref, flag_ref, rsq_ref):
    x = x_ref[...].astype(jnp.float32) * s_ref[0, 0]
    flag_ref[0, 0] = jnp.logical_not(jnp.isfinite(x).all()).astype(jnp.int32)
    out_ref[...] = x.astype(out_ref.dtype)
    rsq_ref[...] = jnp.sum(x * x, axis=1, keepdims=True)


def _scale_sumsq_buffer(buf, s, out_dtype):
    rows = buf.shape[0]
    grid = _grid(rows)
    buf = buf.astype(kernel_dtype(buf.dtype))
    kd_out = kernel_dtype(out_dtype)
    if not on_tpu():
        o = DirectOutRef(kd_out)
        f = DirectOutRef(jnp.int32)
        r = DirectOutRef(jnp.float32)
        _scale_sumsq_kernel(DirectRef(buf), DirectRef(s), o, f, r)
        return o.value.astype(out_dtype), f.value > 0, r.value
    out, flags, rsq = pallas_call(
        _scale_sumsq_kernel,
        grid=(grid,),
        in_specs=[_vmem_spec(), _smem_scalar_spec()],
        out_specs=[
            _vmem_spec(),
            _flag_out_spec(),
            pl.BlockSpec((BLOCK_ROWS, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, WIDTH), kd_out),
            jax.ShapeDtypeStruct((grid, 1), jnp.int32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
    )(buf, s)
    return out.astype(out_dtype), flags.sum() > 0, rsq


def scale_sumsq_packed(
    packed: PackedTree, scale_val, out_dtype=None
) -> Tuple[PackedTree, jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """out = packed * scale; returns (out, found_inf, per_group_row_sumsq).

    The scaler-unscale half-step of the packed optimizer
    (reference: multi_tensor_scale + multi_tensor_l2norm back to back,
    csrc/multi_tensor_scale_kernel.cu + csrc/multi_tensor_l2norm_kernel.cu)
    collapsed into a single pass: each dtype buffer is read once and
    yields the unscaled values, the non-finite flag, AND the (rows, 1)
    partial sums of squares the global-grad-norm clip consumes. The
    row-aligned layout keeps the row sums segmentable into per-tensor
    norms downstream (`l2norm_packed`).
    """
    s = jnp.asarray(scale_val, jnp.float32).reshape(1, 1)
    outs, infs, rsqs = [], [], []
    for buf, g in zip(packed.buffers, packed.spec.groups):
        od = jnp.dtype(out_dtype).name if out_dtype is not None else g.dtype
        out, inf, rsq = _scale_sumsq_buffer(buf, s, od)
        outs.append(out)
        infs.append(inf)
        rsqs.append(rsq)
    found_inf = jnp.stack(infs).any() if infs else jnp.asarray(False)
    return (
        PackedTree(outs, respec(packed.spec, out_dtype)),
        found_inf,
        tuple(rsqs),
    )


# ---------------------------------------------------------------------------
# axpby: out = a*x + b*y, fused non-finite probe
# ---------------------------------------------------------------------------


def _axpby_kernel(x_ref, y_ref, a_ref, b_ref, out_ref, flag_ref):
    out = (
        x_ref[...].astype(jnp.float32) * a_ref[0, 0]
        + y_ref[...].astype(jnp.float32) * b_ref[0, 0]
    )
    flag_ref[0, 0] = jnp.logical_not(jnp.isfinite(out).all()).astype(jnp.int32)
    out_ref[...] = out.astype(out_ref.dtype)


def axpby_packed(
    x: PackedTree, y: PackedTree, a, b, out_dtype=None
) -> Tuple[PackedTree, jnp.ndarray]:
    """out = a*x + b*y over packed buffers; returns (out, found_inf).

    The grad-accumulation merge kernel (reference:
    csrc/multi_tensor_axpby_kernel.cu, used by scaler.py:173-187).
    """
    if x.spec.groups != y.spec.groups:
        raise ValueError(
            "axpby_packed requires x and y packed under the same spec; "
            f"got {x.spec.groups} vs {y.spec.groups}"
        )
    a = jnp.asarray(a, jnp.float32).reshape(1, 1)
    b = jnp.asarray(b, jnp.float32).reshape(1, 1)
    outs, infs = [], []
    for xb, yb, g in zip(x.buffers, y.buffers, x.spec.groups):
        od = jnp.dtype(out_dtype).name if out_dtype is not None else g.dtype
        rows = xb.shape[0]
        grid = _grid(rows)
        xb = xb.astype(kernel_dtype(xb.dtype))
        yb = yb.astype(kernel_dtype(yb.dtype))
        kd_out = kernel_dtype(od)
        if not on_tpu():
            o, f = DirectOutRef(kd_out), DirectOutRef(jnp.int32)
            _axpby_kernel(
                DirectRef(xb), DirectRef(yb), DirectRef(a), DirectRef(b),
                o, f,
            )
            outs.append(o.value.astype(od))
            infs.append(f.value > 0)
            continue
        out, flags = pallas_call(
            _axpby_kernel,
            grid=(grid,),
            in_specs=[
                _vmem_spec(),
                _vmem_spec(),
                _smem_scalar_spec(),
                _smem_scalar_spec(),
            ],
            out_specs=[_vmem_spec(), _flag_out_spec()],
            out_shape=[
                jax.ShapeDtypeStruct((rows, WIDTH), kd_out),
                jax.ShapeDtypeStruct((grid, 1), jnp.int32),
            ],
        )(xb, yb, a, b)
        outs.append(out.astype(od))
        infs.append(flags.sum() > 0)
    found_inf = jnp.stack(infs).any() if infs else jnp.asarray(False)
    return PackedTree(outs, respec(x.spec, out_dtype)), found_inf


def axpby(x: Any, y: Any, a, b) -> Tuple[Any, jnp.ndarray]:
    """Tree-level axpby: returns (a*x + b*y, found_inf)."""
    px = pack_tree(x)
    py = pack_tree(y, px.spec)
    packed, found_inf = axpby_packed(px, py, a, b)
    return unpack_tree(packed), found_inf


# ---------------------------------------------------------------------------
# l2norm: global + optional per-tensor norms
# ---------------------------------------------------------------------------


def _rowsum_sq_kernel(x_ref, out_ref):
    x = x_ref[...].astype(jnp.float32)
    out_ref[...] = jnp.sum(x * x, axis=1, keepdims=True)


def row_sumsq(buf) -> jnp.ndarray:
    rows = buf.shape[0]
    grid = _grid(rows)
    buf = buf.astype(kernel_dtype(buf.dtype))
    if not on_tpu():
        o = DirectOutRef(jnp.float32)
        _rowsum_sq_kernel(DirectRef(buf), o)
        return o.value
    return pallas_call(
        _rowsum_sq_kernel,
        grid=(grid,),
        in_specs=[_vmem_spec()],
        out_specs=pl.BlockSpec((BLOCK_ROWS, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, 1), jnp.float32),
    )(buf)


def l2norm_packed(
    packed: PackedTree, per_tensor: bool = False
) -> Tuple[jnp.ndarray, Optional[Tuple[jnp.ndarray, ...]]]:
    """Global L2 norm (and per-tensor norms) of a packed pytree.

    Two-stage design like the reference (per-chunk partials then cleanup,
    csrc/multi_tensor_l2norm_kernel.cu:198-370): the Pallas stage reduces
    each 1024-wide row to a partial sum of squares; per-tensor norms fall
    out as a segmented row reduction thanks to the row-aligned layout
    (rows never straddle tensors, ops/packing.py).

    Returns (global_norm, per_group_tensor_norms or None); per-group
    results are arrays of per-tensor norms ordered like
    `spec.groups[k].leaf_specs`.
    """
    total = jnp.asarray(0.0, jnp.float32)
    per_group = []
    for buf, group in zip(packed.buffers, packed.spec.groups):
        row_sq = row_sumsq(buf)[:, 0]
        total = total + row_sq.sum()
        if per_tensor:
            seg = jnp.asarray(group_segment_ids(group))
            sums = jax.ops.segment_sum(
                row_sq, seg, num_segments=len(group.leaf_specs) + 1
            )[: len(group.leaf_specs)]
            per_group.append(jnp.sqrt(sums))
    return jnp.sqrt(total), tuple(per_group) if per_tensor else None


def l2norm(tree: Any, per_tensor: bool = False):
    """Tree-level L2 norm; per_tensor returns norms as a matching pytree."""
    packed = pack_tree(tree)
    global_norm, per_group = l2norm_packed(packed, per_tensor=per_tensor)
    if not per_tensor:
        return global_norm, None
    leaves = [None] * packed.spec.n_leaves
    for norms, group in zip(per_group, packed.spec.groups):
        for j, i in enumerate(group.leaf_indices):
            leaves[i] = norms[j]
    return global_norm, jax.tree_util.tree_unflatten(packed.spec.treedef, leaves)
