"""Quantized ring collectives: int8 ppermute hops with fp32 scale sidecars.

EQuARX (PAPERS.md: arXiv 2506.17615) shows an int8-quantized allreduce
built inside XLA loses negligible model quality while cutting wire
bytes ~4x. This module is that idea on the PR-3 ppermute-ring skeleton
(ops/collective_matmul.py): `ring_reduce_scatter` / `ring_all_gather` /
`ring_all_reduce` decompose the lax collective into axis_size-1
neighbour hops, and with ``comm_dtype="int8"`` every hop's payload is
symmetrically quantized to int8 with one fp32 scale per trailing-axis
row riding as a sidecar ppermute (two transfers per hop: the int8 body
and the tiny fp32 scale column — on a ``(rows, 1024)`` packed buffer
the sidecar is 0.4% of the fp32 payload).

Quantization contract (the properties the tests pin):

* **Deterministic round-to-nearest-even.** ``jnp.round`` is IEEE RTNE
  on every backend, and scale = amax/127 is a pure function of the
  payload — two replicas quantizing the same values produce bitwise
  identical ``(q, scale)`` pairs, and every replica dequantizing the
  same pair produces bitwise identical fp32. The all-gather therefore
  keeps params REPLICATED in the strict sense: each rank's own shard
  comes back as dequant(quant(shard)), the same array every other rank
  reconstructs.
* **fp32 hop accumulators.** The reduce-scatter quantizes only what
  moves: the rotating partial sum is re-quantized per hop (its value
  changes each hop), dequantized on arrival into fp32, and the local
  contribution is added in full fp32. The gather quantizes each shard
  ONCE and rotates the ``(q, scale)`` pair unchanged — re-quantizing a
  dequantized payload is idempotent (the row max dequantizes exactly
  back to the scale), so a single quantization error per element is
  the total error, it never compounds around the ring.
* **Graceful degradation.** Axis unbound or size 1 -> identity (what
  the lax collective computes over a 1-axis). A ``chunk`` that does
  not tile the shard -> the plain full-precision lax collective,
  bitwise identical to not using this module at all. Rows that do not
  tile the axis -> plain lax collective (reduce-scatter shares lax's
  divisibility requirement; `ring_all_reduce` falls back to
  ``lax.psum`` which has none).
* **Overflow transparency.** Non-finite inputs saturate (inf -> ±127
  at scale 1.0), so a quantized wire does NOT propagate inf/nan across
  ranks. Callers that need overflow detection must probe BEFORE the
  collective — exactly where contrib/optimizers/distributed.py runs
  its fused unscale+found_inf pass, and why that ordering is load-
  bearing for ``comm_dtype="int8"``.

The rings run under `jax.named_scope` ("qring_rs" / "qring_ag") so
monitor/audit.py can attribute the ppermute hop storm to its ring:
a quantized ring costs 2·m·(axis_size-1) ppermute equations (payload +
sidecar per hop, m chunks) where the lax collective costs one equation
— the audit's per-dtype byte split is what shows the int8 win.

Not differentiable-by-design: quantization has zero gradient almost
everywhere. The TP-boundary layers use ops/collective_matmul.py's
custom_vjp rings (which take the same ``comm_dtype`` knob); this
module serves the optimizer dataflow, which is never differentiated.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from rocm_apex_tpu.utils.compat import axis_size

__all__ = [
    "COMM_DTYPES",
    "check_comm_dtype",
    "quantize_int8",
    "dequantize_int8",
    "ring_reduce_scatter",
    "ring_all_gather",
    "ring_all_reduce",
]

COMM_DTYPES = ("fp32", "int8")


def check_comm_dtype(comm_dtype: str) -> str:
    if comm_dtype not in COMM_DTYPES:
        raise ValueError(
            f"comm_dtype must be one of {COMM_DTYPES}, got {comm_dtype!r}"
        )
    return comm_dtype


def _bound_axis_size(axis_name) -> Optional[int]:
    """Static size of `axis_name`, or None when unbound."""
    try:
        return axis_size(axis_name)
    except NameError:
        return None


def _ring_chunks(rows: int, chunk: Optional[int]) -> Optional[int]:
    """Pieces per shard, or None when `chunk` does not tile `rows`."""
    if chunk is None:
        return 1
    if chunk <= 0 or rows % chunk:
        return None
    return rows // chunk


def quantize_int8(x):
    """Symmetric per-row int8 quantization of a hop payload.

    One fp32 scale per trailing-axis row: scale = amax(|row|)/127, q =
    RTNE(x/scale) clipped to ±127. All-zero (or non-finite-max) rows
    take scale 1.0 so dequantization is exact zeros there. Returns
    ``(q int8, scale fp32 with trailing dim 1)``.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(jnp.isfinite(amax) & (amax > 0.0), amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _hop(payload, axis_name, perm, quantized):
    """One ring hop of `payload` (fp32): quantize, move, dequantize."""
    if not quantized:
        return jax.lax.ppermute(payload, axis_name, perm)
    q, s = quantize_int8(payload)
    q = jax.lax.ppermute(q, axis_name, perm)
    s = jax.lax.ppermute(s, axis_name, perm)
    return dequantize_int8(q, s)


def ring_reduce_scatter(x, axis_name, *, dim=0, comm_dtype="int8",
                        chunk=None):
    """``psum_scatter(x, scatter_dimension=dim, tiled=True)`` as a
    ppermute ring with (optionally) int8-quantized hop payloads.

    Each rank feeds its full ``x``; the output is this rank's row block
    ``x.shape[dim] / axis_size``, summed over the axis. The rotating
    partial sum accumulates in fp32 and is (re)quantized only for the
    wire; rank r's block sums contributions in the fixed ring order
    r+1, r+2, ..., r — deterministic, so replicas agree bitwise on
    shared blocks and the fp32 ring is reproducible against an
    order-matched reference.

    Degradations (see module docstring): unbound/size-1 axis ->
    identity; non-tiling ``chunk`` or rows -> plain ``lax.psum_scatter``.
    """
    check_comm_dtype(comm_dtype)
    n = _bound_axis_size(axis_name)
    if n is None or n == 1:
        return x
    rows_full = x.shape[dim]
    m = _ring_chunks(rows_full // n, chunk) if rows_full % n == 0 else None
    if m is None:
        return jax.lax.psum_scatter(
            x, axis_name, scatter_dimension=dim, tiled=True
        )
    quantized = comm_dtype == "int8"
    idx = jax.lax.axis_index(axis_name)
    rows = rows_full // n
    piece_rows = rows // m
    # accumulators advance to rank+1 each hop and must end at home
    perm = [(j, (j + 1) % n) for j in range(n)]
    acc = [None] * m
    with jax.named_scope("qring_rs"):
        for i in range(n):
            # the block this rank touches now reaches its owner in the
            # remaining n-1-i hops
            dst = (idx + n - 1 - i) % n
            for j in range(m):
                piece = jax.lax.dynamic_slice_in_dim(
                    x, dst * rows + j * piece_rows, piece_rows, axis=dim
                ).astype(jnp.float32)
                if acc[j] is None:
                    acc[j] = piece
                else:
                    acc[j] = _hop(acc[j], axis_name, perm, quantized) + piece
    out = acc[0] if m == 1 else jnp.concatenate(acc, axis=dim)
    return out.astype(x.dtype)


def ring_all_gather(x, axis_name, *, dim=0, comm_dtype="int8", chunk=None):
    """``all_gather(x, axis=dim, tiled=True)`` as a ppermute ring with
    (optionally) int8-quantized hop payloads.

    With ``comm_dtype="int8"`` every shard — including the local one —
    is quantized ONCE and the ``(q, scale)`` pairs rotate unchanged;
    every rank dequantizes the same pairs, so the gathered array is
    bitwise identical on all ranks (the replicated-params invariant the
    ZeRO gather needs). The fp32 ring moves payloads untouched and is
    bitwise equal to ``lax.all_gather``.
    """
    check_comm_dtype(comm_dtype)
    n = _bound_axis_size(axis_name)
    if n is None or n == 1:
        return x
    m = _ring_chunks(x.shape[dim], chunk)
    if m is None:
        return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)
    quantized = comm_dtype == "int8"
    idx = jax.lax.axis_index(axis_name)
    rows = x.shape[dim]
    piece_rows = rows // m
    # receive from rank+1: hop i leaves rank (idx + i)'s shard resident
    perm = [(j, (j - 1) % n) for j in range(n)]
    out_shape = x.shape[:dim] + (n * rows,) + x.shape[dim + 1:]
    out = jnp.zeros(out_shape, x.dtype)
    with jax.named_scope("qring_ag"):
        pieces = []
        for j in range(m):
            piece = jax.lax.slice_in_dim(
                x, j * piece_rows, (j + 1) * piece_rows, axis=dim
            )
            pieces.append(quantize_int8(piece) if quantized else piece)
        for i in range(n):
            src = (idx + i) % n
            nxt = []
            for j, payload in enumerate(pieces):
                if quantized:
                    q, s = payload
                    if i + 1 < n:
                        nxt.append((
                            jax.lax.ppermute(q, axis_name, perm),
                            jax.lax.ppermute(s, axis_name, perm),
                        ))
                    landed = dequantize_int8(q, s, x.dtype)
                else:
                    if i + 1 < n:
                        nxt.append(
                            jax.lax.ppermute(payload, axis_name, perm)
                        )
                    landed = payload
                out = jax.lax.dynamic_update_slice_in_dim(
                    out, landed, src * rows + j * piece_rows, axis=dim
                )
            if nxt:
                pieces = nxt
    return out


def ring_all_reduce(x, axis_name, *, dim=0, comm_dtype="int8", chunk=None):
    """``psum(x)`` as ring reduce-scatter + ring all-gather (the
    classic two-phase ring allreduce). Falls back to ``lax.psum`` when
    the rows do not tile the axis."""
    check_comm_dtype(comm_dtype)
    n = _bound_axis_size(axis_name)
    if n is None or n == 1:
        return x
    if x.shape[dim] % n:
        return jax.lax.psum(x, axis_name)
    shard = ring_reduce_scatter(
        x, axis_name, dim=dim, comm_dtype=comm_dtype, chunk=chunk
    )
    return ring_all_gather(
        shard, axis_name, dim=dim, comm_dtype=comm_dtype, chunk=chunk
    )
