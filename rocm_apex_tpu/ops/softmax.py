"""Scaled masked / causal softmax Pallas kernels.

TPU-native equivalent of the megatron fused softmax kernels
(reference: csrc/megatron/scaled_masked_softmax*.{cpp,h,cu},
scaled_upper_triang_masked_softmax*, surfaced through
apex/transformer/functional/fused_softmax.py:21-93). Unlike the
reference's warp-tiled kernels, there is NO seqlen ≤ 2048 ceiling
(reference: fused_softmax.py:160) — blocks tile the row dimension and
the key dimension stays resident in VMEM (up to ~16K keys fp32).

Math is fp32 with max-subtraction. Mask fills mirror the reference
kernels: the padding-mask variant fills with -10000
(scaled_masked_softmax.h) while the causal variant fills with -inf
(scaled_upper_triang_masked_softmax.h) so future positions get exactly
zero probability. Backward is the fused softmax-grad y*(dy - Σ dy·y)
(reference backward kernels), wired via custom_vjp.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from rocm_apex_tpu.ops._pallas import kernel_dtype, pad_rows, pallas_call, row_block

__all__ = ["scaled_upper_triang_masked_softmax", "scaled_masked_softmax"]

MASK_FILL = -10000.0  # reference: scaled_masked_softmax.h applies -10000


def _block_rows(sk: int) -> int:
    return row_block(sk)


def _pad_axis(x, axis, mult):
    return pad_rows(x, mult, axis=axis)


# ---------------------------------------------------------------------------
# causal (upper-triangular masked)
# ---------------------------------------------------------------------------


def _causal_fwd_kernel(scale, block, sq, x_ref, y_ref):
    s = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32) * scale  # (1, block, sk)
    sk = x.shape[-1]
    row = s * block + jax.lax.broadcasted_iota(jnp.int32, (1, block, sk), 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, block, sk), 2)
    # causal: -inf gives future positions exactly zero probability
    # (reference scaled_upper_triang_masked_softmax.h); the row padding
    # beyond sq uses a finite fill so padded rows don't produce 0/0 NaNs
    x = jnp.where(col > row, -jnp.inf, x)
    x = jnp.where(row >= sq, MASK_FILL, x)
    x = x - jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x)
    y_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _softmax_bwd_kernel(scale, y_ref, dy_ref, dx_ref):
    y = y_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    s = jnp.sum(y * dy, axis=-1, keepdims=True)
    dx_ref[...] = (scale * y * (dy - s)).astype(dx_ref.dtype)


def _causal_fwd_impl(x, scale):
    b, sq, sk = x.shape
    block = _block_rows(sk)
    xp = _pad_axis(x, 1, block)
    sqp = xp.shape[1]
    grid = (b, sqp // block)
    y = pallas_call(
        functools.partial(_causal_fwd_kernel, scale, block, sq),
        grid=grid,
        in_specs=[pl.BlockSpec((1, block, sk), lambda i, s: (i, s, 0))],
        out_specs=pl.BlockSpec((1, block, sk), lambda i, s: (i, s, 0)),
        out_shape=jax.ShapeDtypeStruct(xp.shape, kernel_dtype(x.dtype)),
    )(xp.astype(kernel_dtype(x.dtype)))
    return y[:, :sq].astype(x.dtype)


def _softmax_bwd_impl(y, dy, scale):
    shape = y.shape
    sk = shape[-1]
    y2 = y.reshape(-1, sk)
    dy2 = dy.reshape(-1, sk)
    block = _block_rows(sk)
    y2 = _pad_axis(y2, 0, block)
    dy2 = _pad_axis(dy2, 0, block)
    rows = y2.shape[0]
    dx = pallas_call(
        functools.partial(_softmax_bwd_kernel, scale),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, sk), lambda i: (i, 0)),
            pl.BlockSpec((block, sk), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, sk), kernel_dtype(y.dtype)),
    )(y2.astype(kernel_dtype(y.dtype)), dy2.astype(kernel_dtype(dy.dtype)))
    n = 1
    for d in shape[:-1]:
        n *= d
    return dx[:n].reshape(shape).astype(y.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scaled_upper_triang_masked_softmax(x, scale):
    """softmax(scale·x) with causal masking on (b, sq, sk) inputs.

    Semantics of `ScaledUpperTriangMaskedSoftmax`
    (reference: apex/transformer/functional/fused_softmax.py:21-64 +
    csrc/megatron/scaled_upper_triang_masked_softmax.h), without the
    seqlen ceiling.
    """
    return _causal_fwd_impl(x, scale)


def _causal_vjp_fwd(x, scale):
    y = _causal_fwd_impl(x, scale)
    return y, y


def _causal_vjp_bwd(scale, y, dy):
    return (_softmax_bwd_impl(y, dy, scale),)


scaled_upper_triang_masked_softmax.defvjp(_causal_vjp_fwd, _causal_vjp_bwd)


# ---------------------------------------------------------------------------
# padding-masked
# ---------------------------------------------------------------------------


def _masked_fwd_kernel(scale, x_ref, m_ref, y_ref):
    x = x_ref[...].astype(jnp.float32) * scale  # (1, 1, block, sk)
    masked = m_ref[...] != 0
    x = jnp.where(masked, MASK_FILL, x)
    x = x - jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x)
    y_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _masked_fwd_impl(x, mask, scale):
    b, h, sq, sk = x.shape
    if mask.shape != (b, 1, sq, sk):
        mask = jnp.broadcast_to(mask, (b, 1, sq, sk))
    block = _block_rows(sk)
    xp = _pad_axis(x, 2, block)
    mp = _pad_axis(mask.astype(jnp.int32), 2, block)
    sqp = xp.shape[2]
    grid = (b, h, sqp // block)
    y = pallas_call(
        functools.partial(_masked_fwd_kernel, scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block, sk), lambda i, j, s: (i, j, s, 0)),
            pl.BlockSpec((1, 1, block, sk), lambda i, j, s: (i, 0, s, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block, sk), lambda i, j, s: (i, j, s, 0)),
        out_shape=jax.ShapeDtypeStruct(xp.shape, kernel_dtype(x.dtype)),
    )(xp.astype(kernel_dtype(x.dtype)), mp)
    return y[:, :, :sq].astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def scaled_masked_softmax(x, mask, scale):
    """softmax(scale·x masked_fill mask) on (b, np, sq, sk) inputs.

    Semantics of `ScaledMaskedSoftmax` (reference:
    apex/transformer/functional/fused_softmax.py:67-93 +
    csrc/megatron/scaled_masked_softmax.h): `mask` is boolean with True =
    masked-out, broadcast over heads from (b, 1, sq, sk).
    """
    return _masked_fwd_impl(x, mask, scale)


def _masked_vjp_fwd(x, mask, scale):
    y = _masked_fwd_impl(x, mask, scale)
    return y, y


def _masked_vjp_bwd(scale, y, dy):
    return _softmax_bwd_impl(y, dy, scale), None


scaled_masked_softmax.defvjp(_masked_vjp_fwd, _masked_vjp_bwd)
