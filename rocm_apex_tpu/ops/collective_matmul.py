"""Latency-hiding collective matmuls for the tensor-parallel boundaries.

The Megatron-style TP layer pays a blocking collective at every
Column/RowParallel edge: `all_gather(x) @ W` first moves the whole
activation over ICI, then starts the MXU; `psum(x @ W)` finishes the
matmul before the first byte moves. XLA cannot fix this on its own —
operator fusion stops at dot boundaries (PAPERS.md: arXiv 2301.13062),
so the gathered operand and the pre-reduce product always materialize
between the collective and the dot. The fix is the decomposed
computation-collective schedule of arXiv 2305.06942: split the
collective into a `ppermute` ring of shard-sized (or finer, see
``chunk``) pieces and issue each hop's transfer next to a partial
matmul that does not depend on it, so the ICI transfer of piece i+1
rides under the MXU time of piece i.

Two ops, duals of each other (each is the other's backward):

* `all_gather_matmul(x, w, axis)` — ``all_gather(x, rows) @ w`` where
  ``x`` is the local rows-shard ``(..., rows_local, k)``: the resident
  shard multiplies into its output slot while the ring rotates the
  next shard in.
* `matmul_reduce_scatter(x, w, axis)` — ``psum_scatter(x @ w, rows)``
  where ``x`` holds full rows ``(..., rows, k_local)``: partial
  products accumulate into a rotating fp32 accumulator that lands on
  its destination rank after the last hop — the product is consumed
  piecewise and the full ``(..., rows, n)`` pre-reduce tensor never
  exists.

Both are `jax.custom_vjp`: the backward overlaps the transposed
collective the same way (d/dx of an all-gather-matmul IS a
matmul-reduce-scatter with ``wᵀ``, and vice versa; dW re-rotates the
saved operand instead of materializing the gather). Partial products
accumulate in fp32 regardless of input dtype (bf16 inputs hit the MXU,
sums stay fp32 until the final cast). Both degrade to the plain `lax`
collective + dot when the axis is unbound, ``axis_size == 1``, or
``chunk`` does not tile the shard — same numerics, no ring.

``comm_dtype="int8"`` (ops/quantized_collectives.py; EQuARX, arXiv
2506.17615) quantizes the ring hop payloads: the gather rings quantize
each rotating piece ONCE (per-row fp32 scales ride a sidecar ppermute)
and dequantize on arrival for the dot, so the int8-gather-matmul
equals ``dequant(int8(all_gather(x))) @ w`` slot-for-slot; the
reduce-scatter ring re-quantizes its rotating fp32 accumulator per hop
and adds the local partial product in full fp32. The backward rings
stay exact transposes of each other at the SAME comm dtype (dx of an
int8 gather-matmul is an int8 matmul-reduce-scatter with ``wᵀ``); the
degradation paths stay full-precision plain collectives. This is the
sequence-parallel entry/exit knob — opt-in, activation-quantization
noise is ~1% per hop payload row, acceptable for SP boundary
activations, not for logits.

The rows axis is ``-2`` (the flattened-token axis of a ``(rows, h)``
activation, or the sequence axis of ``(b, s, h)``); the contraction is
the last axis against ``w``'s first.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from rocm_apex_tpu.ops.quantized_collectives import (
    check_comm_dtype,
    dequantize_int8,
    quantize_int8,
)
from rocm_apex_tpu.utils.compat import axis_size

__all__ = ["all_gather_matmul", "matmul_reduce_scatter"]


def _bound_axis_size(axis_name) -> Optional[int]:
    """Static size of `axis_name`, or None when unbound (tp=1 / GSPMD
    usage outside shard_map)."""
    try:
        return axis_size(axis_name)
    except NameError:
        return None


def _mm(a, b):
    """fp32-accumulating matmul; inputs stay in their storage dtype so
    bf16 operands take the MXU fast path."""
    return jnp.matmul(a, b, preferred_element_type=jnp.float32)


def _out_dtype(x, w):
    return jnp.promote_types(x.dtype, w.dtype)


def _ring_chunks(rows: int, chunk: Optional[int]) -> Optional[int]:
    """Pieces per shard, or None when `chunk` does not tile `rows`
    (the caller then falls back to the plain collective)."""
    if chunk is None:
        return 1
    if chunk <= 0 or rows % chunk:
        return None
    return rows // chunk


# -- all_gather_matmul -------------------------------------------------


def _plain_ag_mm(x, w, axis_name):
    n = _bound_axis_size(axis_name)
    if n is not None and n > 1:
        x = jax.lax.all_gather(x, axis_name, axis=x.ndim - 2, tiled=True)
    return _mm(x, w).astype(_out_dtype(x, w))


def _rotating_pieces(x, m, chunk, ax, comm_dtype):
    """Split a gather-ring operand into its rotating payloads: raw
    slices for fp32, `(q, scale)` pairs — quantized ONCE — for int8."""
    pieces = []
    for j in range(m):
        piece = jax.lax.slice_in_dim(x, j * chunk, (j + 1) * chunk, axis=ax)
        pieces.append(
            quantize_int8(piece) if comm_dtype == "int8" else piece
        )
    return pieces


def _rotate_and_land(payload, axis_name, perm, rotate, comm_dtype, dtype):
    """One gather-ring hop: forward the payload (when ``rotate``) and
    return (next_payload_or_None, landed array in ``dtype``)."""
    if comm_dtype == "int8":
        q, s = payload
        nxt = None
        if rotate:
            nxt = (
                jax.lax.ppermute(q, axis_name, perm),
                jax.lax.ppermute(s, axis_name, perm),
            )
        return nxt, dequantize_int8(q, s, dtype)
    nxt = jax.lax.ppermute(payload, axis_name, perm) if rotate else None
    return nxt, payload


def _ring_ag_mm(x, w, axis_name, m, comm_dtype="fp32"):
    """Ring all-gather fused with the matmul: at hop i the resident
    shard (originally rank ``idx + i``'s) multiplies into its output
    slot, piece by piece, while each piece already permutes onward for
    hop i+1 — the transfer hides under the neighbouring dots."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    rows = x.shape[-2]
    chunk = rows // m
    ax = x.ndim - 2
    # receive from rank+1: hop i leaves rank (idx + i)'s shard resident
    perm = [(j, (j - 1) % n) for j in range(n)]
    out = jnp.zeros(
        x.shape[:-2] + (n * rows, w.shape[-1]), _out_dtype(x, w)
    )
    pieces = _rotating_pieces(x, m, chunk, ax, comm_dtype)
    for i in range(n):
        src = (idx + i) % n
        nxt = []
        for j, payload in enumerate(pieces):
            # issue the transfer BEFORE this piece's dot: XLA's
            # async collective-permute runs under the MXU work
            fwd, piece = _rotate_and_land(
                payload, axis_name, perm, i + 1 < n, comm_dtype, x.dtype
            )
            if fwd is not None:
                nxt.append(fwd)
            part = _mm(piece, w).astype(out.dtype)
            out = jax.lax.dynamic_update_slice_in_dim(
                out, part, src * rows + j * chunk, axis=ax
            )
        if nxt:
            pieces = nxt
    return out


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def all_gather_matmul(x, w, axis_name, chunk=None, comm_dtype="fp32"):
    """``all_gather(x, axis=-2) @ w`` with the gather decomposed into a
    ppermute ring whose hops overlap the partial matmuls.

    Args:
      x: local rows-shard ``(..., rows_local, k)``.
      w: ``(k, n)`` — this rank's weight shard (column-parallel).
      axis_name: mesh axis to gather over.
      chunk: rows per ring piece (must tile ``rows_local``; None = one
        piece per shard). A non-tiling chunk falls back to the plain
        ``lax.all_gather`` + dot.
      comm_dtype: "fp32" (default) moves hop payloads untouched;
        "int8" quantizes each rotating piece once with per-row fp32
        scale sidecars (module docstring). Degradation paths stay
        full-precision.

    Returns ``(..., axis_size * rows_local, n)``. The gathered ``x``
    never materializes on the ring path.
    """
    check_comm_dtype(comm_dtype)
    n = _bound_axis_size(axis_name)
    if n is None or n == 1:
        return _mm(x, w).astype(_out_dtype(x, w))
    m = _ring_chunks(x.shape[-2], chunk)
    if m is None:
        return _plain_ag_mm(x, w, axis_name)
    return _ring_ag_mm(x, w, axis_name, m, comm_dtype)


def _ag_mm_fwd(x, w, axis_name, chunk, comm_dtype):
    return all_gather_matmul(x, w, axis_name, chunk, comm_dtype), (x, w)


def _ring_dw_from_gather(x, dy, axis_name, m, comm_dtype="fp32"):
    """dW = all_gather(x)ᵀ @ dy without materializing the gather: the
    saved local shard re-rotates and each hop contracts against its
    own slice of the cotangent."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    rows = x.shape[-2]
    chunk = rows // m
    ax = x.ndim - 2
    perm = [(j, (j - 1) % n) for j in range(n)]
    dw = jnp.zeros(x.shape[-1:] + dy.shape[-1:], jnp.float32)
    pieces = _rotating_pieces(x, m, chunk, ax, comm_dtype)
    for i in range(n):
        src = (idx + i) % n
        nxt = []
        for j, payload in enumerate(pieces):
            fwd, piece = _rotate_and_land(
                payload, axis_name, perm, i + 1 < n, comm_dtype, x.dtype
            )
            if fwd is not None:
                nxt.append(fwd)
            dy_piece = jax.lax.dynamic_slice_in_dim(
                dy, src * rows + j * chunk, chunk, axis=ax
            )
            dw = dw + jnp.einsum(
                "...rk,...rn->kn", piece, dy_piece,
                preferred_element_type=jnp.float32,
            )
        if nxt:
            pieces = nxt
    return dw


def _ag_mm_bwd(axis_name, chunk, comm_dtype, res, dy):
    x, w = res
    n = _bound_axis_size(axis_name)
    if n is None or n == 1:
        dx = _mm(dy, w.swapaxes(-1, -2)).astype(x.dtype)
        dw = jnp.einsum(
            "...rk,...rn->kn", x, dy, preferred_element_type=jnp.float32
        ).astype(w.dtype)
        return dx, dw
    m = _ring_chunks(x.shape[-2], chunk)
    if m is None:
        # plain-collective fallback: transposed collectives, no ring
        dx = jax.lax.psum_scatter(
            _mm(dy, w.swapaxes(-1, -2)), axis_name,
            scatter_dimension=dy.ndim - 2, tiled=True,
        ).astype(x.dtype)
        xg = jax.lax.all_gather(x, axis_name, axis=x.ndim - 2, tiled=True)
        dw = jnp.einsum(
            "...rk,...rn->kn", xg, dy, preferred_element_type=jnp.float32
        ).astype(w.dtype)
        return dx, dw
    # the transposed gather IS a matmul-reduce-scatter: same ring, same
    # overlap, same comm dtype, wᵀ as the operand
    dx = _ring_mm_rs(
        dy, w.swapaxes(-1, -2), axis_name, m, comm_dtype
    ).astype(x.dtype)
    dw = _ring_dw_from_gather(x, dy, axis_name, m, comm_dtype).astype(
        w.dtype
    )
    return dx, dw


all_gather_matmul.defvjp(_ag_mm_fwd, _ag_mm_bwd)


# -- matmul_reduce_scatter ---------------------------------------------


def _plain_mm_rs(x, w, axis_name):
    y = _mm(x, w)
    n = _bound_axis_size(axis_name)
    if n is not None and n > 1:
        y = jax.lax.psum_scatter(
            y, axis_name, scatter_dimension=y.ndim - 2, tiled=True
        )
    return y.astype(_out_dtype(x, w))


def _acc_hop(acc, axis_name, perm, comm_dtype):
    """One reduce-scatter-ring hop of the fp32 accumulator: int8 mode
    re-quantizes per hop (the value changes every hop), fp32 mode moves
    it untouched."""
    if comm_dtype == "int8":
        q, s = quantize_int8(acc)
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        return dequantize_int8(q, s)
    return jax.lax.ppermute(acc, axis_name, perm)


def _ring_mm_rs(x, w, axis_name, m, comm_dtype="fp32"):
    """Reduce-scatter fused with the matmul: a rotating fp32
    accumulator picks up each rank's partial product for one row block
    per hop and lands on the block's owner after the last hop. The
    full pre-reduce product never exists."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    rows_full = x.shape[-2]
    rows = rows_full // n
    chunk = rows // m
    ax = x.ndim - 2
    # accumulators advance to rank+1 each hop and must end at home
    perm = [(j, (j + 1) % n) for j in range(n)]
    acc = [None] * m
    for i in range(n):
        # the block this rank works on now reaches its owner in the
        # remaining n-1-i hops
        dst = (idx + n - 1 - i) % n
        for j in range(m):
            piece = jax.lax.dynamic_slice_in_dim(
                x, dst * rows + j * chunk, chunk, axis=ax
            )
            if acc[j] is not None:
                # rotate first, then add this rank's partial — the
                # permute of piece j hides under piece j+1's dot
                acc[j] = _acc_hop(acc[j], axis_name, perm, comm_dtype)
            part = _mm(piece, w)
            acc[j] = part if acc[j] is None else acc[j] + part
    return jnp.concatenate(acc, axis=ax).astype(_out_dtype(x, w))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def matmul_reduce_scatter(x, w, axis_name, chunk=None, comm_dtype="fp32"):
    """``psum_scatter(x @ w, axis=-2)`` with the reduction decomposed
    into a ppermute ring of accumulators overlapping the partial
    matmuls.

    Args:
      x: full-rows operand ``(..., rows, k_local)`` — this rank's
        contraction shard (row-parallel input).
      w: ``(k_local, n)`` — this rank's weight shard.
      axis_name: mesh axis to reduce-scatter over.
      chunk: rows per ring piece (must tile ``rows / axis_size``;
        None = one piece per destination block). A non-tiling chunk
        falls back to the plain dot + ``lax.psum_scatter``.
      comm_dtype: "fp32" (default) rotates the fp32 accumulator
        untouched; "int8" re-quantizes it per hop with per-row fp32
        scale sidecars (module docstring). Degradation paths stay
        full-precision.

    Returns the local row block ``(..., rows / axis_size, n)``, summed
    over the axis. Partial sums stay fp32 until the final cast.
    """
    check_comm_dtype(comm_dtype)
    n = _bound_axis_size(axis_name)
    if n is None or n == 1:
        return _mm(x, w).astype(_out_dtype(x, w))
    rows_full = x.shape[-2]
    if rows_full % n:
        raise ValueError(
            f"rows {rows_full} not divisible by axis size {n}"
        )
    m = _ring_chunks(rows_full // n, chunk)
    if m is None:
        return _plain_mm_rs(x, w, axis_name)
    return _ring_mm_rs(x, w, axis_name, m, comm_dtype)


def _mm_rs_fwd(x, w, axis_name, chunk, comm_dtype):
    return matmul_reduce_scatter(x, w, axis_name, chunk, comm_dtype), (x, w)


def _ring_dw_from_scatter(x, dy, axis_name, m, comm_dtype="fp32"):
    """dW = xᵀ @ all_gather(dy) without the gather: the local
    cotangent block rotates and contracts against the matching row
    slice of the saved full-rows operand."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    rows = dy.shape[-2]
    chunk = rows // m
    ax = dy.ndim - 2
    perm = [(j, (j - 1) % n) for j in range(n)]
    dw = jnp.zeros(x.shape[-1:] + dy.shape[-1:], jnp.float32)
    pieces = _rotating_pieces(dy, m, chunk, ax, comm_dtype)
    for i in range(n):
        src = (idx + i) % n
        nxt = []
        for j, payload in enumerate(pieces):
            fwd, piece = _rotate_and_land(
                payload, axis_name, perm, i + 1 < n, comm_dtype, dy.dtype
            )
            if fwd is not None:
                nxt.append(fwd)
            x_piece = jax.lax.dynamic_slice_in_dim(
                x, src * rows + j * chunk, chunk, axis=ax
            )
            dw = dw + jnp.einsum(
                "...rk,...rn->kn", x_piece, piece,
                preferred_element_type=jnp.float32,
            )
        if nxt:
            pieces = nxt
    return dw


def _mm_rs_bwd(axis_name, chunk, comm_dtype, res, dy):
    x, w = res
    n = _bound_axis_size(axis_name)
    if n is None or n == 1:
        dx = _mm(dy, w.swapaxes(-1, -2)).astype(x.dtype)
        dw = jnp.einsum(
            "...rk,...rn->kn", x, dy, preferred_element_type=jnp.float32
        ).astype(w.dtype)
        return dx, dw
    m = _ring_chunks(dy.shape[-2], chunk)
    if m is None:
        dyg = jax.lax.all_gather(
            dy, axis_name, axis=dy.ndim - 2, tiled=True
        )
        dx = _mm(dyg, w.swapaxes(-1, -2)).astype(x.dtype)
        dw = jnp.einsum(
            "...rk,...rn->kn", x, dyg, preferred_element_type=jnp.float32
        ).astype(w.dtype)
        return dx, dw
    # the transposed scatter IS an all-gather-matmul with wᵀ at the
    # same comm dtype
    dx = _ring_ag_mm(
        dy, w.swapaxes(-1, -2), axis_name, m, comm_dtype
    ).astype(x.dtype)
    dw = _ring_dw_from_scatter(x, dy, axis_name, m, comm_dtype).astype(
        w.dtype
    )
    return dx, dw


matmul_reduce_scatter.defvjp(_mm_rs_fwd, _mm_rs_bwd)
