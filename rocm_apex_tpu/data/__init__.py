"""Host-side input pipeline: ImageFolder + prefetching loader.

TPU-native rebuild of the reference flagship example's input machinery
(reference: examples/imagenet/main_amp.py — torchvision ImageFolder +
DataLoader(collate_fn=fast_collate) + data_prefetcher): directory
scanning, worker-thread decode, the native `fast_collate` batch
assembly (csrc/host_ops.cpp), and compute/transfer overlap via a
bounded prefetch queue + async `jax.device_put` (the analogue of the
reference's side-stream H2D copies).

Formats: JPEG/PNG/etc. through PIL (decode-bound — scale
``num_workers`` with host cores, exactly like the reference's
DataLoader workers), and raw ``.npy`` uint8 HWC arrays (decode-free —
IO/bandwidth-bound; the right format when the host is core-poor).

    ds = ImageFolder("/data/imagenet/train")
    for x_dev, y_dev in PrefetchLoader(ds, batch_size=128,
                                       image_size=224, rng=rng):
        ...  # x_dev already on device, normalized f32 NHWC

No torch dependency: decode gives uint8 HWC numpy, `fast_collate`
assembles + normalizes, `device_put` ships.
"""

import os
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from rocm_apex_tpu import _native

__all__ = ["ImageFolder", "PrefetchLoader", "IMAGENET_MEAN", "IMAGENET_STD"]

# torchvision's ImageNet normalization constants (the reference's
# main_amp.py mean/std, deferred into fast_collate)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp", ".npy")


class ImageFolder:
    """Directory-per-class dataset (torchvision ImageFolder layout).

    ``root/<class_name>/<image file>``; classes are the sorted
    directory names, labels their indices."""

    def __init__(self, root: str):
        self.root = root
        classes = sorted(
            d
            for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise ValueError(f"no class directories under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(_IMG_EXTS):
                    self.samples.append(
                        (os.path.join(cdir, fn), self.class_to_idx[c])
                    )
        if not self.samples:
            raise ValueError(f"no image files under {root!r}")

    def __len__(self) -> int:
        return len(self.samples)


def _decode(path: str, image_size: int, train_rng: Optional[np.random.RandomState]):
    """One sample -> uint8 HWC (image_size, image_size, 3).

    .npy loads raw (must already be HWC uint8; resized center-crop
    style if larger). Other extensions decode through PIL with the
    reference example's train transform (RandomResizedCrop-lite +
    horizontal flip) when ``train_rng`` is given, else resize+center
    crop."""
    if path.endswith(".npy"):
        arr = np.load(path)
        if arr.dtype != np.uint8 or arr.ndim != 3:
            raise ValueError(f"{path}: .npy samples must be uint8 HWC")
        h, w, _ = arr.shape
        if (h, w) != (image_size, image_size):
            top = (h - image_size) // 2
            left = (w - image_size) // 2
            if top < 0 or left < 0:
                raise ValueError(
                    f"{path}: {arr.shape} smaller than {image_size}"
                )
            arr = arr[top : top + image_size, left : left + image_size]
        if train_rng is not None and train_rng.rand() < 0.5:
            arr = arr[:, ::-1]
        return np.ascontiguousarray(arr)

    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        if train_rng is not None:
            # RandomResizedCrop-lite: random scale in [0.7, 1.0] of the
            # short side, random position, then resize; then flip
            w, h = im.size
            short = min(w, h)
            crop = int(short * (0.7 + 0.3 * train_rng.rand()))
            left = train_rng.randint(0, w - crop + 1)
            top = train_rng.randint(0, h - crop + 1)
            im = im.crop((left, top, left + crop, top + crop))
            im = im.resize((image_size, image_size), Image.BILINEAR)
            arr = np.asarray(im, np.uint8)
            if train_rng.rand() < 0.5:
                arr = arr[:, ::-1]
            return np.ascontiguousarray(arr)
        # eval: resize short side to 1.14x then center crop
        w, h = im.size
        scale = image_size * 8 // 7 / min(w, h)
        im = im.resize(
            (max(image_size, int(w * scale)), max(image_size, int(h * scale))),
            Image.BILINEAR,
        )
        w, h = im.size
        left = (w - image_size) // 2
        top = (h - image_size) // 2
        im = im.crop((left, top, left + image_size, top + image_size))
        return np.ascontiguousarray(np.asarray(im, np.uint8))


class PrefetchLoader:
    """Batches -> device, with decode and H2D overlapped against
    compute (reference: main_amp.py DataLoader workers +
    data_prefetcher side-stream).

    ``num_workers`` decode threads feed a bounded queue of collated
    host batches; the iterator keeps ``prefetch`` batches in flight as
    async `jax.device_put`s, so the step that consumes batch N never
    waits on the decode or transfer of batch N+1. Sampling is with
    replacement per batch from ``rng`` (steady-state throughput
    semantics; epoch iteration is a thin variant the trainer can build
    from `ImageFolder.samples` directly).
    """

    def __init__(
        self,
        dataset: ImageFolder,
        batch_size: int,
        image_size: int,
        *,
        rng: Optional[np.random.RandomState] = None,
        train: bool = True,
        num_workers: int = 4,
        prefetch: int = 2,
        mean: Sequence[float] = IMAGENET_MEAN,
        std: Sequence[float] = IMAGENET_STD,
        steps: Optional[int] = None,
        device_put: bool = True,
        device_normalize: bool = True,
    ):
        """``device_normalize=True`` (default) ships the batch as
        uint8 — 4x fewer host→device bytes — and runs the
        (x/255 − mean)/std on DEVICE, which is the reference's actual
        split: its fast_collate returns a uint8 tensor and the
        prefetcher normalizes on the GPU side-stream
        (main_amp.py data_prefetcher .float().sub_().div_()). False
        normalizes on the host inside the native fast_collate."""
        self.ds = dataset
        self.batch_size = batch_size
        self.image_size = image_size
        self.rng = rng or np.random.RandomState(0)
        self.train = train
        self.num_workers = max(1, num_workers)
        self.prefetch = max(1, prefetch)
        self.mean = mean
        self.std = std
        self.steps = steps
        self.device_put = device_put
        self.device_normalize = device_normalize and device_put

    def _host_batches(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Decode-worker pool -> collated host batches, in order."""
        n = len(self.ds)
        bq: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        pool = None
        if self.num_workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(self.num_workers)

        def one(i, s):
            path, label = self.ds.samples[i]
            r = np.random.RandomState(s) if self.train else None
            return _decode(path, self.image_size, r), label

        def put(item) -> bool:
            # bounded put that re-checks `stop`: a plain blocking put
            # would leave the producer (and its decoded batch + worker
            # pool) pinned forever when the consumer abandons
            # iteration with the queue full
            while not stop.is_set():
                try:
                    bq.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            # per-producer RNG stream (deterministic from self.rng)
            batch_rng = np.random.RandomState(
                self.rng.randint(0, 2**31 - 1)
            )
            made = 0
            try:
                while not stop.is_set():
                    if self.steps is not None and made >= self.steps:
                        break
                    idx = batch_rng.randint(0, n, size=self.batch_size)
                    aug_seeds = batch_rng.randint(
                        0, 2**31 - 1, size=self.batch_size
                    )
                    if pool is not None:
                        out = list(pool.map(one, idx, aug_seeds))
                    else:
                        out = [one(i, s) for i, s in zip(idx, aug_seeds)]
                    imgs = [im for im, _ in out]
                    labels = np.asarray([l for _, l in out], np.int32)
                    if self.device_normalize:
                        # uint8 on the wire; normalization happens on
                        # device after the put
                        x = np.stack(imgs)
                    else:
                        x = _native.fast_collate(imgs, self.mean, self.std)
                    if not put((x, labels)):
                        return
                    made += 1
                put(None)
            except BaseException as e:  # noqa: BLE001
                # surface decode/collate failures to the consumer — a
                # dead producer with no sentinel would hang the
                # training loop on bq.get() forever
                put(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = bq.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            if pool is not None:
                pool.shutdown(wait=False)

    def __iter__(self):
        if not self.device_put:
            yield from self._host_batches()
            return
        import jax
        import jax.numpy as jnp

        if self.device_normalize:
            mean = jnp.asarray(self.mean, jnp.float32)
            std = jnp.asarray(self.std, jnp.float32)

            @jax.jit
            def _norm(x_u8):
                return (x_u8.astype(jnp.float32) / 255.0 - mean) / std

        # keep `prefetch` device transfers in flight: device_put is
        # async, so the copy of batch N+1 overlaps the step on batch N
        pending: List = []
        for x, y in self._host_batches():
            xd = jax.device_put(x)
            if self.device_normalize:
                xd = _norm(xd)
            pending.append((xd, jax.device_put(y)))
            if len(pending) > self.prefetch:
                yield pending.pop(0)
        yield from pending
