"""Fused LayerNorm: functional API + flax modules.

TPU-native rebuild of `apex.normalization`
(reference: apex/normalization/fused_layer_norm.py): the autograd
functions map to `jax.custom_vjp` Pallas kernels (ops/layer_norm.py),
the `nn.Module`s map to flax linen modules. Dtype contracts preserved:

* `FusedLayerNorm` — output dtype = INPUT dtype
  (reference: fused_layer_norm.py:102-196);
* `MixedFusedLayerNorm` — output dtype = PARAM dtype
  (reference: fused_layer_norm.py:199-218 and the
  `forward_affine_mixed_dtypes` native path, csrc/layer_norm_cuda.cpp).

Both compute statistics in fp32 regardless of storage dtype, like the
reference kernels.
"""

from functools import partial
from typing import Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu.ops import layer_norm as _ln_ops

__all__ = [
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "mixed_dtype_fused_layer_norm_affine",
    "mixed_dtype_fused_layer_norm_residual_affine",
    "FusedLayerNorm",
    "MixedFusedLayerNorm",
]

Shape = Union[int, Sequence[int]]


def _normalize_shape(normalized_shape: Shape) -> Tuple[int, ...]:
    if isinstance(normalized_shape, (int, np.integer)):
        return (int(normalized_shape),)
    return tuple(int(d) for d in normalized_shape)


def _to_2d(x, normalized_shape):
    shape = _normalize_shape(normalized_shape)
    n = len(shape)
    if tuple(x.shape[-n:]) != shape:
        raise ValueError(
            f"input trailing dims {x.shape[-n:]} != normalized_shape {shape}"
        )
    hidden = int(np.prod(shape))
    return x.reshape(-1, hidden), x.shape


def fused_layer_norm(x, normalized_shape: Shape, eps: float = 1e-5):
    """Non-affine fused LN (reference: fused_layer_norm.py:63-99,187-196)."""
    x2d, orig_shape = _to_2d(x, normalized_shape)
    return _ln_ops.layer_norm(x2d, eps).reshape(orig_shape)


def fused_layer_norm_affine(x, weight, bias, normalized_shape: Shape, eps: float = 1e-5):
    """Affine fused LN; output dtype = input dtype
    (reference: fused_layer_norm.py:15-42,84-90)."""
    shape = _normalize_shape(normalized_shape)
    hidden = int(np.prod(shape))
    x2d, orig_shape = _to_2d(x, normalized_shape)
    y = _ln_ops.layer_norm_affine(
        x2d, weight.reshape(hidden), bias.reshape(hidden), eps
    )
    return y.reshape(orig_shape).astype(x.dtype)


def mixed_dtype_fused_layer_norm_affine(
    x, weight, bias, normalized_shape: Shape, eps: float = 1e-6
):
    """Affine fused LN; output dtype = WEIGHT dtype
    (reference: fused_layer_norm.py:45-61,96-99)."""
    shape = _normalize_shape(normalized_shape)
    hidden = int(np.prod(shape))
    x2d, orig_shape = _to_2d(x, normalized_shape)
    y = _ln_ops.layer_norm_affine(
        x2d.astype(weight.dtype), weight.reshape(hidden), bias.reshape(hidden), eps
    )
    return y.reshape(orig_shape).astype(weight.dtype)


class FusedLayerNorm(nn.Module):
    """flax module mirroring the reference `FusedLayerNorm`
    (reference: apex/normalization/fused_layer_norm.py:102-196).

    Attributes follow the reference constructor: `normalized_shape`,
    `eps`, `elementwise_affine`. `param_dtype` controls parameter
    storage (fp32 default, like torch).
    """

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _normalize_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param(
                "weight", nn.initializers.ones_init(), shape, self.param_dtype
            )
            bias = self.param(
                "bias", nn.initializers.zeros_init(), shape, self.param_dtype
            )
            return fused_layer_norm_affine(x, weight, bias, shape, self.eps)
        return fused_layer_norm(x, shape, self.eps)


def mixed_dtype_fused_layer_norm_residual_affine(
    x, delta, weight, bias, normalized_shape: Shape, eps: float = 1e-5,
    dropout_rate: float = 0.0, dropout_seed=None,
):
    """(LN(x+delta), x+delta) fused in one kernel; LN output follows
    the weight dtype (the mixed contract), the stream follows x.
    ``dropout_rate > 0`` applies in-kernel dropout to the DELTA before
    the add (TPU hardware PRNG seeded by the int32 scalar
    ``dropout_seed``; mask regenerated in backward, never stored —
    ops/layer_norm.py `layer_norm_residual_dropout_affine`)."""
    if x.shape != delta.shape:
        raise ValueError(
            f"residual/delta shapes differ: {x.shape} vs {delta.shape}"
        )
    x2d, orig = _to_2d(x, normalized_shape)
    d2d, _ = _to_2d(delta, normalized_shape)
    if dropout_rate > 0.0:
        y, s = _ln_ops.layer_norm_residual_dropout_affine(
            x2d,
            d2d,
            weight.reshape(-1),
            bias.reshape(-1),
            dropout_seed,
            dropout_rate,
            eps,
            weight.dtype,
        )
    else:
        y, s = _ln_ops.layer_norm_residual_affine(
            x2d,
            d2d,
            weight.reshape(-1),
            bias.reshape(-1),
            eps,
            weight.dtype,
        )
    return y.reshape(orig), s.reshape(orig)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _psum_grad(x, axis_name):
    """Identity forward / psum backward: when the LN input is a shard
    (sequence parallelism), each rank's affine-param grad is a partial
    row sum and must reduce over the axis — the functional form of
    Megatron's `allreduce_sequence_parallel_gradients` hook."""
    return x


def _psum_grad_fwd(x, axis_name):
    return x, None


def _psum_grad_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


_psum_grad.defvjp(_psum_grad_fwd, _psum_grad_bwd)


class MixedFusedLayerNorm(nn.Module):
    """flax module mirroring `MixedFusedLayerNorm`: always affine, output
    dtype follows the (fp32) params even for bf16/fp16 inputs
    (reference: apex/normalization/fused_layer_norm.py:199-218).

    ``residual``: when given, the residual add fuses into the kernel —
    the call returns ``(LN(residual + x), residual + x)`` so the new
    stream never costs a standalone HBM pass (no reference analogue;
    the CUDA build leaves the add to torch). ``dropout_rate``/
    ``dropout_seed`` additionally drop the incoming ``x`` (the delta)
    inside the kernel before the add — hidden dropout with no mask
    tensor in HBM (TPU-only; see ops/layer_norm.py)."""

    normalized_shape: Shape
    eps: float = 1e-5
    param_dtype: jnp.dtype = jnp.float32
    # set to the mesh axis the input rows are sharded over (sequence
    # parallelism): the weight/bias grads — partial sums over the
    # local rows — psum over it in backward; forward is unchanged
    grad_sync_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, residual=None, dropout_rate: float = 0.0,
                 dropout_seed=None):
        shape = _normalize_shape(self.normalized_shape)
        weight = self.param(
            "weight", nn.initializers.ones_init(), shape, self.param_dtype
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), shape, self.param_dtype
        )
        if self.grad_sync_axis is not None:
            weight = _psum_grad(weight, self.grad_sync_axis)
            bias = _psum_grad(bias, self.grad_sync_axis)
        if residual is not None:
            return mixed_dtype_fused_layer_norm_residual_affine(
                residual, x, weight, bias, shape, self.eps,
                dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            )
        if dropout_rate > 0.0:
            raise ValueError(
                "in-kernel dropout rides the residual form; pass residual="
            )
        return mixed_dtype_fused_layer_norm_affine(x, weight, bias, shape, self.eps)
