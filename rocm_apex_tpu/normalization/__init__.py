"""Fused normalization layers (reference: apex/normalization/__init__.py)."""

from rocm_apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    MixedFusedLayerNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    mixed_dtype_fused_layer_norm_affine,
)

__all__ = [
    "FusedLayerNorm",
    "MixedFusedLayerNorm",
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "mixed_dtype_fused_layer_norm_affine",
]
