"""ZeRO-style distributed optimizers.

Reference: apex/contrib/optimizers/distributed_fused_adam.py,
distributed_fused_lamb.py (SURVEY.md §2.6).
"""

from rocm_apex_tpu.contrib.optimizers.distributed import (  # noqa: F401
    DistributedAdamState,
    DistributedFusedAdam,
    DistributedFusedLAMB,
    DistributedLAMBState,
    distributed_fused_adam,
    distributed_fused_lamb,
)

__all__ = [
    "distributed_fused_adam",
    "distributed_fused_lamb",
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "DistributedAdamState",
    "DistributedLAMBState",
]
