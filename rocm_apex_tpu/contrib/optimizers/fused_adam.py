"""Deprecated contrib FusedAdam (scale-aware step signature).

Reference: apex/contrib/optimizers/fused_adam.py — the older fused
Adam whose ``step(closure, grads, output_params, scale, grad_norms)``
takes explicit grads and a loss scale, built for use with
contrib.FP16_Optimizer. Kept as a shim over the modern
`rocm_apex_tpu.optimizers.fused_adam` (the reference likewise marks it
deprecated in favor of the core optimizer).
"""

import warnings
from typing import Any, Optional, Tuple

import optax

from rocm_apex_tpu.optimizers import _common as c
from rocm_apex_tpu.optimizers.fused_adam import fused_adam

__all__ = ["FusedAdam"]


class FusedAdam(c.FusedOptimizer):
    """Deprecated scale-aware facade (reference contrib fused_adam.py:64:
    `step(grads=…, scale=…)`)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        eps_inside_sqrt: bool = False,
        weight_decay: float = 0.0,
        max_grad_norm: float = 0.0,
        amsgrad: bool = False,
        use_mt: bool = False,
        amp_scale_adjustment: float = 1.0,
    ):
        warnings.warn(
            "contrib.optimizers.FusedAdam is deprecated — use "
            "rocm_apex_tpu.optimizers.FusedAdam (reference deprecates it "
            "identically)",
            DeprecationWarning,
        )
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        if eps_inside_sqrt:
            raise NotImplementedError("eps_inside_sqrt is not supported")
        del use_mt, amp_scale_adjustment, max_grad_norm
        self._kw = dict(
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            weight_decay=weight_decay,
        )
        self._lr = lr
        super().__init__(fused_adam(lr, **self._kw))

    def step_with_scale(self, params, grads, state, scale: float = 1.0,
                        skip: Optional[Any] = None):
        """The deprecated explicit-scale step: grads are divided by
        `scale` inside the fused update."""
        tx = fused_adam(self._lr, grad_scale=1.0 / scale, **self._kw)
        updates, new_state = tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)
        if skip is None:
            return new_params, new_state
        return (
            c.tree_where(skip, params, new_params),
            c.tree_where(skip, state, new_state),
        )
