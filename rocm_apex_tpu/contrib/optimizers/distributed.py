"""ZeRO-style distributed fused optimizers over the data axis.

TPU-native redesign of the reference's sharded-optimizer family
(reference: apex/contrib/optimizers/distributed_fused_adam.py:9-636 and
distributed_fused_lamb.py:6-910). The reference flattens all grads into
one buffer split into blocks/chunks/shards, overlaps **reduce-scatter**
with backward via per-param hooks, keeps each rank's shard of fp32
master params + moments, and **all-gathers** the updated fp16 params
after the step (optionally e5m2-compressed).

Here the same dataflow is three XLA collectives over the ``data`` mesh
axis inside `shard_map`, applied to the packed dtype-group buffers
(ops/packing.py):

    grads  --psum_scatter-->  grad shard           (the reduce-scatter)
    shard update: fused Adam/LAMB Pallas kernel on the rank's shard of
        fp32 masters + moments
    new masters --cast to wire dtype--> all_gather --> updates pytree

The post-step all-gather moves WIRE-dtype params, not fp32 masters
(``allgather_dtype``): "fp32" (default — bitwise master parity, the
reference's default allgather semantics), "bf16" (half the fp32 wire
bytes, the TPU-native analogue of the reference's fp16 gather), or
"e5m2" (fp8, a quarter; the reference's `e5m2_allgather=True`
compressed mode — distributed_fused_adam.py:64,97,198-206 switches its
gather buffer to uint8 e5m2 exactly this way). The masters themselves
always stay fp32 — with a low-precision wire only the gathered copy
rounds, so precision loss does not compound across steps: after every
step the model params equal wire_dtype(master), the reference's
params-from-master contract.

``comm_dtype="int8"`` goes one step further and replaces BOTH
collectives with the quantized ppermute rings of
ops/quantized_collectives.py (EQuARX, arXiv 2506.17615): each hop's
payload is int8 with per-row fp32 scales riding as a sidecar — ~4x
fewer wire bytes than the fp32 one-shot collectives on the same
64-row-aligned packed buffers, measurable via `monitor.audit`'s
per-dtype byte split. The unscale+probe ordering above becomes load-
bearing: quantization saturates inf, so found_inf MUST be read off the
pre-reduce local grads (it is).

Overflow steps skip the param all-gather entirely: the update kernels
freeze the masters bitwise, so the gathered result is exactly the
previous params and the updates are exactly zero — a `lax.cond` emits
the zeros without moving a byte (previously the gather still ran on
skipped steps, pure wasted wire).

Knob collapse relative to the reference (SURVEY.md §7): the
blocks/chunks/process-group plumbing (`dwu_num_blocks=4,
dwu_num_chunks=4`, rs/ar/ag group counts, reference
distributed_fused_adam.py:55-127) exists to hand-overlap NCCL with
bprop; XLA's latency-hiding scheduler owns that here, so the knobs are
gone. `predivide` (reference `predivide=True`) survives: divide grads
by world size before the reduce-scatter (overflow-safe) vs fold 1/N
into the kernel's grad_scale after.

Both transformations must run where the data axis is bound (inside
`shard_map`, or under pmap with the same axis name). Every rank passes
its FULL (unreduced) local grads — the reduce-scatter here replaces the
DDP allreduce; do not pre-average.

**Loss-scaler composition.** `update(..., inv_scale=1/loss_scale,
with_info=True)` unscales the packed local grads — with the fused
`isfinite` probe — in ONE pass per dtype buffer BEFORE the
reduce-scatter (overflow-safe: the wire carries unscaled fp32), pmaxes
the flag over the data axis plus `probe_sync_axes` so every rank takes
the same skip decision, folds a found_inf-predicated no-op into the
update kernels (masters/moments/count freeze, deltas exactly zero),
and returns the flag in the info dict for the host-side
`LossScaler.update` scale/skip logic — which stays unchanged
(amp/scaler.py). This is the reference's `_step_supports_amp_scaling`
contract on sharded state (distributed_fused_adam.py:254-321).

The returned updates are master-driven deltas: applying them with
`optax.apply_updates` makes the model params equal the WIRE-dtype cast
of the fp32 masters (to one fp32 ulp — the delta application re-rounds
once), the semantics of the reference's post-step all-gather of fp16
params from fp32 shards. Under the default ``allgather_dtype="fp32"``
the params are bitwise equal to the masters (the reference's master
parity, restored as the default after round 5's brief bf16 flip —
silent 2⁻⁸-tier param rounding is not a defensible default); the
low-precision wires are the explicit opt-in for gather-bandwidth-bound
runs.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rocm_apex_tpu.ops import optim_kernels
from rocm_apex_tpu.ops.multi_tensor import row_sumsq
from rocm_apex_tpu.ops.optim_kernels import BLOCK_ROWS
from rocm_apex_tpu.ops.packing import group_segment_ids, respec
from rocm_apex_tpu.ops.quantized_collectives import (
    check_comm_dtype,
    ring_all_gather,
    ring_reduce_scatter,
)
from rocm_apex_tpu.optimizers import _common as c
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = [
    "distributed_fused_adam",
    "distributed_fused_lamb",
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "DistributedAdamState",
    "DistributedLAMBState",
]


class DistributedAdamState(NamedTuple):
    count: jnp.ndarray
    master: Tuple[jnp.ndarray, ...]  # fp32 (rows/N, WIDTH) shards
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


class DistributedLAMBState(NamedTuple):
    count: jnp.ndarray
    master: Tuple[jnp.ndarray, ...]
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _shard_meta(spec, axis_name):
    """(world, rank, [(rows_padded, shard_rows) per group])."""
    world = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    dims = []
    for g in spec.groups:
        rows_pad = _round_up(g.rows, BLOCK_ROWS * world)
        dims.append((rows_pad, rows_pad // world))
    return world, rank, dims


def _pad_rows_to(buf, rows_pad):
    if buf.shape[0] == rows_pad:
        return buf
    return jnp.pad(buf, ((0, rows_pad - buf.shape[0]), (0, 0)))


def _slice_shard(buf, rank, shard_rows):
    return jax.lax.dynamic_slice_in_dim(buf, rank * shard_rows, shard_rows, 0)


def _master_shards(spec, params, axis_name):
    from rocm_apex_tpu.ops.packing import pack_tree

    world, rank, dims = _shard_meta(spec, axis_name)
    pp = pack_tree(params, spec)
    shards = []
    for pbuf, (rows_pad, shard_rows) in zip(pp.buffers, dims):
        full = _pad_rows_to(pbuf.astype(jnp.float32), rows_pad)
        shards.append(_slice_shard(full, rank, shard_rows))
    return tuple(shards)


def _scatter_grads(pg, dims, axis_name, world, predivide, comm_dtype="fp32"):
    """reduce-scatter each fp32 grad buffer into this rank's shard.

    ``comm_dtype="int8"`` swaps the one-shot `psum_scatter` for the
    quantized ppermute ring (ops/quantized_collectives.py) — the
    `_shard_meta` row padding is a multiple of BLOCK_ROWS·world, so the
    ring always tiles and the degradation path never triggers here.
    The fused unscale + found_inf probe runs BEFORE this on the full
    local grads (module header), which is load-bearing for the int8
    wire: quantization saturates inf to ±127 and would hide overflow
    from any post-reduce probe.
    """
    shards = []
    for gbuf, (rows_pad, _) in zip(pg.buffers, dims):
        g = _pad_rows_to(gbuf, rows_pad)
        if predivide:
            g = g / world
        if comm_dtype == "int8":
            shards.append(
                ring_reduce_scatter(g, axis_name, dim=0, comm_dtype="int8")
            )
        else:
            shards.append(
                jax.lax.psum_scatter(
                    g, axis_name, scatter_dimension=0, tiled=True
                )
            )
    return shards


_WIRE_DTYPES = {
    "fp32": None,
    "bf16": jnp.bfloat16,
    "e5m2": jnp.float8_e5m2,
}


def _wire_dtype(allgather_dtype):
    try:
        return _WIRE_DTYPES[allgather_dtype]
    except KeyError:
        raise ValueError(
            f"allgather_dtype must be one of {sorted(_WIRE_DTYPES)}, "
            f"got {allgather_dtype!r}"
        ) from None


def _emit_updates(spec, pp, new_masters, dims, axis_name, rank, wire=None,
                  comm_dtype="fp32"):
    """all-gather new master shards in the wire dtype; updates make
    p + u == wire_dtype(master) (== cast(master) for fp32 wire).

    ``comm_dtype="int8"`` routes the gather through the quantized
    ppermute ring instead — but it ships the DELTA (master − current
    param shard), not the master value. Deltas are lr-scale, so the
    per-row int8 grid is ~lr/127 fine where quantizing the master
    value itself would put an O(|param|/127) error on every element.
    Because each rank's delta is computed against the live param
    buffer, any residual from the previous step's quantization is part
    of the next step's delta — built-in error feedback: |master − p|
    stays bounded at one quantization step of the lr-scale grid
    instead of accumulating. Every rank dequantizes the SAME ring
    payloads and every rank computes the same (replicated) param
    shards, so params stay bitwise replicated — the int8 analogue of
    the reference's e5m2 compressed gather.
    """
    deltas = []
    for pbuf, master, (rows_pad, shard_rows) in zip(
        pp.buffers, new_masters, dims
    ):
        if comm_dtype == "int8":
            pshard = _slice_shard(
                _pad_rows_to(pbuf.astype(jnp.float32), rows_pad),
                rank, shard_rows,
            )
            full = ring_all_gather(master - pshard, axis_name, dim=0,
                                   comm_dtype="int8")
            deltas.append(full[: pbuf.shape[0]].astype(jnp.float32))
            continue
        if wire is None:
            send = master
        else:
            # saturate to the wire dtype's finite range: a plain
            # astype overflows |m| > max_finite to inf (e5m2 tops out
            # at 57344), which would poison the param permanently
            fin = float(jnp.finfo(wire).max)
            send = jnp.clip(master, -fin, fin).astype(wire)
        full = jax.lax.all_gather(send, axis_name, axis=0, tiled=True)
        full = full[: pbuf.shape[0]].astype(jnp.float32)
        deltas.append(full - pbuf.astype(jnp.float32))
    return c.deltas_to_updates(spec, deltas)


def _emit_or_freeze(spec, pp, new_masters, dims, axis_name, rank, wire,
                    comm_dtype, found_inf):
    """The post-step param gather, skipped entirely on overflow steps.

    On a found_inf step the masters freeze bitwise (the kernels emit
    exactly-zero deltas), so the gathered result is knowable without
    moving a byte: params already equal wire(master) from the previous
    step, hence updates are exactly zero. `lax.cond` keeps the gather
    out of the executed path — before this, a skipped step still paid
    the full all-gather wire cost for a guaranteed no-op result.
    """
    def _gather(masters):
        return _emit_updates(spec, pp, list(masters), dims, axis_name,
                             rank, wire, comm_dtype)

    if found_inf is None:
        return _gather(tuple(new_masters))

    def _frozen(masters):
        del masters
        zeros = [
            jnp.zeros((pbuf.shape[0], optim_kernels.WIDTH), jnp.float32)
            for pbuf in pp.buffers
        ]
        return c.deltas_to_updates(spec, zeros)

    return jax.lax.cond(found_inf, _frozen, _gather, tuple(new_masters))


def _wd_shards(spec, weight_decay, mask, dims, rank):
    cols = c.wd_columns(spec, weight_decay, mask)
    out = []
    for col, (rows_pad, shard_rows) in zip(cols, dims):
        padded = jnp.pad(col, ((0, rows_pad - col.shape[0]), (0, 0)))
        out.append(_slice_shard(padded, rank, shard_rows))
    return out


def _unscale_probe(pg, inv_scale, axis_name, probe_sync_axes):
    """Fused unscale + found_inf over the FULL local packed grads.

    Runs before the reduce-scatter so the wire carries unscaled fp32
    (the reference unscales pre-reduction too when overflow-safe,
    distributed_fused_adam.py:254-321). The flag is pmaxed over the
    data axis AND any `probe_sync_axes` (e.g. the tensor axis) so the
    kernel-level skip decision is identical on every rank — a re-sync
    in the caller's scaler (`GradScaler.update`) is then idempotent.
    """
    from rocm_apex_tpu.ops.multi_tensor import scale_packed

    pg, local_inf = scale_packed(pg, inv_scale, jnp.float32)
    flag = local_inf.astype(jnp.int32)
    for ax in (axis_name,) + tuple(probe_sync_axes):
        flag = jax.lax.pmax(flag, ax)
    return pg, flag > 0


def _global_grad_sumsq(grad_shards, axis_name):
    """Shards are disjoint after the reduce-scatter, so the global grad
    L2 norm is the psum of per-shard row-sumsq totals (the analogue of
    the reference's compute_L2_grad_norm allreduce,
    distributed_fused_adam.py:55-127)."""
    local = jnp.asarray(0.0, jnp.float32)
    for g in grad_shards:
        local = local + row_sumsq(g).sum()
    return jax.lax.psum(local, axis_name)


def distributed_fused_adam(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
    max_grad_norm: float = 0.0,
    predivide: bool = True,
    allgather_dtype: str = "fp32",
    comm_dtype: str = "fp32",
    axis_name: str = parallel_state.DATA_AXIS,
    probe_sync_axes: Tuple[str, ...] = (),
) -> optax.GradientTransformation:
    """ZeRO-sharded fused Adam over `axis_name`.

    Hyperparameter semantics match `fused_adam` / the reference
    (reference: apex/contrib/optimizers/distributed_fused_adam.py:55-127);
    `max_grad_norm > 0` enables the fused global-norm clip
    (`clip_grad_norm=True` there). Must run with `axis_name` bound.
    `update(..., inv_scale=, with_info=True)` composes the amp loss
    scaler (module header); `probe_sync_axes` lists extra bound mesh
    axes (e.g. the tensor axis) the found_inf flag syncs over.
    ``comm_dtype="int8"`` routes BOTH the grad reduce-scatter and the
    param all-gather through the quantized ppermute rings
    (ops/quantized_collectives.py) — ~4x fewer wire bytes per step;
    mutually exclusive with a non-fp32 ``allgather_dtype`` (pick one
    wire compression).
    """
    beta1, beta2 = betas
    wire = _wire_dtype(allgather_dtype)
    check_comm_dtype(comm_dtype)
    if comm_dtype == "int8" and wire is not None:
        raise ValueError(
            "comm_dtype='int8' already compresses the param gather; "
            f"combine it with allgather_dtype='fp32', not {allgather_dtype!r}"
        )

    def init_fn(params):
        spec = c.build_pack_spec(params)
        world, _, dims = _shard_meta(spec, axis_name)
        zeros = tuple(
            jnp.zeros((shard_rows, optim_kernels.WIDTH), jnp.float32)
            for (_, shard_rows) in dims
        )
        return DistributedAdamState(
            count=jnp.zeros((), jnp.int32),
            master=_master_shards(spec, params, axis_name),
            m=zeros,
            v=zeros,
        )

    def update_fn(grads, state, params=None, *, inv_scale=None,
                  with_info=False):
        if params is None:
            raise ValueError("distributed_fused_adam requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        world, rank, dims = _shard_meta(spec, axis_name)

        found_inf = None
        if inv_scale is not None:
            pg, found_inf = _unscale_probe(
                pg, inv_scale, axis_name, probe_sync_axes
            )

        count_live = state.count + 1
        lr = c.resolve_lr(learning_rate, count_live)
        t = count_live.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - beta1**t
            bc2 = 1.0 - beta2**t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        g_shards = _scatter_grads(
            pg, dims, axis_name, world, predivide, comm_dtype
        )
        gs = jnp.asarray(1.0 if grad_scale is None else grad_scale, jnp.float32)
        if not predivide:
            gs = gs / world
        if max_grad_norm and max_grad_norm > 0:
            gnorm = jnp.sqrt(_global_grad_sumsq(g_shards, axis_name)) * gs
            gs = gs * jnp.where(gnorm > max_grad_norm, max_grad_norm / gnorm, 1.0)

        wd_shards = _wd_shards(spec, weight_decay, weight_decay_mask, dims, rank)

        scalars = [lr, beta1, 1.0 - beta1, beta2, 1.0 - beta2, eps, bc1,
                   bc2, gs]
        if found_inf is not None:
            # kernel-level skip: deltas exactly zero, moments frozen
            scalars = scalars + [found_inf.astype(jnp.float32)]

        new_master, new_m, new_v = [], [], []
        for mast, gsh, mbuf, vbuf, wd in zip(
            state.master, g_shards, state.m, state.v, wd_shards
        ):
            d, m2, v2 = optim_kernels.adam_update(
                mast, gsh, mbuf, vbuf, wd, scalars, adam_w_mode,
            )
            new_master.append(mast + d)
            new_m.append(m2)
            new_v.append(v2)

        if found_inf is None:
            count = count_live
        else:
            count = state.count + jnp.logical_not(found_inf).astype(jnp.int32)

        updates = _emit_or_freeze(
            spec, pp, new_master, dims, axis_name, rank, wire, comm_dtype,
            found_inf,
        )
        new_state = DistributedAdamState(
            count=count,
            master=tuple(new_master),
            m=tuple(new_m),
            v=tuple(new_v),
        )
        if with_info:
            info = {
                "found_inf": (
                    jnp.asarray(False) if found_inf is None else found_inf
                )
            }
            return updates, new_state, info
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def distributed_fused_lamb(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    grad_averaging: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
    predivide: bool = True,
    allgather_dtype: str = "fp32",
    comm_dtype: str = "fp32",
    axis_name: str = parallel_state.DATA_AXIS,
    probe_sync_axes: Tuple[str, ...] = (),
) -> optax.GradientTransformation:
    """ZeRO-sharded fused LAMB over `axis_name`.

    The per-tensor trust ratios ||p||/||u|| are computed from sharded
    buffers: each rank's segmented partial sums are psummed over the
    axis, exactly reproducing the unsharded `fused_lamb` math
    (reference: apex/contrib/optimizers/distributed_fused_lamb.py:6-910,
    whose per-tensor norms ride a dedicated l2-norm kernel + allreduce).
    ``comm_dtype="int8"`` quantizes the grad reduce-scatter and param
    all-gather rings exactly as in `distributed_fused_adam`.
    """
    beta1, beta2 = betas
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    wire = _wire_dtype(allgather_dtype)
    check_comm_dtype(comm_dtype)
    if comm_dtype == "int8" and wire is not None:
        raise ValueError(
            "comm_dtype='int8' already compresses the param gather; "
            f"combine it with allgather_dtype='fp32', not {allgather_dtype!r}"
        )

    def init_fn(params):
        spec = c.build_pack_spec(params)
        world, _, dims = _shard_meta(spec, axis_name)
        zeros = tuple(
            jnp.zeros((shard_rows, optim_kernels.WIDTH), jnp.float32)
            for (_, shard_rows) in dims
        )
        return DistributedLAMBState(
            count=jnp.zeros((), jnp.int32),
            master=_master_shards(spec, params, axis_name),
            m=zeros,
            v=zeros,
        )

    def update_fn(grads, state, params=None, *, inv_scale=None,
                  with_info=False):
        if params is None:
            raise ValueError("distributed_fused_lamb requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        world, rank, dims = _shard_meta(spec, axis_name)

        found_inf = None
        if inv_scale is not None:
            pg, found_inf = _unscale_probe(
                pg, inv_scale, axis_name, probe_sync_axes
            )

        count_live = state.count + 1
        lr = c.resolve_lr(learning_rate, count_live)
        t = count_live.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - beta1**t
            bc2 = 1.0 - beta2**t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        g_shards = _scatter_grads(
            pg, dims, axis_name, world, predivide, comm_dtype
        )
        gs = jnp.asarray(1.0 if grad_scale is None else grad_scale, jnp.float32)
        if not predivide:
            gs = gs / world
        gnorm = jnp.sqrt(_global_grad_sumsq(g_shards, axis_name)) * gs
        if max_grad_norm and max_grad_norm > 0:
            clip = jnp.where(gnorm > max_grad_norm, max_grad_norm / gnorm, 1.0)
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        wd_shards = _wd_shards(spec, weight_decay, weight_decay_mask, dims, rank)
        wd_vals = c.wd_per_tensor(spec, weight_decay, weight_decay_mask)

        new_master, new_m, new_v = [], [], []
        for mast, gsh, mbuf, vbuf, wd, wdv, group, (rows_pad, shard_rows) in zip(
            state.master, g_shards, state.m, state.v, wd_shards, wd_vals,
            spec.groups, dims,
        ):
            u, m2, v2 = optim_kernels.lamb_stage1(
                mast, gsh, mbuf, vbuf, wd,
                [beta1, beta2, 1.0 - beta2, beta3, eps, bc1, bc2, gs, clip],
                adam_w_mode,
            )
            # sharded per-tensor norms: local segmented partials + psum
            n_t = len(group.leaf_specs)
            ids = np.concatenate(
                [
                    group_segment_ids(group),
                    np.full((rows_pad - group.rows,), n_t, np.int32),
                ]
            ).astype(np.int32)
            ids_shard = _slice_shard(jnp.asarray(ids)[:, None], rank, shard_rows)[
                :, 0
            ]

            def per_tensor(buf):
                part = jax.ops.segment_sum(
                    row_sumsq(buf)[:, 0], ids_shard, num_segments=n_t + 1
                )[:n_t]
                return jax.lax.psum(part, axis_name)

            p_norm = jnp.sqrt(per_tensor(mast))
            u_norm = jnp.sqrt(per_tensor(u))
            ratio = jnp.where(
                (p_norm > 0.0) & (u_norm > 0.0), p_norm / u_norm, 1.0
            )
            if not use_nvlamb:
                # trust ratio only for decayed tensors (reference
                # multi_tensor_lamb.cu:255-262)
                eligible = jnp.asarray(np.asarray(wdv) != 0.0)
                ratio = jnp.where(eligible, ratio, 1.0)
            padded = jnp.concatenate([ratio, jnp.ones((1,), ratio.dtype)])
            ratio_col = padded[ids_shard][:, None]
            (d,) = optim_kernels.lamb_stage2(u, ratio_col, [lr])
            if found_inf is not None:
                # buffer-level freeze (stage1 has no skip slot): deltas
                # exactly zero so `mast + d` is bitwise-unchanged
                ok = jnp.logical_not(found_inf)
                d = jnp.where(ok, d, 0.0)
                m2 = jnp.where(ok, m2, mbuf)
                v2 = jnp.where(ok, v2, vbuf)
            new_master.append(mast + d)
            new_m.append(m2)
            new_v.append(v2)

        if found_inf is None:
            count = count_live
        else:
            count = state.count + jnp.logical_not(found_inf).astype(jnp.int32)

        updates = _emit_or_freeze(
            spec, pp, new_master, dims, axis_name, rank, wire, comm_dtype,
            found_inf,
        )
        new_state = DistributedLAMBState(
            count=count,
            master=tuple(new_master),
            m=tuple(new_m),
            v=tuple(new_v),
        )
        if with_info:
            info = {
                "found_inf": (
                    jnp.asarray(False) if found_inf is None else found_inf
                )
            }
            return updates, new_state, info
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


class DistributedFusedAdam(c.FusedOptimizer):
    """Class facade (reference: distributed_fused_adam.py:9-127; the
    dwu_* overlap knobs are subsumed by the XLA scheduler)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        max_grad_norm: float = 0.0,
        predivide: bool = True,
        allgather_dtype: str = "fp32",
        comm_dtype: str = "fp32",
        weight_decay_mask: Optional[Any] = None,
        axis_name: str = parallel_state.DATA_AXIS,
        probe_sync_axes: Tuple[str, ...] = (),
    ):
        if amsgrad:
            raise RuntimeError(
                "DistributedFusedAdam does not support the AMSGrad variant."
            )
        super().__init__(
            distributed_fused_adam(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                adam_w_mode=adam_w_mode,
                weight_decay=weight_decay,
                weight_decay_mask=weight_decay_mask,
                max_grad_norm=max_grad_norm,
                predivide=predivide,
                allgather_dtype=allgather_dtype,
                comm_dtype=comm_dtype,
                axis_name=axis_name,
                probe_sync_axes=probe_sync_axes,
            )
        )


class DistributedFusedLAMB(c.FusedOptimizer):
    """Class facade (reference: distributed_fused_lamb.py:6-910)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        predivide: bool = True,
        allgather_dtype: str = "fp32",
        comm_dtype: str = "fp32",
        weight_decay_mask: Optional[Any] = None,
        axis_name: str = parallel_state.DATA_AXIS,
        probe_sync_axes: Tuple[str, ...] = (),
    ):
        if amsgrad:
            raise RuntimeError(
                "DistributedFusedLAMB does not support the AMSGrad variant."
            )
        super().__init__(
            distributed_fused_lamb(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                grad_averaging=grad_averaging,
                adam_w_mode=adam_w_mode,
                max_grad_norm=max_grad_norm,
                use_nvlamb=use_nvlamb,
                predivide=predivide,
                allgather_dtype=allgather_dtype,
                comm_dtype=comm_dtype,
                weight_decay_mask=weight_decay_mask,
                axis_name=axis_name,
                probe_sync_axes=probe_sync_axes,
            )
        )
