"""Group BatchNorm: NHWC BN with stats merged over device subgroups.

Reference: apex/contrib/groupbn/batch_norm.py:24-260 (`bn_NHWC_impl`,
`BatchNorm2d_NHWC` with `bn_group` peers synchronized through CUDA-IPC
buffers, apex/contrib/csrc/groupbn/). On TPU the IPC plumbing is a
mesh-subgroup collective: `bn_group` consecutive ranks of the data axis
form an `axis_index_groups` partition and the Welford merge rides
`all_gather` within the subgroup (SURVEY.md §7 maps groupbn to
mesh-subgroup collectives). NHWC is the TPU-native layout already.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocm_apex_tpu.parallel import SyncBatchNorm
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = ["BatchNorm2d_NHWC"]


class BatchNorm2d_NHWC(nn.Module):
    """NHWC BN over ``bn_group``-sized subgroups of the data axis, with
    the reference's fused-ReLU option (reference batch_norm.py:135-260;
    fuse_relu epilogue). ``bn_group=1`` is plain local BN; larger groups
    partition the axis into consecutive blocks. The occupancy-tuning
    knobs of the CUDA kernels have no TPU meaning and are accepted but
    ignored."""

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    momentum: float = 0.1
    eps: float = 1e-5
    axis_name: Optional[str] = parallel_state.DATA_AXIS
    use_running_average: Optional[bool] = None
    # accepted for API parity with the CUDA occupancy knobs
    max_cta_per_sm: int = 2
    cta_launch_margin: int = 12

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        if use_running_average is None:
            use_running_average = (
                self.use_running_average
                if self.use_running_average is not None
                else False  # torch default: training mode stats
            )
        groups = None
        axis = self.axis_name if self.bn_group > 1 else None
        if axis is not None:
            try:
                world = axis_size(axis)
            except NameError:
                world = 1
                axis = None
            if axis is not None:
                if world % self.bn_group:
                    raise ValueError(
                        f"bn_group {self.bn_group} does not divide the "
                        f"{axis} axis size {world}"
                    )
                groups = [
                    list(range(i, i + self.bn_group))
                    for i in range(0, world, self.bn_group)
                ]
        y = SyncBatchNorm(
            num_features=self.num_features,
            momentum=self.momentum,
            eps=self.eps,
            axis_name=axis,
            axis_index_groups=groups,
            channel_last=True,
            use_running_average=self.use_running_average,
            name="bn",
        )(x, use_running_average)
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y
