"""Fused label-smoothing softmax cross-entropy.

Reference: apex/contrib/xentropy/softmax_xentropy.py:4-28 (kernels
apex/contrib/csrc/xentropy/xentropy_kernel.cu:726). The Pallas kernel
lives in ops/xentropy.py; this package carries the reference's API.
"""

import jax.numpy as jnp

from rocm_apex_tpu.ops.xentropy import softmax_cross_entropy_loss

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]


class SoftmaxCrossEntropyLoss:
    """Callable mirroring `SoftmaxCrossEntropyLoss.apply`
    (reference: softmax_xentropy.py:4-28): per-row smoothed losses on
    (rows, vocab) logits, labels == ``padding_idx`` produce zero loss
    and zero grad. ``half_to_float`` is accepted for parity; losses are
    always fp32 (the only sensible mode on TPU)."""

    @staticmethod
    def apply(
        logits: jnp.ndarray,
        labels: jnp.ndarray,
        smoothing: float = 0.0,
        padding_idx: int = 0,
        half_to_float: bool = True,
    ) -> jnp.ndarray:
        del half_to_float
        return softmax_cross_entropy_loss(logits, labels, smoothing, padding_idx)

    def __call__(self, *args, **kw):
        return self.apply(*args, **kw)
