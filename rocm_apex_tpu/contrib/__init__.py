"""contrib — the experimental/perf tier of the framework.

TPU-native rebuilds of the reference's `apex.contrib` packages
(reference: apex/contrib/ — SURVEY.md §2.6/§2.8): ZeRO-style
distributed optimizers, fused attention (flash), fused softmax
cross-entropy, transducer, group BN, ASP structured sparsity.
Each subpackage is importable on its own, mirroring the reference's
one-package-per-kernel-family layout.
"""
