"""Fast LayerNorm — the contrib high-perf LN restricted to 2-D views.

Reference: apex/contrib/layer_norm/layer_norm.py:8-80 (`FastLayerNormFN`
returning (y, mu, rsigma), `FastLayerNorm` module; kernels
apex/contrib/csrc/layer_norm/). The row-tiled Pallas kernel in
ops/layer_norm.py serves both this and apex.normalization; this package
carries the contrib API shape.
"""

import flax.linen as nn
import jax.numpy as jnp

from rocm_apex_tpu.ops.layer_norm import layer_norm_affine, layer_norm_fwd

__all__ = ["FastLayerNorm", "fast_layer_norm"]


def fast_layer_norm(x2d, weight, bias, eps: float = 1e-5):
    """(rows, hidden) -> normalized (rows, hidden); the FastLayerNormFN
    contract (reference layer_norm.py:8-38) with the fused backward."""
    if x2d.ndim != 2:
        raise ValueError(
            f"fast_layer_norm operates on 2D (rows, hidden) views, got "
            f"{x2d.shape}"
        )
    return layer_norm_affine(x2d, weight, bias, eps)


class FastLayerNorm(nn.Module):
    """Module facade (reference layer_norm.py:40-80)."""

    hidden_size: int
    eps: float = 1e-5
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        weight = self.param(
            "weight", nn.initializers.ones_init(),
            (self.hidden_size,), self.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(),
            (self.hidden_size,), self.param_dtype,
        )
        shape = x.shape
        y = fast_layer_norm(
            x.reshape(-1, self.hidden_size), weight, bias, self.eps
        )
        return y.reshape(shape)
