"""ResNet bottleneck blocks: fused, and spatially partitioned.

Rebuild of the reference bottleneck package
(reference: apex/contrib/bottleneck/bottleneck.py — `Bottleneck:112`
builds the 1x1/3x3/1x1 conv-bn-relu chain on cudnn-frontend fused
kernels; `SpatialBottleneck:386` splits the spatial H dimension across
ranks and exchanges 1-row halos over explicit NCCL sends before the
3x3 conv). On TPU:

* the fused chain is XLA's convolution+BN+ReLU fusion — the module just
  expresses the chain (NHWC, the reference's `explicit_nhwc`);
* the halo exchange is two `ppermute`s over a mesh axis — the
  collective form of the reference's paired send/recv buffers — inside
  `shard_map`, with the 3x3 conv run VALID over the halo-extended rows.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocm_apex_tpu.parallel import SyncBatchNorm

__all__ = ["Bottleneck", "SpatialBottleneck", "halo_exchange"]


def halo_exchange(x: jnp.ndarray, axis_name: str, halo: int = 1) -> jnp.ndarray:
    """Exchange `halo` boundary rows (axis 1 = H of NHWC) with the
    previous/next rank on `axis_name`; edge ranks get zero padding.

    The collective analogue of the reference's halo send/recv
    (reference bottleneck.py SpatialBottleneck halo streams).
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    top = x[:, :halo]      # first rows -> previous rank's bottom halo
    bot = x[:, -halo:]     # last rows  -> next rank's top halo
    from_prev = jax.lax.ppermute(
        bot, axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    from_next = jax.lax.ppermute(
        top, axis_name, [(i + 1, i) for i in range(n - 1)]
    )
    zeros = jnp.zeros_like(top)
    from_prev = jnp.where(idx == 0, zeros, from_prev)
    from_next = jnp.where(idx == n - 1, zeros, from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=1)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 conv-bn-relu chain with residual
    (reference bottleneck.py:112-200). NHWC; `stride` on the 3x3 like
    torchvision v1.5+ (the reference notes the same placement)."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None

    def _norm(self, name):
        if self.sync_bn_axis is not None:
            return SyncBatchNorm(
                axis_name=self.sync_bn_axis, channel_last=True,
                dtype=self.dtype, name=name,
            )
        return nn.BatchNorm(momentum=0.9, dtype=self.dtype, name=name)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.bottleneck_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv1",
        )(x)
        y = self._norm("bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.bottleneck_channels, (3, 3),
            (self.stride, self.stride), padding=1, use_bias=False,
            dtype=self.dtype, name="conv2",
        )(y)
        y = self._norm("bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.out_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv3",
        )(y)
        y = self._norm("bn3")(y, use_running_average=not train)
        if (
            self.stride != 1
            or self.in_channels != self.out_channels
            or residual.shape != y.shape
        ):
            residual = nn.Conv(
                self.out_channels, (1, 1), (self.stride, self.stride),
                use_bias=False, dtype=self.dtype, name="downsample_conv",
            )(residual)
            residual = self._norm("downsample_bn")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class SpatialBottleneck(nn.Module):
    """Bottleneck over H-sharded activations: each rank holds H/n rows,
    and the 3x3 conv sees 1-row halos from its neighbors
    (reference bottleneck.py:386-512). Must run inside `shard_map` with
    `spatial_axis` bound and the input's H axis sharded over it.
    Stride on the 3x3 is unsupported here, like halo kernels generally
    (the reference restricts its spatial path similarly).
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    spatial_axis: str = "spatial"
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None

    def _norm(self, name):
        if self.sync_bn_axis is not None:
            return SyncBatchNorm(
                axis_name=self.sync_bn_axis, channel_last=True,
                dtype=self.dtype, name=name,
            )
        return nn.BatchNorm(momentum=0.9, dtype=self.dtype, name=name)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.bottleneck_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv1",
        )(x)
        y = self._norm("bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        # 3x3 with cross-rank halos: VALID over the halo-extended rows
        # reproduces pad-1 SAME of the full (unsharded) H
        y = halo_exchange(y, self.spatial_axis, halo=1)
        y = nn.Conv(
            self.bottleneck_channels, (3, 3),
            padding=((0, 0), (1, 1)), use_bias=False,
            dtype=self.dtype, name="conv2",
        )(y)
        y = self._norm("bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.out_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv3",
        )(y)
        y = self._norm("bn3")(y, use_running_average=not train)
        if self.in_channels != self.out_channels:
            residual = nn.Conv(
                self.out_channels, (1, 1), use_bias=False,
                dtype=self.dtype, name="downsample_conv",
            )(residual)
            residual = self._norm("downsample_bn")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)
