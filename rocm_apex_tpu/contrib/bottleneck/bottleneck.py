"""ResNet bottleneck blocks: fused, and spatially partitioned.

Rebuild of the reference bottleneck package
(reference: apex/contrib/bottleneck/bottleneck.py — `Bottleneck:112`
builds the 1x1/3x3/1x1 conv-bn-relu chain on cudnn-frontend fused
kernels; `SpatialBottleneck:386` splits the spatial H dimension across
ranks and exchanges 1-row halos over explicit NCCL sends before the
3x3 conv). On TPU:

* the fused chain is XLA's convolution+BN+ReLU fusion — the module just
  expresses the chain (NHWC, the reference's `explicit_nhwc`);
* the halo exchange is two `ppermute`s over a mesh axis — the
  collective form of the reference's paired send/recv buffers — inside
  `shard_map`, with the 3x3 conv run VALID over the halo-extended rows.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocm_apex_tpu.parallel import SyncBatchNorm
from rocm_apex_tpu.utils.compat import axis_size

__all__ = ["Bottleneck", "SpatialBottleneck", "halo_exchange"]


def halo_exchange(x: jnp.ndarray, axis_name: str, halo: int = 1) -> jnp.ndarray:
    """Exchange `halo` boundary rows (axis 1 = H of NHWC) with the
    previous/next rank on `axis_name`; edge ranks get zero padding.

    The collective analogue of the reference's halo send/recv
    (reference bottleneck.py SpatialBottleneck halo streams).
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    top = x[:, :halo]      # first rows -> previous rank's bottom halo
    bot = x[:, -halo:]     # last rows  -> next rank's top halo
    from_prev = jax.lax.ppermute(
        bot, axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    from_next = jax.lax.ppermute(
        top, axis_name, [(i + 1, i) for i in range(n - 1)]
    )
    zeros = jnp.zeros_like(top)
    from_prev = jnp.where(idx == 0, zeros, from_prev)
    from_next = jnp.where(idx == n - 1, zeros, from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=1)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 conv-bn-relu chain with residual
    (reference bottleneck.py:112-200). NHWC; `stride` on the 3x3 like
    torchvision v1.5+ (the reference notes the same placement)."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None

    def _norm(self, name):
        if self.sync_bn_axis is not None:
            return SyncBatchNorm(
                axis_name=self.sync_bn_axis, channel_last=True,
                dtype=self.dtype, name=name,
            )
        return nn.BatchNorm(momentum=0.9, dtype=self.dtype, name=name)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.bottleneck_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv1",
        )(x)
        y = self._norm("bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.bottleneck_channels, (3, 3),
            (self.stride, self.stride), padding=1, use_bias=False,
            dtype=self.dtype, name="conv2",
        )(y)
        y = self._norm("bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.out_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv3",
        )(y)
        y = self._norm("bn3")(y, use_running_average=not train)
        if (
            self.stride != 1
            or self.in_channels != self.out_channels
            or residual.shape != y.shape
        ):
            residual = nn.Conv(
                self.out_channels, (1, 1), (self.stride, self.stride),
                use_bias=False, dtype=self.dtype, name="downsample_conv",
            )(residual)
            residual = self._norm("downsample_bn")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class SpatialBottleneck(nn.Module):
    """Bottleneck over H-sharded activations: each rank holds H/n rows,
    and the 3x3 conv sees 1-row halos from its neighbors
    (reference bottleneck.py:386-512). Must run inside `shard_map` with
    `spatial_axis` bound and the input's H axis sharded over it.
    Stride on the 3x3 is unsupported here, like halo kernels generally
    (the reference restricts its spatial path similarly).
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    spatial_axis: str = "spatial"
    dtype: jnp.dtype = jnp.float32
    sync_bn_axis: Optional[str] = None

    def _norm(self, name):
        if self.sync_bn_axis is not None:
            return SyncBatchNorm(
                axis_name=self.sync_bn_axis, channel_last=True,
                dtype=self.dtype, name=name,
            )
        return nn.BatchNorm(momentum=0.9, dtype=self.dtype, name=name)

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(
            self.bottleneck_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv1",
        )(x)
        y = self._norm("bn1")(y, use_running_average=not train)
        y = nn.relu(y)
        # 3x3 with cross-rank halos: VALID over the halo-extended rows
        # reproduces pad-1 SAME of the full (unsharded) H
        y = halo_exchange(y, self.spatial_axis, halo=1)
        y = nn.Conv(
            self.bottleneck_channels, (3, 3),
            padding=((0, 0), (1, 1)), use_bias=False,
            dtype=self.dtype, name="conv2",
        )(y)
        y = self._norm("bn2")(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.Conv(
            self.out_channels, (1, 1), use_bias=False,
            dtype=self.dtype, name="conv3",
        )(y)
        y = self._norm("bn3")(y, use_running_average=not train)
        if self.in_channels != self.out_channels:
            residual = nn.Conv(
                self.out_channels, (1, 1), use_bias=False,
                dtype=self.dtype, name="downsample_conv",
            )(residual)
            residual = self._norm("downsample_bn")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class FusedBottleneck(nn.Module):
    """Training-mode bottleneck on the fused Pallas kernel chain
    (ops/fused_bottleneck.py): BN-apply+ReLU prologues, conv-as-matmul
    on the MXU, BN-statistics epilogues, and a merged
    dgrad/wgrad/BN-reduction kernel per conv in backward — the TPU
    counterpart of the reference's cudnn fused bottleneck
    (reference: apex/contrib/bottleneck/bottleneck.py:112,
    apex/contrib/csrc/bottleneck/bottleneck.cpp).

    Stride must be 1 (stride-2 blocks use the XLA `Bottleneck`);
    eval mode falls back to the unfused chain with running statistics.
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    dtype: jnp.dtype = jnp.bfloat16
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = True):
        from rocm_apex_tpu.ops.fused_bottleneck import bottleneck_fused

        cin, cmid, cout = (
            self.in_channels, self.bottleneck_channels, self.out_channels,
        )
        downsample = cin != cout
        init = nn.initializers.he_normal()
        ones = nn.initializers.ones
        zeros = nn.initializers.zeros
        w1 = self.param("conv1_kernel", init, (cin, cmid), jnp.float32)
        w2 = self.param("conv2_kernel", init, (3, 3, cmid, cmid), jnp.float32)
        w3 = self.param("conv3_kernel", init, (cmid, cout), jnp.float32)
        g1 = self.param("bn1_scale", ones, (cmid,), jnp.float32)
        b1 = self.param("bn1_bias", zeros, (cmid,), jnp.float32)
        g2 = self.param("bn2_scale", ones, (cmid,), jnp.float32)
        b2 = self.param("bn2_bias", zeros, (cmid,), jnp.float32)
        g3 = self.param("bn3_scale", ones, (cout,), jnp.float32)
        b3 = self.param("bn3_bias", zeros, (cout,), jnp.float32)
        if downsample:
            wd = self.param("downsample_kernel", init, (cin, cout), jnp.float32)
            # bn4 = the downsample branch BN (flat-leaf naming keeps
            # amp keep_batchnorm_fp32 path detection working)
            gd = self.param("bn4_scale", ones, (cout,), jnp.float32)
            bd = self.param("bn4_bias", zeros, (cout,), jnp.float32)
        else:
            wd = gd = bd = None

        names = ["bn1", "bn2", "bn3"] + (["bn4"] if downsample else [])
        dims = [cmid, cmid, cout] + ([cout] if downsample else [])
        ras = [
            (
                self.variable("batch_stats", f"{nm}_mean", zeros, None, (d,)),
                self.variable("batch_stats", f"{nm}_var", ones, None, (d,)),
            )
            for nm, d in zip(names, dims)
        ]

        if train:
            xw = x.astype(self.dtype)
            z, stats = bottleneck_fused(
                self.epsilon, downsample, xw,
                w1.astype(self.dtype), g1, b1,
                w2.astype(self.dtype), g2, b2,
                w3.astype(self.dtype), g3, b3,
                *(
                    (wd.astype(self.dtype), gd, bd)
                    if downsample else (None, None, None)
                ),
            )
            if not self.is_initializing():
                m = self.momentum
                for (ra_mu, ra_var), st in zip(ras, stats):
                    if st is None:
                        continue
                    mu, var = st
                    ra_mu.value = m * ra_mu.value + (1 - m) * mu
                    ra_var.value = m * ra_var.value + (1 - m) * var
            return z

        # eval: the plain chain with running statistics (XLA fuses the
        # inference-mode scale/bias into the conv epilogues fine)
        def bn(y, g, b, ra):
            mu, var = ra[0].value, ra[1].value
            rs = jax.lax.rsqrt(var + self.epsilon)
            return (y.astype(jnp.float32) - mu) * rs * g + b

        xw = x.astype(self.dtype)
        n, h, w_, _ = x.shape
        y = xw.reshape(-1, cin) @ w1.astype(self.dtype)
        y = jnp.maximum(bn(y, g1, b1, ras[0]), 0.0).astype(self.dtype)
        y = jax.lax.conv_general_dilated(
            y.reshape(n, h, w_, cmid), w2.astype(self.dtype), (1, 1),
            "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ).reshape(-1, cmid)
        y = jnp.maximum(bn(y, g2, b2, ras[1]), 0.0).astype(self.dtype)
        y = bn(y @ w3.astype(self.dtype), g3, b3, ras[2])
        if downsample:
            r = bn(
                xw.reshape(-1, cin) @ wd.astype(self.dtype),
                gd, bd, ras[3],
            )
        else:
            r = xw.reshape(-1, cout).astype(jnp.float32)
        z = jnp.maximum(y + r, 0.0).astype(self.dtype)
        return z.reshape(n, h, w_, cout)
