"""Fused ResNet bottleneck + spatial-parallel variant.

Reference: apex/contrib/bottleneck/bottleneck.py:112-512 (cudnn-frontend
fused conv-bn-relu `Bottleneck`, and `SpatialBottleneck` with explicit
halo exchange across spatially-partitioned ranks).
"""

from rocm_apex_tpu.contrib.bottleneck.bottleneck import (  # noqa: F401
    Bottleneck,
    FusedBottleneck,
    SpatialBottleneck,
    halo_exchange,
)

__all__ = [
    "Bottleneck",
    "FusedBottleneck",
    "SpatialBottleneck",
    "halo_exchange",
]
