"""Fused multi-head attention modules.

Reference: apex/contrib/multihead_attn/ (SelfMultiheadAttn,
EncdecMultiheadAttn, fast_mask_softmax_dropout_func) — fully fused
QKV GEMMs + softmax + dropout + out-proj, with bias / additive-mask /
"norm_add" (fused residual + LayerNorm) variants.
"""

from rocm_apex_tpu.contrib.multihead_attn.attn import (  # noqa: F401
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
    fast_mask_softmax_dropout,
)

__all__ = [
    "SelfMultiheadAttn",
    "EncdecMultiheadAttn",
    "fast_mask_softmax_dropout",
]
