"""Self / encoder-decoder multi-head attention, flash-cored.

Rebuild of the reference's fused MHA family
(reference: apex/contrib/multihead_attn/self_multihead_attn.py:27,
encdec_multihead_attn.py, fast_self_multihead_attn_func.py:243): one
fused input projection (QKV for self, Q + packed KV for encdec), the
attention core, and the output projection, with the reference's three
option axes:

* ``bias``       — projection biases on/off;
* ``mask``       — key-padding mask and/or additive attention mask;
* ``include_norm_add`` — the "norm_add" variant: pre-LayerNorm on the
  input and a residual add of the ORIGINAL input to the output
  (reference self_multihead_attn.py lyr_norm + residual semantics).

The core is the Pallas flash kernel when dropout is off (or eval);
with attention dropout in training it falls back to the materialized
scores path so the dropout pattern matches the stock implementation.
Layout is batch-first ``(b, s, h)`` — the reference uses ``(s, b, h)``
for CUDA-contiguity reasons that do not apply on TPU.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu.normalization import FusedLayerNorm
from rocm_apex_tpu.ops.flash_attention import flash_attention

__all__ = [
    "SelfMultiheadAttn",
    "EncdecMultiheadAttn",
    "fast_mask_softmax_dropout",
]


def fast_mask_softmax_dropout(
    scores: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    dropout_rate: float,
    deterministic: bool,
    rng=None,
    scale: float = 1.0,
):
    """Standalone masked-softmax(+dropout) on materialized scores
    (reference: fast_mask_softmax_dropout_func.py). ``mask`` True =
    masked."""
    s = scores.astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, -1e30, s)
    p = jax.nn.softmax(s, axis=-1).astype(scores.dtype)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return p


def _attend(q, k, v, bias, heads, dropout, deterministic, dropout_rng):
    """(b, s, h*d) projected operands -> (b, s, h*d) context."""
    b, sq, hd_all = q.shape
    sk = k.shape[1]
    d = hd_all // heads
    scale = 1.0 / np.sqrt(d)
    use_flash = dropout == 0.0 or deterministic
    qh = q.reshape(b, sq, heads, d).transpose(0, 2, 1, 3)
    kh = k.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    vh = v.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    if use_flash:
        # bias here is always a mask (key padding / attn mask), never
        # learned: skip the dbias kernel explicitly
        ctx = flash_attention(
            qh.reshape(b * heads, sq, d),
            kh.reshape(b * heads, sk, d),
            vh.reshape(b * heads, sk, d),
            bias,
            False,
            scale,
            compute_dbias=False,
        ).reshape(b, heads, sq, d)
    else:
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qh, kh, preferred_element_type=jnp.float32
        ) * scale
        if bias is not None:
            nb = bias.shape[0]
            s = s + bias.reshape(nb, -1, sq, sk).astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout), 0.0).astype(q.dtype)
        ctx = jnp.einsum(
            "bhqk,bhkd->bhqd", p, vh, preferred_element_type=q.dtype
        )
    return ctx.transpose(0, 2, 1, 3).reshape(b, sq, hd_all)


def _combine_masks(b, sq, sk, key_padding_mask, attn_mask):
    """-> additive (b, sq, sk) bias or None. key_padding_mask (b, sk)
    True = pad; attn_mask additive (sq, sk) or bool (True = masked)."""
    bias = None
    if key_padding_mask is not None:
        bias = jnp.where(
            key_padding_mask[:, None, :], -1e30, 0.0
        ).astype(jnp.float32)
        bias = jnp.broadcast_to(bias, (b, sq, sk))
    if attn_mask is not None:
        am = attn_mask
        if am.dtype == jnp.bool_:
            am = jnp.where(am, -1e30, 0.0)
        am = jnp.broadcast_to(am.astype(jnp.float32), (sq, sk))[None]
        bias = am if bias is None else bias + am
    return bias


class SelfMultiheadAttn(nn.Module):
    """Reference: apex/contrib/multihead_attn/self_multihead_attn.py:27."""

    num_heads: int
    hidden_size: Optional[int] = None  # inferred from input when None
    dropout: float = 0.0
    bias: bool = True
    include_norm_add: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        query: jnp.ndarray,
        key_padding_mask: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        h = self.hidden_size or query.shape[-1]
        if h % self.num_heads:
            raise ValueError(f"hidden {h} not divisible by {self.num_heads}")
        residual = query
        if self.include_norm_add:
            query = FusedLayerNorm(h, name="lyr_norm")(query)
        qkv = nn.Dense(
            3 * h, use_bias=self.bias, dtype=self.dtype, name="qkv_proj"
        )(query)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, sq, _ = q.shape
        bias = _combine_masks(b, sq, sq, key_padding_mask, attn_mask)
        rng = (
            self.make_rng("dropout")
            if (self.dropout > 0.0 and not deterministic)
            else None
        )
        ctx = _attend(
            q, k, v, bias, self.num_heads, self.dropout, deterministic, rng
        )
        out = nn.Dense(
            h, use_bias=self.bias, dtype=self.dtype, name="out_proj"
        )(ctx)
        if self.include_norm_add:
            out = out + residual
        return out


class EncdecMultiheadAttn(nn.Module):
    """Reference: apex/contrib/multihead_attn/encdec_multihead_attn.py."""

    num_heads: int
    hidden_size: Optional[int] = None
    dropout: float = 0.0
    bias: bool = True
    include_norm_add: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        query: jnp.ndarray,
        key: jnp.ndarray,
        key_padding_mask: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        h = self.hidden_size or query.shape[-1]
        if h % self.num_heads:
            raise ValueError(f"hidden {h} not divisible by {self.num_heads}")
        residual = query
        if self.include_norm_add:
            query = FusedLayerNorm(h, name="lyr_norm")(query)
        q = nn.Dense(
            h, use_bias=self.bias, dtype=self.dtype, name="q_proj"
        )(query)
        kv = nn.Dense(
            2 * h, use_bias=self.bias, dtype=self.dtype, name="kv_proj"
        )(key)
        k, v = jnp.split(kv, 2, axis=-1)
        b, sq, _ = q.shape
        sk = k.shape[1]
        bias = _combine_masks(b, sq, sk, key_padding_mask, attn_mask)
        rng = (
            self.make_rng("dropout")
            if (self.dropout > 0.0 and not deterministic)
            else None
        )
        ctx = _attend(
            q, k, v, bias, self.num_heads, self.dropout, deterministic, rng
        )
        out = nn.Dense(
            h, use_bias=self.bias, dtype=self.dtype, name="out_proj"
        )(ctx)
        if self.include_norm_add:
            out = out + residual
        return out
