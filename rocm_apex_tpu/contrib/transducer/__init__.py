"""RNN-T transducer joint + loss.

Reference: apex/contrib/transducer/transducer.py:5-195 (kernels
apex/contrib/csrc/transducer/transducer_joint_kernel.cu:979,
transducer_loss_kernel.cu:767).
"""

from rocm_apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
    transducer_loss_packed,
)

__all__ = [
    "TransducerJoint",
    "TransducerLoss",
    "transducer_joint",
    "transducer_loss",
    "transducer_loss_packed",
]
