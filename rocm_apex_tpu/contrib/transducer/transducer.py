"""Transducer (RNN-T) joint and loss, TPU-native.

Rebuild of the reference transducer package
(reference: apex/contrib/transducer/transducer.py — TransducerJoint:5,
TransducerLoss:69; device code transducer_joint_kernel.cu:979 tiled
f+g broadcast add, transducer_loss_kernel.cu:767 alpha/beta dynamic
programming in-kernel).

The joint is the broadcast add ``f (B,T,H) + g (B,U,H) -> (B,T,U,H)``
with optional fused ReLU/dropout epilogue — pure XLA fusion territory.

The loss runs the log-space alpha recursion

    alpha[t,u] = logaddexp(alpha[t-1,u] + blank[t-1,u],
                           alpha[t,u-1] + emit[t,u-1])

as a `lax.scan` over T where each row's prefix recurrence over U is
closed-form via `cumlogsumexp` (substituting b[u] = alpha[t,u] - E[u],
E = prefix-sum of emit, turns the recurrence into a running
log-sum-exp) — the scan-friendly alternative to the reference's
per-cell wavefront kernel. The backward (the reference's fused
softmax+loss backward) falls out of `jax.grad` through the scan.
"""

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "transducer_joint",
    "transducer_loss",
    "transducer_loss_packed",
    "TransducerJoint",
    "TransducerLoss",
]

_NEG = -1e30


def transducer_joint(
    f: jnp.ndarray,
    g: jnp.ndarray,
    f_len: jnp.ndarray,
    g_len: jnp.ndarray,
    *,
    pack_output: bool = False,
    relu: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng=None,
    batch_offset: Optional[jnp.ndarray] = None,
    packed_batch: int = 0,
):
    """f (B,T,H) + g (B,U,H) -> joint (B,T,U,H), or packed (total, H).

    Mirrors `TransducerJoint.forward`
    (reference transducer.py:43-67): `batch_offset` = cumsum(f_len*g_len)
    and `packed_batch` (static total) are required when packing.
    """
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout needs dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
    if not pack_output:
        return h
    if batch_offset is None or packed_batch == 0:
        raise ValueError(
            "Please specify batch_offset and packed_batch when packing is "
            "enabled"
        )
    B, T, U, H = h.shape
    # packed row i of batch b sits at batch_offset[b-1] + t*g_len[b] + u
    idx = jnp.arange(packed_batch)
    start = jnp.concatenate([jnp.zeros((1,), batch_offset.dtype), batch_offset])
    b = jnp.searchsorted(batch_offset, idx, side="right")
    r = idx - start[b]
    t = r // g_len[b]
    u = r % g_len[b]
    return h[b, t, u]


def transducer_loss(
    x: jnp.ndarray,
    label: jnp.ndarray,
    f_len: jnp.ndarray,
    y_len: jnp.ndarray,
    blank_idx: int,
) -> jnp.ndarray:
    """Per-batch RNN-T negative log-likelihood.

    ``x`` (B, T, U, V) raw logits (log-softmax applied inside, matching
    the reference's fused softmax+loss, transducer.py:69-117);
    ``label`` (B, U-1) targets; ``f_len`` time lengths; ``y_len`` label
    lengths (U dimension covers y_len+1 states).
    """
    B, T, U, V = x.shape
    lp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    blank = lp[..., blank_idx]  # (B, T, U)
    # emit[b, t, u] = lp of label[b, u] at (t, u); u = y_len.. masked
    lbl = jnp.minimum(label, V - 1)
    emit = jnp.take_along_axis(
        lp[:, :, : U - 1],
        jnp.broadcast_to(lbl[:, None, :, None], (B, T, U - 1, 1)),
        axis=3,
    )[..., 0]
    emit = jnp.concatenate([emit, jnp.full((B, T, 1), _NEG)], axis=2)
    u_ids = jnp.arange(U)[None, :]
    emit = jnp.where(u_ids[:, None, :] < y_len[:, None, None], emit, _NEG)

    def row(alpha_prev, inputs):
        # alpha_prev (B, U): alpha[t-1, :]; inputs: (blank[t-1], emit[t])
        blank_prev, emit_row = inputs
        a = alpha_prev + blank_prev  # (B, U)
        # E[u] = sum_{j<u} emit_row[j]
        E = jnp.concatenate(
            [jnp.zeros((B, 1)), jnp.cumsum(emit_row[:, :-1], axis=1)], axis=1
        )
        b = jax.lax.cumlogsumexp(a - E, axis=1)
        return E + b, None

    # t = 0 row: alpha[0, u] = prefix sums of emit[0]
    alpha0 = jnp.concatenate(
        [jnp.zeros((B, 1)), jnp.cumsum(emit[:, 0, :-1], axis=1)], axis=1
    )
    # iterate t = 1..T-1; stack (blank[t-1], emit[t]) pairs
    if T > 1:
        xs = (
            jnp.moveaxis(blank[:, :-1], 1, 0),  # (T-1, B, U)
            jnp.moveaxis(emit[:, 1:], 1, 0),
        )
        def step(c, i):
            a, _ = row(c, i)
            return a, a

        _, rows = jax.lax.scan(step, alpha0, xs)
        alphas = jnp.concatenate([alpha0[None], rows], axis=0)  # (T, B, U)
    else:
        alphas = alpha0[None]
    alphas = jnp.moveaxis(alphas, 0, 1)  # (B, T, U)

    bi = jnp.arange(B)
    t_last = jnp.clip(f_len - 1, 0, T - 1)
    alpha_end = alphas[bi, t_last, y_len]
    final_blank = blank[bi, t_last, y_len]
    return -(alpha_end + final_blank)


class TransducerJoint:
    """Module facade (reference transducer.py:5-67). Stateless; the
    CUDA tiling knobs (`opt`, `fwd_tile_size`) are accepted and ignored
    (XLA tiles the broadcast add)."""

    def __init__(
        self,
        pack_output: bool = False,
        relu: bool = False,
        dropout: bool = False,
        opt: int = 1,
        fwd_tile_size: int = 4,
        dropout_prob: float = 0.0,
        probe_mask: bool = False,
    ):
        if (relu or dropout) and opt != 1:
            raise NotImplementedError(
                "ReLU and dropout fusion is only supported with opt=1"
            )
        del fwd_tile_size, probe_mask
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(
        self, f, g, f_len, g_len, batch_offset=None, packed_batch=0,
        dropout_rng=None,
    ):
        return transducer_joint(
            f, g, f_len, g_len,
            pack_output=self.pack_output,
            relu=self.relu,
            dropout_rate=self.dropout_prob if self.dropout else 0.0,
            dropout_rng=dropout_rng,
            batch_offset=batch_offset,
            packed_batch=packed_batch,
        )


def transducer_loss_packed(
    x: jnp.ndarray,
    label: jnp.ndarray,
    f_len: jnp.ndarray,
    y_len: jnp.ndarray,
    blank_idx: int,
    batch_offset: jnp.ndarray,
    max_f_len: int,
) -> jnp.ndarray:
    """`transducer_loss` on packed ``x (total, V)`` input.

    The packed layout is the joint's `pack_output=True` form: batch b's
    rows occupy ``[batch_offset[b-1], batch_offset[b])`` with row
    ``t*(y_len[b]+1) + u`` inside the span, where
    ``batch_offset = cumsum(f_len*(y_len+1))`` and ``max_f_len`` is the
    static T bound (reference transducer.py:89-117 packed_input args).

    TPU-native strategy: one gather restores the padded (B, T, U, V)
    layout — the recurrence then runs on the dense fast path, and the
    gather's transpose scatters cotangents back so don't-care rows get
    exactly zero gradient (matching the reference's packed backward).
    Padded cells gather row 0 and are masked/ignored by the loss (u
    beyond y_len is forced to -inf, t beyond f_len never reaches the
    final alpha read).
    """
    B = label.shape[0]
    U = label.shape[1] + 1
    T = int(max_f_len)
    g_len = y_len + 1
    start = jnp.concatenate(
        [jnp.zeros((1,), batch_offset.dtype), batch_offset[:-1]]
    )
    t_ids = jnp.arange(T)[None, :, None]
    u_ids = jnp.arange(U)[None, None, :]
    rows = start[:, None, None] + t_ids * g_len[:, None, None] + u_ids
    valid = (t_ids < f_len[:, None, None]) & (u_ids < g_len[:, None, None])
    rows = jnp.where(valid, rows, 0)
    x_pad = x[rows]  # (B, T, U, V)
    return transducer_loss(x_pad, label, f_len, y_len, blank_idx)


class TransducerLoss:
    """Module facade (reference transducer.py:69-117), including the
    packed-input mode (batch_offset + max_f_len, reference :89-117)."""

    def __init__(
        self,
        fuse_softmax_backward: bool = True,
        opt: int = 1,
        packed_input: bool = False,
    ):
        del fuse_softmax_backward, opt
        self.packed_input = packed_input

    def __call__(
        self, x, label, f_len, y_len, blank_idx,
        batch_offset=None, max_f_len=None,
    ):
        if self.packed_input:
            if batch_offset is None or max_f_len is None:
                raise ValueError(
                    "Please specify batch_offset and max_f_len when "
                    "packing is enabled"
                )
            return transducer_loss_packed(
                x, label, f_len, y_len, blank_idx, batch_offset,
                max_f_len,
            )
        return transducer_loss(x, label, f_len, y_len, blank_idx)
