"""2:4 structured sparsity (ASP), functional.

Rebuild of the reference ASP
(reference: apex/contrib/sparsity/asp.py:21-217 — `init_model_for_pruning`
/ `init_optimizer_for_pruning` monkey-patch `optimizer.step` to re-apply
the masks after every update; masks from sparse_masklib.py `m4n2_1d`,
best 2-of-4 magnitudes per group). Functionally:

    masks  = compute_sparse_masks(params, is_prunable)
    params = apply_masks(params, masks)
    tx     = optax.chain(inner_tx, maintain_sparsity(masks))

`maintain_sparsity` is the optax analogue of the step patch: it masks
the updates so pruned weights receive zero deltas and therefore stay
zero — checkpoint-aware for free (masks are derivable from the zeros).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = [
    "create_mask",
    "compute_sparse_masks",
    "apply_masks",
    "maintain_sparsity",
    "ASP",
]


def create_mask(weight: jnp.ndarray, pattern: str = "m4n2_1d") -> jnp.ndarray:
    """Bool keep-mask with the reference's m4n2 pattern: within every
    group of 4 consecutive elements along the last dim, keep the 2
    largest magnitudes (reference: sparse_masklib.py m4n2_1d)."""
    if pattern != "m4n2_1d":
        raise ValueError(f"unsupported pattern {pattern!r}")
    if weight.shape[-1] % 4:
        raise ValueError(
            f"last dim {weight.shape[-1]} not divisible by the group size 4"
        )
    g = jnp.abs(weight).reshape(*weight.shape[:-1], -1, 4)
    # rank within each group; keep the top 2
    order = jnp.argsort(g, axis=-1)  # ascending
    rank = jnp.argsort(order, axis=-1)
    keep = rank >= 2
    return keep.reshape(weight.shape)


def _default_prunable(path, leaf) -> bool:
    """The reference prunes >=2D weights of linear/conv modules with
    both dims >= 16 (asp.py whitelist + size guard)."""
    return (
        hasattr(leaf, "ndim")
        and leaf.ndim >= 2
        and leaf.shape[-1] % 4 == 0
        and min(leaf.shape[-1], leaf.shape[-2]) >= 16
    )


def compute_sparse_masks(
    params: Any,
    is_prunable: Optional[Callable] = None,
    pattern: str = "m4n2_1d",
) -> Any:
    """Mask pytree: bool keep-mask for prunable leaves, None elsewhere
    (reference: ASP.compute_sparse_masks, asp.py:21-150)."""
    pred = is_prunable or _default_prunable

    def one(path, leaf):
        return create_mask(leaf, pattern) if pred(path, leaf) else None

    return jax.tree_util.tree_map_with_path(one, params)


def apply_masks(params: Any, masks: Any) -> Any:
    """Zero out pruned weights."""
    return jax.tree_util.tree_map(
        lambda p, m: p if m is None else jnp.where(m, p, 0).astype(p.dtype),
        params,
        masks,
        is_leaf=lambda x: x is None,
    )


def maintain_sparsity(masks: Any) -> optax.GradientTransformation:
    """Optax transform masking updates so pruned weights stay pruned —
    the functional analogue of the reference's optimizer.step patch
    (asp.py init_optimizer_for_pruning)."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        masked = jax.tree_util.tree_map(
            lambda u, m: u if m is None else jnp.where(m, u, 0).astype(u.dtype),
            updates,
            masks,
            is_leaf=lambda x: x is None,
        )
        return masked, state

    return optax.GradientTransformation(init_fn, update_fn)


class ASP:
    """Stateful facade with the reference's entry points (asp.py:21):

        asp = ASP()
        params = asp.init_model_for_pruning(params)
        tx = asp.init_optimizer_for_pruning(tx)
    """

    def __init__(
        self,
        mask_calculator: str = "m4n2_1d",
        is_prunable: Optional[Callable] = None,
    ):
        self.pattern = mask_calculator
        self.is_prunable = is_prunable
        self.masks = None

    def init_model_for_pruning(self, params):
        self.masks = compute_sparse_masks(params, self.is_prunable, self.pattern)
        return apply_masks(params, self.masks)

    def init_optimizer_for_pruning(self, tx: optax.GradientTransformation):
        if self.masks is None:
            raise RuntimeError("call init_model_for_pruning first")
        return optax.chain(tx, maintain_sparsity(self.masks))

    def compute_sparse_masks(self, params):
        self.masks = compute_sparse_masks(params, self.is_prunable, self.pattern)
        return self.masks
