"""ASP — automatic structured (2:4) sparsity.

Reference: apex/contrib/sparsity/asp.py:21-217 + sparse_masklib.py.
"""

from rocm_apex_tpu.contrib.sparsity.asp import (  # noqa: F401
    ASP,
    apply_masks,
    compute_sparse_masks,
    create_mask,
    maintain_sparsity,
)

__all__ = [
    "ASP",
    "compute_sparse_masks",
    "apply_masks",
    "create_mask",
    "maintain_sparsity",
]
