"""Packed variable-length attention over cumulative sequence offsets.

Rebuild of the reference FMHA
(reference: apex/contrib/fmha/fmha.py:33-118 — qkv ``(total, 3, h, d)``
packed along the token axis, ``cu_seqlens`` (b+1,) int32 prefix
offsets, returns ``(total, h, d)``). The reference's hand-tiled kernels
cap seqlen at 512 with `_nl` variants for small batch
(apex/contrib/csrc/fmha/); here the default path is packed-NATIVE
(`flash_attention_segments`: segment-id masking straight over the
token stream, O(total) allocations, matching the reference's design
point), with the padded-batch path (`flash_attention_varlen` with
in-kernel per-row key bounds) retained behind ``packed=False``. No
(s, s) score or mask tensor ever materializes in HBM on either path.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocm_apex_tpu.ops.flash_attention import flash_attention_varlen
from rocm_apex_tpu.ops.flash_attention_segments import (
    flash_attention_segments,
)

__all__ = ["fmha", "FMHA"]


def _unpack_ids(cu_seqlens: jnp.ndarray, total: int, max_s: int):
    """token -> (sequence, offset-within-sequence) for packed layouts."""
    tok = jnp.arange(total)
    seq_id = jnp.searchsorted(cu_seqlens[1:], tok, side="right")
    offset = tok - cu_seqlens[seq_id]
    return seq_id, offset


def fmha(
    qkv: jnp.ndarray,
    cu_seqlens: jnp.ndarray,
    max_s: int,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    packed: bool = True,
) -> jnp.ndarray:
    """Packed-varlen attention: ``qkv (total, 3, h, d)`` -> ``(total, h, d)``.

    `cu_seqlens` is the (b+1,) int32 prefix-sum of sequence lengths and
    `max_s` the static padding length (reference fmha.py:33-56 takes the
    same triple). No 512-token ceiling.

    ``packed=True`` (default) runs the packed-NATIVE kernel
    (`flash_attention_segments`): attention directly over the token
    stream with segment-id masking — every allocation O(total), like
    the reference kernels (apex/contrib/csrc/fmha/fmha_api.cpp:432).
    ``packed=False`` keeps the padded-batch path (scatter to
    (b, max_s, …), per-row kv bounds, gather back) whose compute and
    HBM scale with b·max_s — faster only when lengths are uniform and
    aligned.
    """
    total, three, h, d = qkv.shape
    assert three == 3, qkv.shape
    b = cu_seqlens.shape[0] - 1
    if packed:
        seg, _ = _unpack_ids(cu_seqlens, total, max_s)
        q = qkv[:, 0].transpose(1, 0, 2)  # (h, total, d)
        k = qkv[:, 1].transpose(1, 0, 2)
        v = qkv[:, 2].transpose(1, 0, 2)
        ctx = flash_attention_segments(
            q, k, v, seg.astype(jnp.int32), causal, scale
        )
        return ctx.transpose(1, 0, 2)  # (total, h, d)
    seq_id, offset = _unpack_ids(cu_seqlens, total, max_s)

    # scatter packed tokens into the padded (b, max_s, 3, h, d) batch
    padded = jnp.zeros((b, max_s, 3, h, d), qkv.dtype)
    padded = padded.at[seq_id, offset].set(qkv)
    q = padded[:, :, 0].transpose(0, 2, 1, 3).reshape(b * h, max_s, d)
    k = padded[:, :, 1].transpose(0, 2, 1, 3).reshape(b * h, max_s, d)
    v = padded[:, :, 2].transpose(0, 2, 1, 3).reshape(b * h, max_s, d)

    # per-(batch*heads)-row key bound, enforced IN-KERNEL: no (s, s)
    # mask tensor ever reaches HBM (round-1 review: the previous
    # materialized additive bias was the exact O(b·s²) buffer flash
    # attention exists to avoid)
    lengths = cu_seqlens[1:] - cu_seqlens[:-1]  # (b,)
    kv_lengths = jnp.repeat(lengths.astype(jnp.int32), h)  # (b*h,)

    # INVARIANT: rows with kv_lengths == 0 have UNSPECIFIED output from
    # flash_attention_varlen (its docstring reserves them). A zero-length
    # sequence in cu_seqlens contributes no packed tokens, so the gather
    # below never reads such a row — every gathered (seq_id, offset)
    # satisfies offset < lengths[seq_id]. Future callers of
    # flash_attention_varlen must preserve this: never consume rows
    # beyond their kv bound.
    ctx = flash_attention_varlen(q, k, v, kv_lengths, causal, scale)
    ctx = ctx.reshape(b, h, max_s, d).transpose(0, 2, 1, 3)  # (b, s, h, d)
    return ctx[seq_id, offset]


class FMHA(nn.Module):
    """Module facade (reference fmha.py:60-118): packed qkv in, context
    out, with the projection layers owned by the caller."""

    causal: bool = False

    @nn.compact
    def __call__(self, qkv, cu_seqlens, max_s):
        return fmha(qkv, cu_seqlens, max_s, causal=self.causal)
