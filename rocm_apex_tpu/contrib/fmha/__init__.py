"""Packed-varlen fused multi-head attention.

Reference: apex/contrib/fmha/fmha.py:33-118 (FMHAFun/FMHA over packed
qkv + cu_seqlens, seqlen <= 512). Here the core is the Pallas flash
attention, so the seqlen bound is gone.
"""

from rocm_apex_tpu.contrib.fmha.fmha import FMHA, fmha  # noqa: F401

__all__ = ["fmha", "FMHA"]
