"""Fused LAMB as XLA-tree-fused per-leaf updates.

TPU-native rebuild of `FusedLAMB` (reference:
apex/optimizers/fused_lamb.py:4-215 + csrc/multi_tensor_lamb.cu:413):
global grad-norm clip, Adam-style moment stage, per-tensor trust ratio
||p||/||update|| (applied only to decayed tensors unless `use_nvlamb`,
reference lamb.cu:255-262), grad averaging, both decay modes. The
reference's per-tensor norms are per-leaf scalar reductions here.
Tree-fused math, not packed buffers: see optimizers/fused_adam.py
header for the measured rationale.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_lamb", "FusedLAMB", "FusedLAMBState"]


class FusedLAMBState(NamedTuple):
    count: jnp.ndarray
    m: Any  # fp32 exp_avg tree
    v: Any  # fp32 exp_avg_sq tree


def fused_lamb(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    grad_averaging: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
    packed: bool = False,
) -> optax.GradientTransformation:
    """Build the fused LAMB transformation (reference fused_lamb.py:24-87).

    `packed=True` runs the pipeline over flat dtype-group buffers
    (optimizers/packed.py): the global grad norm comes from the same
    fused pass that unscales and probes the grads, trust ratios from
    segmented row reductions — O(dtype-groups) traced equations, parity
    with this path to a documented reduction-order tolerance.
    """
    if packed:
        from rocm_apex_tpu.optimizers.packed import packed_lamb

        return packed_lamb(
            learning_rate,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            weight_decay=weight_decay,
            grad_averaging=grad_averaging,
            adam_w_mode=adam_w_mode,
            max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb,
            weight_decay_mask=weight_decay_mask,
            grad_scale=grad_scale,
        )
    beta1, beta2 = betas
    beta3 = 1.0 - beta1 if grad_averaging else 1.0

    def init_fn(params):
        return FusedLAMBState(
            count=jnp.zeros((), jnp.int32),
            m=c.zeros_like_f32(params),
            v=c.zeros_like_f32(params),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params in update()")
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        t = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - beta1**t
            bc2 = 1.0 - beta2**t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )

        # global grad norm, then the clip factor (reference
        # fused_lamb.py:107-137 + lamb.cu:66: grads are divided by
        # max(||g||/max_norm, 1), i.e. multiplied by our `clip`)
        gsq = sum(
            jnp.sum(g.astype(jnp.float32) ** 2)
            for g in jax.tree_util.tree_leaves(grads)
        )
        gnorm = jnp.sqrt(gsq) * gs
        if max_grad_norm and max_grad_norm > 0:
            clip = jnp.where(gnorm > max_grad_norm, max_grad_norm / gnorm, 1.0)
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        wd = c.wd_tree(params, weight_decay, weight_decay_mask)

        def upd(p, g, m, v, wd):
            # stage 1 (lamb.cu:96-141): un-trust-scaled update direction
            pf = p.astype(jnp.float32)
            gf = g.astype(jnp.float32) * gs * clip
            if not adam_w_mode:  # MODE_0: decay into the scaled grad
                gf = gf + wd * pf
            m2 = beta1 * m + beta3 * gf
            v2 = beta2 * v + (1.0 - beta2) * gf * gf
            u = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if adam_w_mode:  # MODE_1: decay in the update
                u = u + wd * pf
            # stage 2 (lamb.cu:243-262): per-tensor trust ratio
            # ||p|| / ||u|| when both nonzero, only for decayed tensors
            # unless use_nvlamb
            p_norm = jnp.sqrt(jnp.sum(pf * pf))
            u_norm = jnp.sqrt(jnp.sum(u * u))
            ratio = jnp.where(
                (p_norm > 0.0) & (u_norm > 0.0), p_norm / u_norm, 1.0
            )
            if not use_nvlamb and wd == 0.0:
                ratio = jnp.asarray(1.0, jnp.float32)
            return -lr * ratio * u, m2, v2

        out = jax.tree_util.tree_map(upd, params, grads, state.m, state.v, wd)
        updates, m2, v2 = c.unzip_tree(params, out, 3)
        return updates, FusedLAMBState(count=count, m=m2, v=v2)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedLAMB(c.FusedOptimizer):
    """Class facade mirroring the reference constructor
    (reference: apex/optimizers/fused_lamb.py:24-87)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        super().__init__(
            fused_lamb(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                grad_averaging=grad_averaging,
                adam_w_mode=adam_w_mode,
                max_grad_norm=max_grad_norm,
                use_nvlamb=use_nvlamb,
                weight_decay_mask=weight_decay_mask,
            )
        )
