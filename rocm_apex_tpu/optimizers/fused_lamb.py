"""Fused LAMB over packed buffers.

TPU-native rebuild of `FusedLAMB` (reference:
apex/optimizers/fused_lamb.py:4-215 + csrc/multi_tensor_lamb.cu:413):
global grad-norm clip, Adam-style moment stage, per-tensor trust ratio
||p||/||update|| (applied only to decayed tensors unless `use_nvlamb`,
reference lamb.cu:255-262), grad averaging, both decay modes. The
reference's per-tensor norms are segmented row reductions here
(ops/packing.py layout invariant).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np
import optax

from rocm_apex_tpu.ops import optim_kernels
from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_lamb", "FusedLAMB", "FusedLAMBState"]


class FusedLAMBState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


def fused_lamb(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    grad_averaging: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Build the fused LAMB transformation (reference fused_lamb.py:24-87)."""
    beta1, beta2 = betas
    beta3 = 1.0 - beta1 if grad_averaging else 1.0

    def init_fn(params):
        spec = c.build_pack_spec(params)
        return FusedLAMBState(
            count=jnp.zeros((), jnp.int32),
            m=c.zero_group_buffers(spec),
            v=c.zero_group_buffers(spec),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        t = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - beta1**t
            bc2 = 1.0 - beta2**t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = 1.0 if grad_scale is None else grad_scale

        # global grad norm over every group, then the clip factor
        # (reference fused_lamb.py:107-137 + lamb.cu:66: grads are divided
        # by max(||g||/max_norm, 1), i.e. multiplied by our `clip`).
        from rocm_apex_tpu.ops.multi_tensor import row_sumsq

        gsq = jnp.asarray(0.0, jnp.float32)
        for gbuf in pg.buffers:
            gsq = gsq + row_sumsq(gbuf).sum()
        gnorm = jnp.sqrt(gsq) * gs
        if max_grad_norm and max_grad_norm > 0:
            clip = jnp.where(gnorm > max_grad_norm, max_grad_norm / gnorm, 1.0)
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        wd_cols = c.wd_columns(spec, weight_decay, weight_decay_mask)
        wd_vals = c.wd_per_tensor(spec, weight_decay, weight_decay_mask)

        deltas, new_m, new_v = [], [], []
        for pbuf, gbuf, mbuf, vbuf, wd, wdv, group in zip(
            pp.buffers, pg.buffers, state.m, state.v, wd_cols, wd_vals, spec.groups
        ):
            u, m2, v2 = optim_kernels.lamb_stage1(
                pbuf,
                gbuf,
                mbuf,
                vbuf,
                wd,
                [beta1, beta2, beta3, eps, bc1, bc2, gs, clip],
                adam_w_mode,
            )
            # per-tensor trust ratios (reference lamb.cu:243-262):
            # ratio = ||p|| / ||u|| when both nonzero, only for decayed
            # tensors unless use_nvlamb.
            p_norm = jnp.sqrt(c.per_tensor_sumsq(group, pbuf))
            u_norm = jnp.sqrt(c.per_tensor_sumsq(group, u))
            ratio = jnp.where(
                (p_norm > 0.0) & (u_norm > 0.0), p_norm / u_norm, 1.0
            )
            if not use_nvlamb:
                eligible = jnp.asarray(np.asarray(wdv) != 0.0)
                ratio = jnp.where(eligible, ratio, 1.0)
            ratio_col = c.per_tensor_to_columns(group, ratio)
            (d,) = optim_kernels.lamb_stage2(u, ratio_col, [lr])
            deltas.append(d)
            new_m.append(m2)
            new_v.append(v2)

        updates = c.deltas_to_updates(spec, deltas)
        return updates, FusedLAMBState(count=count, m=tuple(new_m), v=tuple(new_v))

    return optax.GradientTransformation(init_fn, update_fn)


class FusedLAMB(c.FusedOptimizer):
    """Class facade mirroring the reference constructor
    (reference: apex/optimizers/fused_lamb.py:24-87)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        super().__init__(
            fused_lamb(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                grad_averaging=grad_averaging,
                adam_w_mode=adam_w_mode,
                max_grad_norm=max_grad_norm,
                use_nvlamb=use_nvlamb,
                weight_decay_mask=weight_decay_mask,
            )
        )
