"""Fused Adagrad over packed buffers.

TPU-native rebuild of `FusedAdagrad` (reference:
apex/optimizers/fused_adagrad.py:5-121 + csrc/multi_tensor_adagrad.cu:100):
h += g²; update = g/(√h + eps); `adagrad_w_mode` decouples weight decay
(reference :30-36).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import optax

from rocm_apex_tpu.ops import optim_kernels
from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_adagrad", "FusedAdagrad", "FusedAdagradState"]


class FusedAdagradState(NamedTuple):
    count: jnp.ndarray
    sum: Tuple[jnp.ndarray, ...]  # fp32 accumulator ("sum" in torch Adagrad)


def fused_adagrad(
    learning_rate: c.ScalarOrSchedule = 1e-2,
    *,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    def init_fn(params):
        spec = c.build_pack_spec(params)
        return FusedAdagradState(
            count=jnp.zeros((), jnp.int32), sum=c.zero_group_buffers(spec)
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        gs = 1.0 if grad_scale is None else grad_scale
        wd_cols = c.wd_columns(spec, weight_decay, weight_decay_mask)

        deltas, new_h = [], []
        for pbuf, gbuf, hbuf, wd in zip(pp.buffers, pg.buffers, state.sum, wd_cols):
            d, h2 = optim_kernels.adagrad_update(
                pbuf, gbuf, hbuf, wd, [lr, eps, gs], adagrad_w_mode
            )
            deltas.append(d)
            new_h.append(h2)

        updates = c.deltas_to_updates(spec, deltas)
        return updates, FusedAdagradState(count=count, sum=tuple(new_h))

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdagrad(c.FusedOptimizer):
    """Class facade (reference: apex/optimizers/fused_adagrad.py:5-60)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        super().__init__(
            fused_adagrad(
                lr,
                eps=eps,
                weight_decay=weight_decay,
                adagrad_w_mode=adagrad_w_mode,
                weight_decay_mask=weight_decay_mask,
            )
        )
