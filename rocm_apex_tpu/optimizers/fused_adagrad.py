"""Fused Adagrad as XLA-tree-fused per-leaf updates.

TPU-native rebuild of `FusedAdagrad` (reference:
apex/optimizers/fused_adagrad.py:5-121 + csrc/multi_tensor_adagrad.cu:100):
h += g²; update = g/(√h + eps); `adagrad_w_mode` decouples weight decay
(reference :30-36). Tree-fused math, not packed buffers: see
optimizers/fused_adam.py header for the measured rationale.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_adagrad", "FusedAdagrad", "FusedAdagradState"]


class FusedAdagradState(NamedTuple):
    count: jnp.ndarray
    sum: Any  # fp32 accumulator tree ("sum" in torch Adagrad)


def fused_adagrad(
    learning_rate: c.ScalarOrSchedule = 1e-2,
    *,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    def init_fn(params):
        return FusedAdagradState(
            count=jnp.zeros((), jnp.int32), sum=c.zeros_like_f32(params)
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params in update()")
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd = c.wd_tree(params, weight_decay, weight_decay_mask)

        def upd(p, g, h, wd):
            pf = p.astype(jnp.float32)
            gf = g.astype(jnp.float32) * gs
            if not adagrad_w_mode:
                gf = gf + wd * pf
            h2 = h + gf * gf
            u = gf / (jnp.sqrt(h2) + eps)
            if adagrad_w_mode:
                u = u + wd * pf
            return -lr * u, h2

        out = jax.tree_util.tree_map(upd, params, grads, state.sum, wd)
        updates, h2 = c.unzip_tree(params, out, 2)
        return updates, FusedAdagradState(count=count, sum=h2)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdagrad(c.FusedOptimizer):
    """Class facade (reference: apex/optimizers/fused_adagrad.py:5-60)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        super().__init__(
            fused_adagrad(
                lr,
                eps=eps,
                weight_decay=weight_decay,
                adagrad_w_mode=adagrad_w_mode,
                weight_decay_mask=weight_decay_mask,
            )
        )
