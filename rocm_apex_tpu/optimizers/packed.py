"""Packed-buffer fused optimizer step: the multi_tensor_apply pipeline.

The reference's defining trick is `multi_tensor_apply` driving the whole
amp update pipeline — unscale, global-norm clip, Adam/LAMB — as a
handful of wide kernels over flat tensor lists (reference:
csrc/multi_tensor_apply.cuh:84-146, csrc/multi_tensor_scale_kernel.cu,
csrc/multi_tensor_adam.cu, csrc/multi_tensor_lamb.cu). This module is
that pipeline over the dtype-segregated packed buffers of
`ops/packing.py`:

    pack once  → one fused unscale + isfinite probe + row-sumsq pass
               → global grad norm + clip factor
               → one Adam/LAMB kernel         ... PER DTYPE GROUP
    unpack once

so the traced update phase emits O(dtype-groups) equations instead of
the tree_map path's O(num_leaves) small fusions (the
fusion-granularity cost of arXiv 2301.13062; `monitor.audit` asserts
the equation count in tests/L0/test_packed_optimizers.py). The
overflow skip is a `found_inf`-predicated no-op folded into the update
kernel's buffer writes (ops/optim_kernels.py `_adam_kernel` has_skip) —
no post-hoc O(leaves) `tree_where` select pass, and the whole step
stays inside one jit (the reference syncs the noop flag to host,
apex/amp/scaler.py:206-209).

**When packing loses.** Packing params+grads is a physical relayout
(~20 ms/step on a 134M-param model at measured 27 GB/s effective — see
optimizers/mixed.py header), while XLA already tree-fuses the per-leaf
math into bandwidth-bound fusions. The packed step therefore amortizes
by (a) keeping moments — and in `PackedOptimizerStep`, the fp32
masters — PACKED in the optimizer state so only params/grads cross the
layout boundary each step, and (b) being the layout ZeRO needs anyway
(contrib/optimizers/distributed.py reduce-scatters these exact
buffers). Prefer the tree path (`fused_adam()` default,
`MixedPrecisionAdam`) when the leaf count is small or the model is
large enough that the pack traffic dominates; prefer `packed=True`
when leaf count (kernel-launch/fusion granularity), audit-stable
program shape, or shardability dominate. docs/perf.md quantifies the
tradeoff.

Entry points: `packed_adam` / `packed_lamb` (optax transformations —
what `fused_adam(packed=True)` / `fused_lamb(packed=True)` return),
the buffer-level `adam_phase` / `lamb_phase` (the auditable unit: no
pack/unpack inside), and `PackedOptimizerStep` (the mixed-precision
train-step wrapper mirroring `MixedPrecisionAdam.step_and_probe`).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rocm_apex_tpu.ops.multi_tensor import scale_sumsq_packed
from rocm_apex_tpu.ops.optim_kernels import adam_update, lamb_stage1, lamb_stage2
from rocm_apex_tpu.ops.packing import (
    PackedTree,
    build_pack_spec,
    pack_tree,
    respec,
    unpack_tree,
)
from rocm_apex_tpu.optimizers import _common as c

__all__ = [
    "PackedAdamState",
    "PackedLAMBState",
    "PackedStepState",
    "PackedOptimizerStep",
    "packed_adam",
    "packed_lamb",
    "adam_phase",
    "lamb_phase",
]


class PackedAdamState(NamedTuple):
    count: jnp.ndarray  # i32 step counter
    m: Tuple[jnp.ndarray, ...]  # packed fp32 exp_avg buffers (per dtype group)
    v: Tuple[jnp.ndarray, ...]  # packed fp32 exp_avg_sq buffers


class PackedLAMBState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


def _bias_corrections(bias_correction, beta1, beta2, count):
    t = count.astype(jnp.float32)
    if bias_correction:
        return 1.0 - beta1**t, 1.0 - beta2**t
    one = jnp.asarray(1.0, jnp.float32)
    return one, one


def _grad_norm_from_rowsq(rsqs) -> jnp.ndarray:
    """Global L2 norm from the per-group (rows, 1) row-sumsq partials."""
    total = jnp.asarray(0.0, jnp.float32)
    for rsq in rsqs:
        total = total + rsq[:, 0].sum()
    return jnp.sqrt(total)


def _clip_factor(gnorm, max_grad_norm):
    # reference lamb.cu:66 divides grads by max(||g||/max_norm, 1);
    # `clip` is the reciprocal multiplier
    if max_grad_norm and max_grad_norm > 0:
        return jnp.where(gnorm > max_grad_norm, max_grad_norm / gnorm, 1.0)
    return jnp.asarray(1.0, jnp.float32)


# ---------------------------------------------------------------------------
# the auditable phases: buffers in, buffers out — no pack/unpack inside
# ---------------------------------------------------------------------------


def adam_phase(
    pp: PackedTree,
    pg: PackedTree,
    m: Tuple[jnp.ndarray, ...],
    v: Tuple[jnp.ndarray, ...],
    wd_cols,
    *,
    lr,
    beta1: float,
    beta2: float,
    eps: float,
    bc1,
    bc2,
    grad_scale,
    adam_w_mode: bool = True,
    max_grad_norm: float = 0.0,
    skip=None,
):
    """Unscale + probe (+ optional global-norm clip) + Adam over buffers.

    The whole amp update pipeline as 2 Pallas passes per dtype group:
    one `scale_sumsq_packed` pass (unscale × grad_scale, fused isfinite
    flag, row sums of squares) and one `adam_update` pass with the
    found_inf-predicated no-op folded into the kernel's buffer writes.
    Returns ``(delta_bufs, new_m, new_v, found_inf)``; every output is
    bit-frozen (deltas exactly zero) when found_inf (or the caller's
    `skip`) trips.
    """
    pgs, found_inf, rsqs = scale_sumsq_packed(pg, grad_scale, jnp.float32)
    skip_flag = found_inf if skip is None else jnp.logical_or(found_inf, skip)
    clip = _clip_factor(_grad_norm_from_rowsq(rsqs), max_grad_norm)
    skip_f = skip_flag.astype(jnp.float32)
    deltas, new_m, new_v = [], [], []
    for pb, gb, mb, vb, wdc in zip(pp.buffers, pgs.buffers, m, v, wd_cols):
        # grad_scale already applied by the fused pass; the kernel's gs
        # slot carries the clip factor (x*1.0 is bitwise-exact when off)
        d, nm, nv = adam_update(
            pb, gb, mb, vb, wdc,
            [lr, beta1, 1.0 - beta1, beta2, 1.0 - beta2, eps, bc1, bc2,
             clip, skip_f],
            adam_w_mode,
        )
        deltas.append(d)
        new_m.append(nm)
        new_v.append(nv)
    return tuple(deltas), tuple(new_m), tuple(new_v), skip_flag


def lamb_phase(
    pp: PackedTree,
    pg: PackedTree,
    m: Tuple[jnp.ndarray, ...],
    v: Tuple[jnp.ndarray, ...],
    wd_cols,
    wd_vals,
    *,
    lr,
    beta1: float,
    beta2: float,
    beta3: float,
    eps: float,
    bc1,
    bc2,
    grad_scale,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    skip=None,
):
    """Unscale + probe + global-norm clip + LAMB over buffers.

    Stage 1 (`lamb_stage1`) emits the un-trust-scaled direction per
    group; trust ratios ||p||/||u|| come from the segmented row
    reductions the row-aligned layout makes legal (`per_tensor_sumsq`),
    gated to decayed tensors unless `use_nvlamb` via the STATIC
    `wd_vals` (reference lamb.cu:255-262); stage 2 applies
    -lr·ratio·u. Returns ``(delta_bufs, new_m, new_v, found_inf)``.
    """
    pgs, found_inf, rsqs = scale_sumsq_packed(pg, grad_scale, jnp.float32)
    skip_flag = found_inf if skip is None else jnp.logical_or(found_inf, skip)
    gnorm = _grad_norm_from_rowsq(rsqs)
    clip = _clip_factor(gnorm, max_grad_norm)
    ok = jnp.logical_not(skip_flag)
    deltas, new_m, new_v = [], [], []
    for group, pb, gb, mb, vb, wdc, wdv in zip(
        pp.spec.groups, pp.buffers, pgs.buffers, m, v, wd_cols, wd_vals
    ):
        u, nm, nv = lamb_stage1(
            pb, gb, mb, vb, wdc,
            [beta1, beta2, 1.0 - beta2, beta3, eps, bc1, bc2, 1.0, clip],
            adam_w_mode,
        )
        p_norm = jnp.sqrt(c.per_tensor_sumsq(group, pb))
        u_norm = jnp.sqrt(c.per_tensor_sumsq(group, u))
        ratio = jnp.where(
            (p_norm > 0.0) & (u_norm > 0.0), p_norm / u_norm, 1.0
        )
        if not use_nvlamb:
            eligible = np.asarray(wdv) != 0.0
            ratio = jnp.where(jnp.asarray(eligible), ratio, 1.0)
        rcol = c.per_tensor_to_columns(group, ratio)
        (d,) = lamb_stage2(u, rcol, [lr])
        # stage1 has no skip scalar: buffer-level freeze (jnp.where, not
        # an arithmetic blend — overflowed steps carry inf/nan)
        deltas.append(jnp.where(ok, d, 0.0))
        new_m.append(jnp.where(ok, nm, mb))
        new_v.append(jnp.where(ok, nv, vb))
    return tuple(deltas), tuple(new_m), tuple(new_v), skip_flag


# ---------------------------------------------------------------------------
# optax transformations — what fused_adam/fused_lamb(packed=True) return
# ---------------------------------------------------------------------------


def packed_adam(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
    max_grad_norm: float = 0.0,
) -> optax.GradientTransformation:
    """`fused_adam` hyperparameter semantics over packed buffers.

    Same math as the tree path (bit-identical updates on finite fp32
    grads — tests/L0/test_packed_optimizers.py asserts it), but the
    update phase is `adam_phase`: O(dtype-groups) equations, moments
    held packed in `PackedAdamState`, and overflowed steps freeze
    params AND moments inside the kernel instead of relying on the
    caller's skip branch.
    """
    beta1, beta2 = betas

    def init_fn(params):
        spec = build_pack_spec(params)
        return PackedAdamState(
            count=jnp.zeros((), jnp.int32),
            m=c.zero_group_buffers(spec),
            v=c.zero_group_buffers(spec),
        )

    def update_fn(grads, state, params=None, *, skip=None):
        if params is None:
            raise ValueError("packed_adam requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        count_live = state.count + 1
        lr = c.resolve_lr(learning_rate, count_live)
        bc1, bc2 = _bias_corrections(bias_correction, beta1, beta2, count_live)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd_cols = c.wd_columns(spec, weight_decay, weight_decay_mask)
        deltas, m2, v2, skipped = adam_phase(
            pp, pg, state.m, state.v, wd_cols,
            lr=lr, beta1=beta1, beta2=beta2, eps=eps, bc1=bc1, bc2=bc2,
            grad_scale=gs, adam_w_mode=adam_w_mode,
            max_grad_norm=max_grad_norm, skip=skip,
        )
        count = state.count + jnp.logical_not(skipped).astype(jnp.int32)
        updates = c.deltas_to_updates(spec, deltas)
        return updates, PackedAdamState(count=count, m=m2, v=v2)

    update_fn.kernel_skip = True  # FusedOptimizer.step routes skip here
    return optax.GradientTransformation(init_fn, update_fn)


def packed_lamb(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    grad_averaging: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    """`fused_lamb` hyperparameter semantics over packed buffers.

    The global grad norm comes from the SAME fused pass that unscales
    and probes the gradients (`scale_sumsq_packed`) — the reference
    runs multi_tensor_l2norm as a separate launch sweep. Trust-ratio
    norms use the segmented row reductions; reduction ORDER differs
    from the tree path's per-leaf `jnp.sum`, so parity is to a
    documented ~1e-6 relative tolerance rather than bitwise (see
    tests/L0/test_packed_optimizers.py).
    """
    beta1, beta2 = betas
    beta3 = 1.0 - beta1 if grad_averaging else 1.0

    def init_fn(params):
        spec = build_pack_spec(params)
        return PackedLAMBState(
            count=jnp.zeros((), jnp.int32),
            m=c.zero_group_buffers(spec),
            v=c.zero_group_buffers(spec),
        )

    def update_fn(grads, state, params=None, *, skip=None):
        if params is None:
            raise ValueError("packed_lamb requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        count_live = state.count + 1
        lr = c.resolve_lr(learning_rate, count_live)
        bc1, bc2 = _bias_corrections(bias_correction, beta1, beta2, count_live)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd_cols = c.wd_columns(spec, weight_decay, weight_decay_mask)
        wd_vals = c.wd_per_tensor(spec, weight_decay, weight_decay_mask)
        deltas, m2, v2, skipped = lamb_phase(
            pp, pg, state.m, state.v, wd_cols, wd_vals,
            lr=lr, beta1=beta1, beta2=beta2, beta3=beta3, eps=eps,
            bc1=bc1, bc2=bc2, grad_scale=gs, adam_w_mode=adam_w_mode,
            max_grad_norm=max_grad_norm, use_nvlamb=use_nvlamb, skip=skip,
        )
        count = state.count + jnp.logical_not(skipped).astype(jnp.int32)
        updates = c.deltas_to_updates(spec, deltas)
        return updates, PackedLAMBState(count=count, m=m2, v=v2)

    update_fn.kernel_skip = True
    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# PackedOptimizerStep: the mixed-precision train-step wrapper
# ---------------------------------------------------------------------------


class PackedStepState(NamedTuple):
    count: jnp.ndarray
    model: Any  # compute-dtype param tree (feed to model.apply)
    master: Tuple[jnp.ndarray, ...]  # PACKED fp32 master buffers
    m: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


class PackedOptimizerStep:
    """Mixed-precision packed train step (Adam or LAMB math).

    API-compatible with `MixedPrecisionAdam` (`init` / `model_params` /
    `step` / `step_and_probe`), but masters and moments live PACKED in
    the state: each step packs only the grads (and re-derives the spec
    from the model tree), runs `adam_phase`/`lamb_phase` on resident
    buffers, and unpacks only the compute-dtype model copy. That is the
    minimum possible layout traffic for a packed step — the design
    tradeoff quantified in the module header and docs/perf.md.
    """

    def __init__(
        self,
        optimizer: str = "adam",
        learning_rate: c.ScalarOrSchedule = 1e-3,
        *,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: Optional[float] = None,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        weight_decay_mask: Optional[Any] = None,
        max_grad_norm: float = 0.0,
        grad_averaging: bool = True,
        use_nvlamb: bool = False,
        compute_dtype: jnp.dtype = jnp.bfloat16,
    ):
        if optimizer not in ("adam", "lamb"):
            raise ValueError(f"optimizer must be 'adam' or 'lamb', got {optimizer!r}")
        self.optimizer = optimizer
        self.learning_rate = learning_rate
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.beta3 = 1.0 - self.beta1 if grad_averaging else 1.0
        self.eps = eps if eps is not None else (1e-8 if optimizer == "adam" else 1e-6)
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.weight_decay_mask = weight_decay_mask
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.compute_dtype = compute_dtype

    def _model_spec(self, model):
        return build_pack_spec(model)

    def init(self, params) -> PackedStepState:
        """`params` may be fp32 (they seed the masters exactly) or
        already in compute dtype."""
        master_tree = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), params
        )
        model = jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype), master_tree
        )
        spec = self._model_spec(model)
        f32 = respec(spec, jnp.float32)
        master = pack_tree(master_tree, f32).buffers
        return PackedStepState(
            count=jnp.zeros((), jnp.int32),
            model=model,
            master=master,
            m=c.zero_group_buffers(spec),
            v=c.zero_group_buffers(spec),
        )

    def model_params(self, state: PackedStepState):
        """The compute-dtype tree for `model.apply` (== state.model)."""
        return state.model

    def masters(self, state: PackedStepState):
        """Unpack the fp32 master buffers to a params-shaped tree
        (checkpointing/diagnostics — not on the step hot path)."""
        spec = self._model_spec(state.model)
        return unpack_tree(
            PackedTree(tuple(state.master), respec(spec, jnp.float32))
        )

    def _step(self, state, grads, *, grad_scale=None, skip=None):
        spec = self._model_spec(state.model)
        f32 = respec(spec, jnp.float32)
        pg = pack_tree(grads, spec)  # native dtype; the fused pass casts
        pm = PackedTree(tuple(state.master), f32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        count_live = state.count + 1
        lr = c.resolve_lr(self.learning_rate, count_live)
        bc1, bc2 = _bias_corrections(
            self.bias_correction, self.beta1, self.beta2, count_live
        )
        wd_cols = c.wd_columns(spec, self.weight_decay, self.weight_decay_mask)
        if self.optimizer == "adam":
            deltas, m2, v2, skipped = adam_phase(
                pm, pg, state.m, state.v, wd_cols,
                lr=lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                bc1=bc1, bc2=bc2, grad_scale=gs,
                adam_w_mode=self.adam_w_mode,
                max_grad_norm=self.max_grad_norm, skip=skip,
            )
        else:
            wd_vals = c.wd_per_tensor(
                spec, self.weight_decay, self.weight_decay_mask
            )
            deltas, m2, v2, skipped = lamb_phase(
                pm, pg, state.m, state.v, wd_cols, wd_vals,
                lr=lr, beta1=self.beta1, beta2=self.beta2, beta3=self.beta3,
                eps=self.eps, bc1=bc1, bc2=bc2, grad_scale=gs,
                adam_w_mode=self.adam_w_mode,
                max_grad_norm=self.max_grad_norm,
                use_nvlamb=self.use_nvlamb, skip=skip,
            )
        # deltas are exactly zero on skipped steps: master2 == master
        # bitwise, and the model copy re-cast is value-preserving
        master2 = tuple(mb + d for mb, d in zip(state.master, deltas))
        model2 = unpack_tree(
            PackedTree(
                tuple(b.astype(self.compute_dtype) for b in master2),
                respec(spec, self.compute_dtype),
            )
        )
        new_state = PackedStepState(
            count=state.count + jnp.logical_not(skipped).astype(jnp.int32),
            model=model2,
            master=master2,
            m=m2,
            v=v2,
        )
        return new_state, skipped

    def step(self, state, grads, *, grad_scale=None, skip=None):
        """One packed update; `grads` are w.r.t. `state.model`,
        `grad_scale` (1/loss_scale) fuses the unscale, `skip` ORs into
        the kernel-level found_inf freeze. Returns the new state."""
        new_state, _ = self._step(
            state, grads, grad_scale=grad_scale, skip=skip
        )
        return new_state

    def step_and_probe(self, state, grads, *, grad_scale=None):
        """`step` with the overflow probe fused into the unscale pass
        (exactly one fused reduction per dtype buffer). Returns
        ``(new_state, found_inf)`` — `MixedPrecisionAdam` contract."""
        return self._step(state, grads, grad_scale=grad_scale)
