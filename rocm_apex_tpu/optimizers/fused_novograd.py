"""Fused NovoGrad as XLA-tree-fused per-leaf updates.

TPU-native rebuild of `FusedNovoGrad` (reference:
apex/optimizers/fused_novograd.py:4-214 + csrc/multi_tensor_novograd.cu:188):
per-layer second moment stored as the blended grad *norm* (not squared,
reference fused_novograd.py:158-177), L2 or inf norm types, `init_zero`
vs first-step-norm initialization, grad averaging, and both decay
placements (`reg_inside_moment`). Per-tensor norms are per-leaf scalar
reductions here. Tree-fused math, not packed buffers: see
optimizers/fused_adam.py header for the measured rationale.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_novograd", "FusedNovoGrad", "FusedNovoGradState"]


class FusedNovoGradState(NamedTuple):
    count: jnp.ndarray
    m: Any  # fp32 exp_avg tree
    v: Any  # per-tensor norm scalars, tree of () fp32


def fused_novograd(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.95, 0.98),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    reg_inside_moment: bool = False,
    norm_type: int = 2,
    init_zero: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Build the fused NovoGrad transformation
    (reference fused_novograd.py:66-90)."""
    if norm_type not in (0, 2):
        raise RuntimeError("FusedNovoGrad only supports l2 (2) / inf (0) norm")
    beta1, beta2 = betas
    beta3 = 1.0 - beta1 if grad_averaging else 1.0

    def init_fn(params):
        return FusedNovoGradState(
            count=jnp.zeros((), jnp.int32),
            m=c.zeros_like_f32(params),
            v=jax.tree_util.tree_map(
                lambda _: jnp.zeros((), jnp.float32), params
            ),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params in update()")
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        t = count.astype(jnp.float32)
        if bias_correction:
            # the reference's launcher uses sqrt for the 2nd-moment
            # correction (reference: csrc/multi_tensor_novograd.cu:151:
            # bias_correction2 = sqrt(1 - beta2^step))
            bc1 = 1.0 - beta1**t
            bc2 = jnp.sqrt(1.0 - beta2**t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd = c.wd_tree(params, weight_decay, weight_decay_mask)

        def blend(old, new):
            # EMA of *norms*: L2 blends in squared space, inf linearly
            # (reference: csrc/multi_tensor_novograd.cu:161-164 via
            # multi_tensor_norm_out_cuda).
            if norm_type == 2:
                return jnp.sqrt(beta2 * old * old + (1.0 - beta2) * new * new)
            return beta2 * old + (1.0 - beta2) * new

        def upd(p, g, m, vscalar, wd):
            pf = p.astype(jnp.float32)
            gf = g.astype(jnp.float32)
            if norm_type == 2:
                norm = jnp.sqrt(jnp.sum(gf * gf)) * gs
            else:
                norm = jnp.max(jnp.abs(gf)) * gs
            if init_zero:
                v2 = blend(vscalar, norm)
            else:
                # first step seeds v with the raw norm "so first blend
                # has no effect" (reference fused_novograd.py:167)
                v2 = jnp.where(count == 1, norm, blend(vscalar, norm))
            gf = gf * gs
            denom = v2 / bc2 + eps
            if reg_inside_moment:  # MOMENT_MODE_0 (novograd.cu:99-105)
                m2 = beta1 * m + beta3 * (gf / denom + wd * pf)
                d = -lr * (m2 / bc1)
            else:  # MOMENT_MODE_1, decoupled decay (:107-114)
                m2 = beta1 * m + beta3 * gf
                d = -lr * ((m2 / bc1) / denom + wd * pf)
            return d, m2, v2

        out = jax.tree_util.tree_map(upd, params, grads, state.m, state.v, wd)
        updates, m2, v2 = c.unzip_tree(params, out, 3)
        return updates, FusedNovoGradState(count=count, m=m2, v=v2)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedNovoGrad(c.FusedOptimizer):
    """Class facade mirroring the reference constructor
    (reference: apex/optimizers/fused_novograd.py:66-90)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.95, 0.98),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        reg_inside_moment: bool = False,
        grad_averaging: bool = True,
        norm_type: int = 2,
        init_zero: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        super().__init__(
            fused_novograd(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                grad_averaging=grad_averaging,
                reg_inside_moment=reg_inside_moment,
                norm_type=norm_type,
                init_zero=init_zero,
                weight_decay_mask=weight_decay_mask,
            )
        )
