"""Fused NovoGrad over packed buffers.

TPU-native rebuild of `FusedNovoGrad` (reference:
apex/optimizers/fused_novograd.py:4-214 + csrc/multi_tensor_novograd.cu:188):
per-layer second moment stored as the blended grad *norm* (not squared,
reference fused_novograd.py:158-177), L2 or inf norm types, `init_zero`
vs first-step-norm initialization, grad averaging, and both decay
placements (`reg_inside_moment`).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu.ops import optim_kernels
from rocm_apex_tpu.ops.packing import group_segment_ids
from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_novograd", "FusedNovoGrad", "FusedNovoGradState"]


class FusedNovoGradState(NamedTuple):
    count: jnp.ndarray
    m: Tuple[jnp.ndarray, ...]  # fp32 exp_avg group buffers
    v: Tuple[jnp.ndarray, ...]  # per-tensor norm vectors, one (n_tensors,) per group


def _per_tensor_norm(group, gbuf, norm_type: int) -> jnp.ndarray:
    if norm_type == 2:
        return jnp.sqrt(c.per_tensor_sumsq(group, gbuf))
    # inf norm: segmented max over rows (XLA reduce; the reference computes
    # this host-side per tensor, fused_novograd.py:168-170)
    row_max = jnp.max(jnp.abs(gbuf.astype(jnp.float32)), axis=1)
    seg = jnp.asarray(group_segment_ids(group))
    return jax.ops.segment_max(
        row_max, seg, num_segments=len(group.leaf_specs) + 1
    )[: len(group.leaf_specs)]


def fused_novograd(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.95, 0.98),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    reg_inside_moment: bool = False,
    norm_type: int = 2,
    init_zero: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Build the fused NovoGrad transformation
    (reference fused_novograd.py:66-90)."""
    if norm_type not in (0, 2):
        raise RuntimeError("FusedNovoGrad only supports l2 (2) / inf (0) norm")
    beta1, beta2 = betas
    beta3 = 1.0 - beta1 if grad_averaging else 1.0

    def init_fn(params):
        spec = c.build_pack_spec(params)
        return FusedNovoGradState(
            count=jnp.zeros((), jnp.int32),
            m=c.zero_group_buffers(spec),
            v=tuple(
                jnp.zeros((len(g.leaf_specs),), jnp.float32) for g in spec.groups
            ),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        t = count.astype(jnp.float32)
        if bias_correction:
            # the reference's launcher uses sqrt for the 2nd-moment
            # correction (reference: csrc/multi_tensor_novograd.cu:151:
            # bias_correction2 = sqrt(1 - beta2^step))
            bc1 = 1.0 - beta1**t
            bc2 = jnp.sqrt(1.0 - beta2**t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = 1.0 if grad_scale is None else grad_scale
        wd_cols = c.wd_columns(spec, weight_decay, weight_decay_mask)

        def blend(old, new):
            # EMA of *norms*: L2 blends in squared space, inf linearly
            # (reference: csrc/multi_tensor_novograd.cu:161-164 via
            # multi_tensor_norm_out_cuda).
            if norm_type == 2:
                return jnp.sqrt(beta2 * old * old + (1.0 - beta2) * new * new)
            return beta2 * old + (1.0 - beta2) * new

        deltas, new_m, new_v = [], [], []
        for pbuf, gbuf, mbuf, vvec, wd, group in zip(
            pp.buffers, pg.buffers, state.m, state.v, wd_cols, spec.groups
        ):
            norm = _per_tensor_norm(group, gbuf, norm_type) * gs
            if init_zero:
                v2 = blend(vvec, norm)
            else:
                # first step seeds v with the raw norm "so first blend has
                # no effect" (reference fused_novograd.py:167); later steps
                # blend.
                v2 = jnp.where(count == 1, norm, blend(vvec, norm))
            v_col = c.per_tensor_to_columns(group, v2)
            d, m2 = optim_kernels.novograd_update(
                pbuf,
                gbuf,
                mbuf,
                v_col,
                wd,
                [lr, beta1, beta3, eps, bc1, bc2, gs],
                reg_inside_moment,
            )
            deltas.append(d)
            new_m.append(m2)
            new_v.append(v2)

        updates = c.deltas_to_updates(spec, deltas)
        return updates, FusedNovoGradState(
            count=count, m=tuple(new_m), v=tuple(new_v)
        )

    return optax.GradientTransformation(init_fn, update_fn)


class FusedNovoGrad(c.FusedOptimizer):
    """Class facade mirroring the reference constructor
    (reference: apex/optimizers/fused_novograd.py:66-90)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.95, 0.98),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        reg_inside_moment: bool = False,
        grad_averaging: bool = True,
        norm_type: int = 2,
        init_zero: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        super().__init__(
            fused_novograd(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                grad_averaging=grad_averaging,
                reg_inside_moment=reg_inside_moment,
                norm_type=norm_type,
                init_zero=init_zero,
                weight_decay_mask=weight_decay_mask,
            )
        )
