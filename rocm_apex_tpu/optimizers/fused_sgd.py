"""Fused SGD as XLA-tree-fused per-leaf updates.

TPU-native rebuild of `FusedSGD` (reference:
apex/optimizers/fused_sgd.py:6-227 + csrc/multi_tensor_sgd_kernel.cu:322):
momentum/nesterov/dampening/weight-decay with the reference's
first-momentum-step semantics (buf = d on the first application) and the
`wd_after_momentum` placement option. The reference's depth-3 variant
(materializing an fp16 model copy in-kernel for amp master weights) is
covered by the amp layer's master-weight wrapper instead
(rocm_apex_tpu/amp/_process_optimizer.py). Tree-fused math, not packed
buffers: see optimizers/fused_adam.py header for the measured rationale.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_sgd", "FusedSGD", "FusedSGDState"]


class FusedSGDState(NamedTuple):
    count: jnp.ndarray
    momentum_buffer: Any  # fp32 tree


def fused_sgd(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    wd_after_momentum: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Build the fused SGD transformation (reference fused_sgd.py:6-91)."""
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init_fn(params):
        return FusedSGDState(
            count=jnp.zeros((), jnp.int32),
            momentum_buffer=c.zeros_like_f32(params),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params in update()")
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        first = state.count == 0
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd = c.wd_tree(params, weight_decay, weight_decay_mask)

        def upd(p, g, mbuf, wd):
            # mirrors the sgd functor (csrc/multi_tensor_sgd_kernel.cu):
            # first momentum application sets buf = d
            pf = p.astype(jnp.float32)
            gf = g.astype(jnp.float32) * gs
            if not wd_after_momentum:
                gf = gf + wd * pf
            if momentum != 0.0:
                buf = jnp.where(
                    first, gf, momentum * mbuf + (1.0 - dampening) * gf
                )
                d = gf + momentum * buf if nesterov else buf
            else:
                buf = mbuf
                d = gf
            if wd_after_momentum:
                d = d + wd * pf
            return -lr * d, buf

        out = jax.tree_util.tree_map(upd, params, grads, state.momentum_buffer, wd)
        updates, buf = c.unzip_tree(params, out, 2)
        return updates, FusedSGDState(count=count, momentum_buffer=buf)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedSGD(c.FusedOptimizer):
    """Class facade mirroring the reference constructor
    (reference: apex/optimizers/fused_sgd.py:6-91)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_after_momentum: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        super().__init__(
            fused_sgd(
                lr,
                momentum=momentum,
                dampening=dampening,
                weight_decay=weight_decay,
                nesterov=nesterov,
                wd_after_momentum=wd_after_momentum,
                weight_decay_mask=weight_decay_mask,
            )
        )
