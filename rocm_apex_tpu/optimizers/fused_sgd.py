"""Fused SGD over packed buffers.

TPU-native rebuild of `FusedSGD` (reference:
apex/optimizers/fused_sgd.py:6-227 + csrc/multi_tensor_sgd_kernel.cu:322):
momentum/nesterov/dampening/weight-decay with the reference's
first-momentum-step semantics (buf = d on the first application) and the
`wd_after_momentum` placement option. The reference's depth-3 variant
(materializing an fp16 model copy in-kernel for amp master weights) is
covered by the amp layer's master-weight wrapper instead
(rocm_apex_tpu/amp/_process_optimizer.py).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import optax

from rocm_apex_tpu.ops import optim_kernels
from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_sgd", "FusedSGD", "FusedSGDState"]


class FusedSGDState(NamedTuple):
    count: jnp.ndarray
    momentum_buffer: Tuple[jnp.ndarray, ...]  # fp32 group buffers


def fused_sgd(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    wd_after_momentum: bool = False,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Build the fused SGD transformation (reference fused_sgd.py:6-91)."""
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init_fn(params):
        spec = c.build_pack_spec(params)
        return FusedSGDState(
            count=jnp.zeros((), jnp.int32),
            momentum_buffer=c.zero_group_buffers(spec),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        first = (state.count == 0).astype(jnp.float32)
        gs = 1.0 if grad_scale is None else grad_scale
        wd_cols = c.wd_columns(spec, weight_decay, weight_decay_mask)

        deltas, new_buf = [], []
        for pbuf, gbuf, mbuf, wd in zip(
            pp.buffers, pg.buffers, state.momentum_buffer, wd_cols
        ):
            d, b2 = optim_kernels.sgd_update(
                pbuf,
                gbuf,
                mbuf,
                wd,
                [lr, momentum, dampening, first, gs],
                nesterov,
                wd_after_momentum,
                momentum != 0.0,
            )
            deltas.append(d)
            new_buf.append(b2)

        updates = c.deltas_to_updates(spec, deltas)
        return updates, FusedSGDState(count=count, momentum_buffer=tuple(new_buf))

    return optax.GradientTransformation(init_fn, update_fn)


class FusedSGD(c.FusedOptimizer):
    """Class facade mirroring the reference constructor
    (reference: apex/optimizers/fused_sgd.py:6-91)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_after_momentum: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        super().__init__(
            fused_sgd(
                lr,
                momentum=momentum,
                dampening=dampening,
                weight_decay=weight_decay,
                nesterov=nesterov,
                wd_after_momentum=wd_after_momentum,
                weight_decay_mask=weight_decay_mask,
            )
        )
