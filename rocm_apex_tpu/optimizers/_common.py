"""Shared plumbing for the fused optimizer family.

The reference optimizers all follow one pattern: group params by dtype
{fp16/bf16, fp32} and issue one multi_tensor_applier launch per bucket
(reference: apex/optimizers/fused_adam.py:117-170). Here the grouping IS
the packed layout (ops/packing.py): every optimizer packs params once,
packs grads fp32 into the same row layout, runs one Pallas update per
dtype-group buffer, and emits optax-style fp32 delta updates.
"""

from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rocm_apex_tpu.ops.packing import (
    WIDTH,
    PackSpec,
    PackedTree,
    build_pack_spec,
    group_segment_ids,
    pack_like,
    pack_tree,
    respec,
    unpack_tree,
)

__all__ = [
    "ScalarOrSchedule",
    "resolve_lr",
    "pack_params_and_grads",
    "wd_columns",
    "wd_per_tensor",
    "wd_tree",
    "per_tensor_to_columns",
    "deltas_to_updates",
    "unzip_tree",
    "zero_group_buffers",
    "zeros_like_f32",
    "tree_where",
    "FusedOptimizer",
]

ScalarOrSchedule = Union[float, jnp.ndarray, Callable]


def resolve_lr(lr: ScalarOrSchedule, count):
    """Accept a constant or an optax-style schedule step→lr."""
    return lr(count) if callable(lr) else lr


def wd_tree(params: Any, weight_decay: float, mask: Optional[Any] = None):
    """Per-leaf python-float weight decay (True in `mask` = decayed).

    `mask` may be any pytree with the same LEAF COUNT as params (the
    torch-param-group stand-in contract shared with `wd_columns`)."""
    if mask is None:
        return jax.tree_util.tree_map(lambda _: weight_decay, params)
    p_struct = jax.tree_util.tree_structure(params)
    m_leaves = jax.tree_util.tree_leaves(mask)
    if len(m_leaves) != p_struct.num_leaves:
        raise ValueError(
            f"weight_decay mask has {len(m_leaves)} leaves, "
            f"params have {p_struct.num_leaves}"
        )
    return jax.tree_util.tree_unflatten(
        p_struct, [weight_decay if on else 0.0 for on in m_leaves]
    )


def zeros_like_f32(params: Any):
    """fp32 zero tree shaped like `params` (moment-state init)."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )


def unzip_tree(params: Any, out: Any, n: int) -> Tuple[Any, ...]:
    """Split a params-shaped tree of n-tuples into n params-shaped trees.

    Container-safe: uses the params treedef to stop flattening at the
    per-leaf tuples, so params pytrees that themselves contain tuples /
    NamedTuples (legal JAX containers) unzip correctly — a naive
    ``is_leaf=lambda x: isinstance(x, tuple)`` would stop at them."""
    treedef = jax.tree_util.tree_structure(params)
    flat = treedef.flatten_up_to(out)
    return tuple(
        jax.tree_util.tree_unflatten(treedef, [t[i] for t in flat])
        for i in range(n)
    )


def pack_params_and_grads(params: Any, grads: Any):
    """Pack params (native dtypes) and grads (fp32) into aligned buffers."""
    spec = build_pack_spec(params)
    pp = pack_tree(params, spec)
    pg = pack_like(respec(spec, jnp.float32), grads)
    return spec, pp, pg


def wd_columns(spec: PackSpec, weight_decay, mask: Optional[Any] = None):
    """Per-group (rows, 1) fp32 weight-decay columns.

    `mask` is a static pytree of bools (True = apply decay) — the
    functional stand-in for the reference's per-param-group weight_decay
    (torch param groups, e.g. excluding biases/LN). Rows of masked-out or
    padding tensors get 0.
    """
    mask_leaves = None
    if mask is not None:
        mask_leaves = jax.tree_util.tree_leaves(mask)
        if len(mask_leaves) != spec.n_leaves:
            raise ValueError(
                f"weight_decay mask has {len(mask_leaves)} leaves, "
                f"params have {spec.n_leaves}"
            )
    cols = []
    for g in spec.groups:
        col = np.zeros((g.rows, 1), np.float32)
        for i, ls in zip(g.leaf_indices, g.leaf_specs):
            on = True if mask_leaves is None else bool(mask_leaves[i])
            if on:
                col[ls.row_start : ls.row_start + ls.nrows] = 1.0
        cols.append(jnp.asarray(col) * weight_decay)
    return cols


def wd_per_tensor(spec: PackSpec, weight_decay: float, mask: Optional[Any] = None):
    """Static per-tensor decay values per group (numpy), for trust-ratio
    rules that depend on whether a tensor is decayed
    (reference: csrc/multi_tensor_lamb.cu stage 2 `decay != 0`)."""
    mask_leaves = None
    if mask is not None:
        mask_leaves = jax.tree_util.tree_leaves(mask)
    out = []
    for g in spec.groups:
        vals = np.zeros((len(g.leaf_specs),), np.float32)
        for j, i in enumerate(g.leaf_indices):
            on = True if mask_leaves is None else bool(mask_leaves[i])
            vals[j] = weight_decay if on else 0.0
        out.append(vals)
    return out


def per_tensor_to_columns(group, values: jnp.ndarray) -> jnp.ndarray:
    """Spread per-tensor values (n_tensors,) to a (rows, 1) column."""
    seg = jnp.asarray(group_segment_ids(group))
    padded = jnp.concatenate([values, jnp.zeros((1,), values.dtype)])
    return padded[seg][:, None]


def per_tensor_sumsq(group, buf: jnp.ndarray) -> jnp.ndarray:
    """Per-tensor sum of squares of a group buffer via segmented row sums."""
    from rocm_apex_tpu.ops.multi_tensor import row_sumsq

    row_sq = row_sumsq(buf)[:, 0]
    seg = jnp.asarray(group_segment_ids(group))
    return jax.ops.segment_sum(row_sq, seg, num_segments=len(group.leaf_specs) + 1)[
        : len(group.leaf_specs)
    ]


def deltas_to_updates(spec: PackSpec, deltas) -> Any:
    """fp32 delta buffers → an optax updates pytree (fp32 leaves).

    `optax.apply_updates` computes (p + u) in promoted fp32 and casts back
    to p.dtype — identical rounding to the reference's in-kernel fp32 math
    + final store (csrc/multi_tensor_adam.cu MATH_T accumulators).
    """
    return unpack_tree(PackedTree(deltas, respec(spec, jnp.float32)))


def zero_group_buffers(spec: PackSpec, dtype=jnp.float32):
    return tuple(jnp.zeros((g.rows, WIDTH), dtype) for g in spec.groups)


def tree_where(pred, new, old):
    return jax.tree_util.tree_map(lambda n, o: jnp.where(pred, n, o), new, old)


class FusedOptimizer:
    """Apex-style class facade over an optax fused transform.

    Drop-in shape of the reference's `torch.optim.Optimizer` subclasses
    (reference: apex/optimizers/__init__.py:1-6) restated functionally:
    ``state = opt.init(params)``, ``params, state = opt.step(params, grads,
    state)``. `skip` integrates dynamic-loss-scale step skipping: when
    True, params AND optimizer state are left untouched (the jit-safe
    analogue of amp's step-patching, reference apex/amp/handle.py:128-154).
    """

    def __init__(self, tx: optax.GradientTransformation):
        self.tx = tx

    def init(self, params):
        return self.tx.init(params)

    def step(self, params, grads, state, *, skip=None):
        if skip is not None and getattr(self.tx.update, "kernel_skip", False):
            # packed transforms fold the skip into the update kernel's
            # buffer writes (deltas exactly zero, moments/count frozen)
            # — no O(leaves) tree_where select pass afterwards
            updates, new_state = self.tx.update(grads, state, params, skip=skip)
            return optax.apply_updates(params, updates), new_state
        updates, new_state = self.tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)
        if skip is None:
            return new_params, new_state
        return (
            tree_where(skip, params, new_params),
            tree_where(skip, state, new_state),
        )

    # optax duck-typing so the class can be passed anywhere a
    # GradientTransformation is expected (e.g. amp.initialize).
    @property
    def update(self):
        return self.tx.update
