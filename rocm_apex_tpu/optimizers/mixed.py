"""Mixed-precision training state: bf16 model params + fp32 masters.

The reference's performance architecture for mixed precision keeps TWO
copies of the model — low-precision params the model computes with and
fp32 masters the optimizer updates, the update writing the low-precision
copy out in the same kernel (reference:
apex/amp/_process_optimizer.py:28-90 master-weight management,
apex/optimizers/fused_sgd.py depth-3 lists with fp16 copy-out,
apex/contrib/optimizers/distributed_fused_adam.py fp32 shards +
all-gathered fp16 params). This module is that architecture as a
functional train state:

    opt    = MixedPrecisionAdam(...)
    state  = opt.init(params_fp32)
    ...
    loss, grads = value_and_grad(loss_fn)(state.model)   # bf16 tree
    state = opt.step(state, grads, grad_scale=1/S, skip=skip)

**Why the update is XLA-fused tree math, not the packed Pallas kernel.**
The CUDA reference packs tensor lists into flat buffers because a kernel
launch per tensor dominates there (csrc/multi_tensor_apply.cuh). On TPU
the measured reality is the opposite: (8,128)-tiled 2-D arrays do NOT
linearize for free, so every pack/unpack of the parameter set is a
physical relayout — profiled at ~20 ms/step on a 134M-param GPT (the
gradient-pack loop fusion ran at 27 GB/s against an >800 GB/s chip),
while XLA fuses the whole per-leaf Adam update into a handful of
bandwidth-bound fusions with zero packing traffic. XLA fusion IS the
multi-tensor-apply of this hardware. The packed Pallas kernels remain
the substrate where packing is structurally required — the row-sharded
ZeRO optimizers (contrib/optimizers/distributed.py) and the
multi_tensor parity layer (ops/multi_tensor.py).

Skip-step (dynamic loss scaling) folds into the update as a select on
every buffer being written anyway — the jit-safe analogue of the
reference's optimizer.step no-op patch (apex/amp/handle.py:128-154).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu.optimizers import _common as c

__all__ = [
    "MixedPrecisionAdam",
    "MixedPrecisionState",
    "MixedPrecisionLamb",
]


class MixedPrecisionState(NamedTuple):
    count: jnp.ndarray
    model: Any   # compute-dtype param tree (feed to model.apply)
    master: Any  # fp32 master tree
    m: Any
    v: Any


class MixedPrecisionAdam:
    """Fused Adam/AdamW over mixed-precision train state.

    Hyperparameters match `fused_adam` / the reference
    (apex/optimizers/fused_adam.py:20-60); `compute_dtype` is the model
    params' dtype (bf16 = the O5/O2 recipe). `weight_decay_mask` is a
    bool pytree (True = decay), the functional stand-in for torch param
    groups.
    """

    def __init__(
        self,
        learning_rate: c.ScalarOrSchedule = 1e-3,
        *,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        weight_decay_mask: Optional[Any] = None,
        compute_dtype: jnp.dtype = jnp.bfloat16,
    ):
        self.learning_rate = learning_rate
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.weight_decay_mask = weight_decay_mask
        self.compute_dtype = compute_dtype

    def init(self, params) -> MixedPrecisionState:
        """`params` may be fp32 (preferred: they seed the masters
        exactly) or already in compute dtype."""
        master = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), params
        )
        model = jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype), master
        )
        zeros = jax.tree_util.tree_map(jnp.zeros_like, master)
        return MixedPrecisionState(
            count=jnp.zeros((), jnp.int32),
            model=model,
            master=master,
            m=zeros,
            v=jax.tree_util.tree_map(jnp.zeros_like, master),
        )

    def model_params(self, state: MixedPrecisionState):
        """The compute-dtype tree for `model.apply` (== state.model)."""
        return state.model

    def step(
        self,
        state: MixedPrecisionState,
        grads,
        *,
        grad_scale=None,
        skip=None,
    ) -> MixedPrecisionState:
        """One fused update. `grads` are w.r.t. the compute-dtype params
        (`state.model`); `grad_scale` (1/loss_scale) fuses the unscale;
        `skip` freezes every buffer when True."""
        b1, b2, eps = self.beta1, self.beta2, self.eps
        live_t = (state.count + 1).astype(jnp.float32)
        lr = c.resolve_lr(self.learning_rate, state.count + 1)
        if self.bias_correction:
            bc1 = 1.0 - b1**live_t
            bc2 = 1.0 - b2**live_t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        if skip is None:
            live = jnp.asarray(1.0, jnp.float32)
            count = state.count + 1
        else:
            live = 1.0 - jnp.asarray(skip, jnp.float32)
            count = state.count + live.astype(jnp.int32)

        wd_tree = c.wd_tree(
            state.master, self.weight_decay, self.weight_decay_mask
        )

        def upd(p, g, m, v, wd):
            gf = g.astype(jnp.float32) * gs
            if not self.adam_w_mode:  # L2 mode: decay into the gradient
                gf = gf + wd * p
            m2 = b1 * m + (1.0 - b1) * gf
            v2 = b2 * v + (1.0 - b2) * gf * gf
            u = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if self.adam_w_mode:  # AdamW: decoupled decay
                u = u + wd * p
            p2 = p - lr * u
            # jnp.where, not an arithmetic blend: skipped steps carry
            # inf/nan in p2, and inf * 0.0 == nan would poison p
            on = live > 0.0
            return (
                jnp.where(on, p2, p),
                jnp.where(on, m2, m),
                jnp.where(on, v2, v),
            )

        out = jax.tree_util.tree_map(
            upd, state.master, grads, state.m, state.v, wd_tree
        )
        master2, m2, v2 = c.unzip_tree(state.master, out, 3)
        return MixedPrecisionState(
            count=count,
            model=jax.tree_util.tree_map(
                lambda x: x.astype(self.compute_dtype), master2
            ),
            master=master2,
            m=m2,
            v=v2,
        )

    def step_and_probe(
        self,
        state: MixedPrecisionState,
        grads,
        *,
        grad_scale=None,
    ):
        """`step` with the overflow probe fused into the update pass.

        Returns ``(new_state, found_inf)``. A standalone
        `all_finite(grads)` probe costs a full extra pass over the
        gradients as dozens of separate reduce kernels (~18 ms/step
        measured on the 134M GPT); here each leaf's fp32 sum rides the
        update fusion that already reads the gradient, and the
        skip-select applies to the provisional outputs afterwards —
        overflow semantics identical to probe-then-skip (reference:
        the in-kernel noop_flag of multi_tensor_scale,
        csrc/multi_tensor_scale_kernel.cu:30-136)."""
        b1, b2, eps = self.beta1, self.beta2, self.eps
        live_t = (state.count + 1).astype(jnp.float32)
        lr = c.resolve_lr(self.learning_rate, state.count + 1)
        if self.bias_correction:
            bc1 = 1.0 - b1**live_t
            bc2 = 1.0 - b2**live_t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd_tree = c.wd_tree(
            state.master, self.weight_decay, self.weight_decay_mask
        )

        def upd(p, g, m, v, wd):
            gf = g.astype(jnp.float32) * gs
            probe = jnp.sum(gf)  # fused with the pass that reads gf
            if not self.adam_w_mode:
                gf = gf + wd * p
            m2 = b1 * m + (1.0 - b1) * gf
            v2 = b2 * v + (1.0 - b2) * gf * gf
            u = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if self.adam_w_mode:
                u = u + wd * p
            return (p - lr * u, m2, v2, probe)

        out = jax.tree_util.tree_map(
            upd, state.master, grads, state.m, state.v, wd_tree
        )
        new_master, new_m, new_v, probes = c.unzip_tree(
            state.master, out, 4
        )
        found_inf = ~jnp.isfinite(
            sum(jax.tree_util.tree_leaves(probes))
        )
        ok = ~found_inf

        def sel(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, old
            )

        master2 = sel(new_master, state.master)
        new_state = MixedPrecisionState(
            count=state.count + ok.astype(jnp.int32),
            model=jax.tree_util.tree_map(
                lambda x: x.astype(self.compute_dtype), master2
            ),
            master=master2,
            m=sel(new_m, state.m),
            v=sel(new_v, state.v),
        )
        return new_state, found_inf


class MixedPrecisionLamb:
    """Fused LAMB over mixed-precision train state — the BERT-Large
    recipe (reference: apex/optimizers/fused_lamb.py:4-215 semantics on
    the apex master-weight architecture, and
    fused_mixed_precision_lamb.py:8-256 which is the same marriage on
    the CUDA side).

    Same state shape as `MixedPrecisionAdam` (bf16 model copy + fp32
    masters + moments), with LAMB's extra structure arranged for HBM
    bandwidth — on a 330M-param BERT the naive tree-LAMB costs
    ~15 ms/step in optimizer machinery (round-5 profile: 202 standalone
    per-tensor reduce kernels + the materialized update-direction
    buffers and their scan-carry copies):

    * the overflow probe IS the global grad-norm pass — LAMB must read
      every gradient for the clip anyway, so `found_inf` falls out of
      the same per-leaf sum-of-squares (non-finite gsq == overflow);
    * the update direction ``u`` is NEVER materialized: pass A updates
      the moments and emits the (psq, usq) trust-ratio partials from
      registers; pass B recomputes ``u`` from (m2, v2, master) and
      applies ``p − lr·ratio·u`` with the bf16 model copy emitted from
      the same fusion. Recomputing u costs re-reading m2/v2 (8 B/param)
      and saves writing+re-reading a 4 B/param u buffer — net −4 B and
      one fewer kernel boundary;
    * ``moment_dtype=bf16`` (optional) halves the m/v traffic and
      state, the analogue of the reference's fp16-moment modes. Numerics
      caveat — trust-ratio skew: pass A emits ``usq`` (the ratio
      denominator) from the PRE-rounding fp32 moments in-register,
      while pass B recomputes the applied ``u`` from the STORED
      bf16-rounded moments — so with bf16 moments the update direction
      and the ratio scaling it are ~2⁻⁹-tier inconsistent with each
      other (and with an fp32-moment run). Accepted as designed: the
      ratio is one scalar per tensor and checkpoint-replay consistency
      anchors on pass B's stored moments; runs that must be bitwise-
      comparable against an fp32-moment baseline need
      ``moment_dtype=fp32``.

    Trust-ratio semantics match `fused_lamb` exactly: ratio =
    ||master||/||u|| for decayed tensors (all tensors with
    `use_nvlamb`), identity otherwise; the clip divides grads by
    max(||g||/max_grad_norm, 1).
    """

    def __init__(
        self,
        learning_rate: c.ScalarOrSchedule = 1e-3,
        *,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        grad_averaging: bool = True,
        adam_w_mode: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        weight_decay_mask: Optional[Any] = None,
        compute_dtype: jnp.dtype = jnp.bfloat16,
        moment_dtype: jnp.dtype = jnp.float32,
        store_model: bool = True,
    ):
        self.learning_rate = learning_rate
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.beta3 = 1.0 - self.beta1 if grad_averaging else 1.0
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.weight_decay_mask = weight_decay_mask
        self.compute_dtype = compute_dtype
        self.moment_dtype = moment_dtype
        # store_model=False keeps state.model EMPTY (None) and
        # `model_params` casts from the masters on demand: the cast is
        # the same 6 B/param of traffic either way, but a scan-carried
        # model copy is double-buffered by XLA — on a 330M BERT that is
        # 2 x 0.66 GB of the 16 GB chip (the b8 OOM margin)
        self.store_model = store_model

    def init(self, params) -> MixedPrecisionState:
        master = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), params
        )
        model = (
            jax.tree_util.tree_map(
                lambda x: x.astype(self.compute_dtype), master
            )
            if self.store_model
            else None
        )
        zeros = jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, self.moment_dtype), master
        )
        return MixedPrecisionState(
            count=jnp.zeros((), jnp.int32),
            model=model,
            master=master,
            m=zeros,
            v=jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, self.moment_dtype), master
            ),
        )

    def model_params(self, state: MixedPrecisionState):
        if state.model is not None:
            return state.model
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype), state.master
        )

    def step_and_probe(
        self,
        state: MixedPrecisionState,
        grads,
        *,
        grad_scale=None,
    ):
        """One fused update; returns ``(new_state, found_inf)``.

        `grads` are w.r.t. `state.model`; `grad_scale` (1/loss_scale)
        fuses the unscale. On overflow every buffer (and the count)
        freezes — the skip-step contract of the reference's
        `_step_supports_amp_scaling` path
        (fused_mixed_precision_lamb.py:140-256)."""
        b1, b2, b3, eps = self.beta1, self.beta2, self.beta3, self.eps
        live_t = (state.count + 1).astype(jnp.float32)
        lr = c.resolve_lr(self.learning_rate, state.count + 1)
        if self.bias_correction:
            bc1 = 1.0 - b1**live_t
            bc2 = 1.0 - b2**live_t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd_tree = c.wd_tree(
            state.master, self.weight_decay, self.weight_decay_mask
        )

        # global grad norm = the overflow probe (one read of g)
        gsq = sum(
            jnp.sum((g.astype(jnp.float32) * gs) ** 2)
            for g in jax.tree_util.tree_leaves(grads)
        )
        found_inf = ~jnp.isfinite(gsq)
        gnorm = jnp.sqrt(gsq)
        if self.max_grad_norm and self.max_grad_norm > 0:
            clip = jnp.where(
                gnorm > self.max_grad_norm, self.max_grad_norm / gnorm, 1.0
            )
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        ok = ~found_inf
        live = ok.astype(jnp.float32)

        def _u(m2, v2, p, wd):
            u = (m2.astype(jnp.float32) / bc1) / (
                jnp.sqrt(v2.astype(jnp.float32) / bc2) + eps
            )
            if self.adam_w_mode:
                u = u + wd * p
            return u

        # Leaf routing: large lane-aligned leaves run the per-leaf
        # Pallas kernel pair (ops/optim_kernels.lamb_leaf_stage1/2 —
        # norms emitted from the update pass, u never materialized);
        # the rest (biases, LN params: negligible bytes) keep the
        # XLA tree math. The tree formulation leaves the trust-ratio
        # norms as standalone reduce kernels re-reading every buffer —
        # ~16 ms/step on a 330M BERT (round-5 profile).
        from rocm_apex_tpu.ops import optim_kernels as _ok

        def _leaf_view(x):
            """(rows, cols) 2-D view for the kernel path, or None."""
            if x.ndim == 0 or x.size < (1 << 16):
                return None
            cols = x.shape[-1]
            if cols % 128 != 0:
                return None
            rows = int(np.prod(x.shape[:-1]))
            return rows, cols

        def _padded(x, rows, cols, rows_p):
            x2 = x.reshape(rows, cols)
            if rows_p != rows:
                x2 = jnp.pad(x2, ((0, rows_p - rows), (0, 0)))
            return x2

        # pass A: moment update + trust-ratio partials, u in-register
        def stage_a(p, g, m, v, wd):
            view = _leaf_view(p)
            if view is not None:
                rows, cols = view
                block = _ok._leaf_block(rows, cols, 6)
                rows_p = -(-rows // block) * block
                m2, v2, psq, usq = _ok.lamb_leaf_stage1(
                    _padded(p, rows, cols, rows_p),
                    _padded(g, rows, cols, rows_p),
                    _padded(m, rows, cols, rows_p),
                    _padded(v, rows, cols, rows_p),
                    [b1, b2, b3, eps, bc1, bc2, gs * clip, live],
                    float(wd), self.adam_w_mode,
                )
                return (
                    m2[:rows].reshape(p.shape).astype(m.dtype),
                    v2[:rows].reshape(p.shape).astype(v.dtype),
                    psq,
                    usq,
                )
            gf = g.astype(jnp.float32) * gs * clip
            pf = p  # master, already fp32
            if not self.adam_w_mode:
                gf = gf + wd * pf
            m2f = b1 * m.astype(jnp.float32) + b3 * gf
            v2f = b2 * v.astype(jnp.float32) + (1.0 - b2) * gf * gf
            u = _u(m2f, v2f, pf, wd)
            return (
                jnp.where(ok, m2f, m.astype(jnp.float32)).astype(m.dtype),
                jnp.where(ok, v2f, v.astype(jnp.float32)).astype(v.dtype),
                jnp.sum(pf * pf),
                jnp.sum(u * u),
            )

        out_a = jax.tree_util.tree_map(
            stage_a, state.master, grads, state.m, state.v, wd_tree
        )
        new_m, new_v, psq, usq = c.unzip_tree(state.master, out_a, 4)

        # per-tensor ratio (scalar math on the reduction results)
        def ratio_of(psq, usq, wd):
            r = jnp.where(
                (psq > 0.0) & (usq > 0.0),
                jnp.sqrt(psq) / jnp.sqrt(usq),
                1.0,
            )
            if not self.use_nvlamb and wd == 0.0:
                r = jnp.asarray(1.0, jnp.float32)
            return r

        ratios = jax.tree_util.tree_map(ratio_of, psq, usq, wd_tree)

        # pass B: recompute u (from the NEW moments) and apply; the
        # compute-dtype model copy rides the same kernel/fusion. NOTE
        # pass B uses the pass-A moment values as STORED (after any
        # moment_dtype rounding) so a reloaded checkpoint reproduces
        # the same params
        def stage_b(p, m2, v2, wd, r):
            view = _leaf_view(p)
            if view is not None:
                rows, cols = view
                block = _ok._leaf_block(rows, cols, 5)
                rows_p = -(-rows // block) * block
                # model_dtype=None with store_model=False: emitting
                # the model copy here would be a dead ~2 B/param write
                p2, c2 = _ok.lamb_leaf_stage2(
                    _padded(p, rows, cols, rows_p),
                    _padded(m2, rows, cols, rows_p),
                    _padded(v2, rows, cols, rows_p),
                    [eps, bc1, bc2, lr * r, live],
                    float(wd), self.adam_w_mode,
                    self.compute_dtype if state.model is not None else None,
                )
                return (
                    p2[:rows].reshape(p.shape),
                    c2[:rows].reshape(p.shape) if c2 is not None else None,
                )
            u = _u(m2, v2, p, wd)
            p2 = p - lr * r * u
            p2 = jnp.where(ok, p2, p)
            return (
                p2,
                p2.astype(self.compute_dtype)
                if state.model is not None
                else None,
            )

        out_b = jax.tree_util.tree_map(
            stage_b, state.master, new_m, new_v, wd_tree, ratios
        )
        master2, model2 = c.unzip_tree(state.master, out_b, 2)

        new_state = MixedPrecisionState(
            count=state.count + ok.astype(jnp.int32),
            model=model2 if state.model is not None else None,
            master=master2,
            m=new_m,
            v=new_v,
        )
        return new_state, found_inf
