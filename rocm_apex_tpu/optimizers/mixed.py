"""Mixed-precision training state: bf16 model params + fp32 masters.

The reference's performance architecture for mixed precision keeps TWO
copies of the model — low-precision params the model computes with and
fp32 masters the optimizer updates, the update writing the low-precision
copy out in the same kernel (reference:
apex/amp/_process_optimizer.py:28-90 master-weight management,
apex/optimizers/fused_sgd.py depth-3 lists with fp16 copy-out,
apex/contrib/optimizers/distributed_fused_adam.py fp32 shards +
all-gathered fp16 params). This module is that architecture as a
functional train state:

    opt    = MixedPrecisionAdam(...)
    state  = opt.init(params_fp32)
    ...
    loss, grads = value_and_grad(loss_fn)(state.model)   # bf16 tree
    state = opt.step(state, grads, grad_scale=1/S, skip=skip)

**Why the update is XLA-fused tree math, not the packed Pallas kernel.**
The CUDA reference packs tensor lists into flat buffers because a kernel
launch per tensor dominates there (csrc/multi_tensor_apply.cuh). On TPU
the measured reality is the opposite: (8,128)-tiled 2-D arrays do NOT
linearize for free, so every pack/unpack of the parameter set is a
physical relayout — profiled at ~20 ms/step on a 134M-param GPT (the
gradient-pack loop fusion ran at 27 GB/s against an >800 GB/s chip),
while XLA fuses the whole per-leaf Adam update into a handful of
bandwidth-bound fusions with zero packing traffic. XLA fusion IS the
multi-tensor-apply of this hardware. The packed Pallas kernels remain
the substrate where packing is structurally required — the row-sharded
ZeRO optimizers (contrib/optimizers/distributed.py) and the
multi_tensor parity layer (ops/multi_tensor.py).

Skip-step (dynamic loss scaling) folds into the update as a select on
every buffer being written anyway — the jit-safe analogue of the
reference's optimizer.step no-op patch (apex/amp/handle.py:128-154).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from rocm_apex_tpu.optimizers import _common as c

__all__ = ["MixedPrecisionAdam", "MixedPrecisionState"]


class MixedPrecisionState(NamedTuple):
    count: jnp.ndarray
    model: Any   # compute-dtype param tree (feed to model.apply)
    master: Any  # fp32 master tree
    m: Any
    v: Any


class MixedPrecisionAdam:
    """Fused Adam/AdamW over mixed-precision train state.

    Hyperparameters match `fused_adam` / the reference
    (apex/optimizers/fused_adam.py:20-60); `compute_dtype` is the model
    params' dtype (bf16 = the O5/O2 recipe). `weight_decay_mask` is a
    bool pytree (True = decay), the functional stand-in for torch param
    groups.
    """

    def __init__(
        self,
        learning_rate: c.ScalarOrSchedule = 1e-3,
        *,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        weight_decay_mask: Optional[Any] = None,
        compute_dtype: jnp.dtype = jnp.bfloat16,
    ):
        self.learning_rate = learning_rate
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.weight_decay_mask = weight_decay_mask
        self.compute_dtype = compute_dtype

    def init(self, params) -> MixedPrecisionState:
        """`params` may be fp32 (preferred: they seed the masters
        exactly) or already in compute dtype."""
        master = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), params
        )
        model = jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype), master
        )
        zeros = jax.tree_util.tree_map(jnp.zeros_like, master)
        return MixedPrecisionState(
            count=jnp.zeros((), jnp.int32),
            model=model,
            master=master,
            m=zeros,
            v=jax.tree_util.tree_map(jnp.zeros_like, master),
        )

    def model_params(self, state: MixedPrecisionState):
        """The compute-dtype tree for `model.apply` (== state.model)."""
        return state.model

    def step(
        self,
        state: MixedPrecisionState,
        grads,
        *,
        grad_scale=None,
        skip=None,
    ) -> MixedPrecisionState:
        """One fused update. `grads` are w.r.t. the compute-dtype params
        (`state.model`); `grad_scale` (1/loss_scale) fuses the unscale;
        `skip` freezes every buffer when True."""
        b1, b2, eps = self.beta1, self.beta2, self.eps
        live_t = (state.count + 1).astype(jnp.float32)
        lr = c.resolve_lr(self.learning_rate, state.count + 1)
        if self.bias_correction:
            bc1 = 1.0 - b1**live_t
            bc2 = 1.0 - b2**live_t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        if skip is None:
            live = jnp.asarray(1.0, jnp.float32)
            count = state.count + 1
        else:
            live = 1.0 - jnp.asarray(skip, jnp.float32)
            count = state.count + live.astype(jnp.int32)

        wd_tree = c.wd_tree(
            state.master, self.weight_decay, self.weight_decay_mask
        )

        def upd(p, g, m, v, wd):
            gf = g.astype(jnp.float32) * gs
            if not self.adam_w_mode:  # L2 mode: decay into the gradient
                gf = gf + wd * p
            m2 = b1 * m + (1.0 - b1) * gf
            v2 = b2 * v + (1.0 - b2) * gf * gf
            u = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if self.adam_w_mode:  # AdamW: decoupled decay
                u = u + wd * p
            p2 = p - lr * u
            # jnp.where, not an arithmetic blend: skipped steps carry
            # inf/nan in p2, and inf * 0.0 == nan would poison p
            on = live > 0.0
            return (
                jnp.where(on, p2, p),
                jnp.where(on, m2, m),
                jnp.where(on, v2, v),
            )

        out = jax.tree_util.tree_map(
            upd, state.master, grads, state.m, state.v, wd_tree
        )
        master2, m2, v2 = c.unzip_tree(state.master, out, 3)
        return MixedPrecisionState(
            count=count,
            model=jax.tree_util.tree_map(
                lambda x: x.astype(self.compute_dtype), master2
            ),
            master=master2,
            m=m2,
            v=v2,
        )

    def step_and_probe(
        self,
        state: MixedPrecisionState,
        grads,
        *,
        grad_scale=None,
    ):
        """`step` with the overflow probe fused into the update pass.

        Returns ``(new_state, found_inf)``. A standalone
        `all_finite(grads)` probe costs a full extra pass over the
        gradients as dozens of separate reduce kernels (~18 ms/step
        measured on the 134M GPT); here each leaf's fp32 sum rides the
        update fusion that already reads the gradient, and the
        skip-select applies to the provisional outputs afterwards —
        overflow semantics identical to probe-then-skip (reference:
        the in-kernel noop_flag of multi_tensor_scale,
        csrc/multi_tensor_scale_kernel.cu:30-136)."""
        b1, b2, eps = self.beta1, self.beta2, self.eps
        live_t = (state.count + 1).astype(jnp.float32)
        lr = c.resolve_lr(self.learning_rate, state.count + 1)
        if self.bias_correction:
            bc1 = 1.0 - b1**live_t
            bc2 = 1.0 - b2**live_t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd_tree = c.wd_tree(
            state.master, self.weight_decay, self.weight_decay_mask
        )

        def upd(p, g, m, v, wd):
            gf = g.astype(jnp.float32) * gs
            probe = jnp.sum(gf)  # fused with the pass that reads gf
            if not self.adam_w_mode:
                gf = gf + wd * p
            m2 = b1 * m + (1.0 - b1) * gf
            v2 = b2 * v + (1.0 - b2) * gf * gf
            u = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if self.adam_w_mode:
                u = u + wd * p
            return (p - lr * u, m2, v2, probe)

        out = jax.tree_util.tree_map(
            upd, state.master, grads, state.m, state.v, wd_tree
        )
        new_master, new_m, new_v, probes = c.unzip_tree(
            state.master, out, 4
        )
        found_inf = ~jnp.isfinite(
            sum(jax.tree_util.tree_leaves(probes))
        )
        ok = ~found_inf

        def sel(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, old
            )

        master2 = sel(new_master, state.master)
        new_state = MixedPrecisionState(
            count=state.count + ok.astype(jnp.int32),
            model=jax.tree_util.tree_map(
                lambda x: x.astype(self.compute_dtype), master2
            ),
            master=master2,
            m=sel(new_m, state.m),
            v=sel(new_v, state.v),
        )
        return new_state, found_inf
