"""Mixed-precision LAMB with in-step grad-scaler integration.

TPU-native rebuild of `FusedMixedPrecisionLamb` (reference:
apex/optimizers/fused_mixed_precision_lamb.py:8-256 +
csrc/multi_tensor_lamb_mp.cu:496): LAMB that operates directly on mixed
fp32/bf16/fp16 param pytrees, keeps `lr`/`step` as device scalars, and
consumes the loss scaler's `inv_scale`/`found_inf` inside the step — the
step counter only advances on non-overflow steps and a skipped step
leaves params and moments untouched (the reference's
`_step_supports_amp_scaling` contract).
"""

from typing import Any, Optional, Tuple

import jax.numpy as jnp

from rocm_apex_tpu.optimizers import _common as c
from rocm_apex_tpu.optimizers.fused_lamb import FusedLAMBState, fused_lamb

__all__ = ["FusedMixedPrecisionLamb"]


class FusedMixedPrecisionLamb:
    """Scaler-aware LAMB facade (reference constructor :8-74)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError(
                "FusedMixedPrecisionLamb does not support the AMSGrad variant."
            )
        self._kw = dict(
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            weight_decay=weight_decay,
            grad_averaging=grad_averaging,
            adam_w_mode=adam_w_mode,
            max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb,
            weight_decay_mask=weight_decay_mask,
        )
        self.lr = lr

    def init(self, params) -> FusedLAMBState:
        return fused_lamb(self.lr, **self._kw).init(params)

    def step(
        self,
        params,
        grads,
        state: FusedLAMBState,
        *,
        inv_scale=None,
        found_inf=None,
    ):
        """One step; grads may still carry the loss scale.

        `inv_scale` (1/loss_scale) fuses the unscale into the update
        kernels; `found_inf` makes the whole step a no-op (params, moments
        AND the step count — reference fused_mixed_precision_lamb.py:140-256
        advances `step` only when `found_inf == 0`).
        """
        gs = 1.0 if inv_scale is None else inv_scale
        opt = c.FusedOptimizer(fused_lamb(self.lr, grad_scale=gs, **self._kw))
        skip = None if found_inf is None else jnp.asarray(found_inf)
        return opt.step(params, grads, state, skip=skip)
