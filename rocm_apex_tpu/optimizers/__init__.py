"""Fused optimizers (reference: apex/optimizers/__init__.py:1-6).

Each exists in two shapes: an optax `GradientTransformation` factory
(`fused_adam(...)`) for functional pipelines, and an apex-style class
(`FusedAdam`) with `init`/`step`. All run one Pallas update kernel per
dtype bucket over packed pytree buffers (ops/packing.py, ops/optim_kernels.py).
"""

from rocm_apex_tpu.optimizers.fused_adam import FusedAdam, FusedAdamState, fused_adam
from rocm_apex_tpu.optimizers.fused_adagrad import (
    FusedAdagrad,
    FusedAdagradState,
    fused_adagrad,
)
from rocm_apex_tpu.optimizers.fused_lamb import FusedLAMB, FusedLAMBState, fused_lamb
from rocm_apex_tpu.optimizers.fused_mixed_precision_lamb import (
    FusedMixedPrecisionLamb,
)
from rocm_apex_tpu.optimizers.fused_novograd import (
    FusedNovoGrad,
    FusedNovoGradState,
    fused_novograd,
)
from rocm_apex_tpu.optimizers.fused_sgd import FusedSGD, FusedSGDState, fused_sgd
from rocm_apex_tpu.optimizers.packed import (
    PackedAdamState,
    PackedLAMBState,
    PackedOptimizerStep,
    PackedStepState,
    packed_adam,
    packed_lamb,
)

__all__ = [
    "FusedAdam",
    "FusedAdamState",
    "fused_adam",
    "FusedAdagrad",
    "FusedAdagradState",
    "fused_adagrad",
    "FusedLAMB",
    "FusedLAMBState",
    "fused_lamb",
    "FusedMixedPrecisionLamb",
    "FusedNovoGrad",
    "FusedNovoGradState",
    "fused_novograd",
    "FusedSGD",
    "FusedSGDState",
    "fused_sgd",
    "PackedAdamState",
    "PackedLAMBState",
    "PackedOptimizerStep",
    "PackedStepState",
    "packed_adam",
    "packed_lamb",
]
