"""Fused Adam/AdamW as XLA-tree-fused per-leaf updates.

TPU-native rebuild of `FusedAdam` (reference:
apex/optimizers/fused_adam.py:4-173 + csrc/multi_tensor_adam.cu:24-171):
fp32 math, `adam_w_mode` switching between L2 and decoupled decay,
optional bias correction, and bf16/fp16 param support (reference
fused_adam.py:134-145 — the ROCm fork's bf16 path is primary here).

**Why tree-fused math by default, not the packed Pallas kernels.** The
CUDA reference packs tensor lists into flat buffers because a kernel
launch per tensor dominates there (csrc/multi_tensor_apply.cuh). On TPU
the measured reality is the opposite: (8,128)-tiled arrays do not
linearize for free, so packing params+grads every step is a ~20 ms/step
physical relayout on a 134M-param model (optimizers/mixed.py header has
the numbers), while XLA fuses the whole per-leaf update into a handful
of bandwidth-bound fusions with zero packing traffic. `packed=True`
opts into the multi_tensor_apply pipeline (optimizers/packed.py): the
update phase becomes O(dtype-groups) traced equations instead of
O(leaves), moments live packed, and overflow skipping folds into the
kernel — the right trade when fusion granularity, audit-stable program
shape, or shardability dominate (the row-sharded ZeRO optimizers in
contrib/optimizers/distributed.py always run packed). docs/perf.md
§"The optimizer step" quantifies when each side wins.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_adam", "FusedAdam", "FusedAdamState"]


class FusedAdamState(NamedTuple):
    count: jnp.ndarray  # i32 step counter
    m: Any  # fp32 exp_avg tree
    v: Any  # fp32 exp_avg_sq tree


def fused_adam(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
    packed: bool = False,
) -> optax.GradientTransformation:
    """Build the fused Adam gradient transformation.

    Hyperparameter semantics match the reference exactly
    (reference: apex/optimizers/fused_adam.py:20-60): `adam_w_mode=True`
    is AdamW (decoupled decay), False folds decay into the gradient.
    `grad_scale` (1/loss_scale) fuses gradient unscaling into the update
    pass. `weight_decay_mask` replaces torch param groups for
    decay-exempting biases/norm params. `packed=True` runs the same
    math over flat dtype-group buffers (optimizers/packed.py): same
    updates bit-for-bit on fp32, O(dtype-groups) traced equations, and
    a kernel-level found_inf no-op on overflow.
    """
    if packed:
        from rocm_apex_tpu.optimizers.packed import packed_adam

        return packed_adam(
            learning_rate,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            adam_w_mode=adam_w_mode,
            weight_decay=weight_decay,
            weight_decay_mask=weight_decay_mask,
            grad_scale=grad_scale,
        )
    beta1, beta2 = betas

    def init_fn(params):
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            m=c.zeros_like_f32(params),
            v=c.zeros_like_f32(params),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params in update()")
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        t = count.astype(jnp.float32)
        if bias_correction:  # reference fused_adam.py:117-127
            bc1 = 1.0 - beta1**t
            bc2 = 1.0 - beta2**t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = jnp.asarray(
            1.0 if grad_scale is None else grad_scale, jnp.float32
        )
        wd = c.wd_tree(params, weight_decay, weight_decay_mask)

        def upd(p, g, m, v, wd):
            # mirrors AdamFunctor (csrc/multi_tensor_adam.cu:24-171),
            # fp32 in-register math regardless of storage dtype
            pf = p.astype(jnp.float32)
            gf = g.astype(jnp.float32) * gs
            if not adam_w_mode:  # L2 mode folds decay into the gradient
                gf = gf + wd * pf
            m2 = beta1 * m + (1.0 - beta1) * gf
            v2 = beta2 * v + (1.0 - beta2) * gf * gf
            u = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if adam_w_mode:  # decoupled decay (AdamW)
                u = u + wd * pf
            # fp32 delta: optax.apply_updates adds in fp32 and casts
            # back to the param dtype (same contract as the packed path)
            return -lr * u, m2, v2

        out = jax.tree_util.tree_map(upd, params, grads, state.m, state.v, wd)
        updates, m2, v2 = c.unzip_tree(params, out, 3)
        return updates, FusedAdamState(count=count, m=m2, v=v2)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdam(c.FusedOptimizer):
    """Class facade mirroring the reference constructor signature
    (reference: apex/optimizers/fused_adam.py:4-80). `amsgrad` is
    rejected exactly like the reference (:79-80)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        super().__init__(
            fused_adam(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                adam_w_mode=adam_w_mode,
                weight_decay=weight_decay,
                weight_decay_mask=weight_decay_mask,
            )
        )
