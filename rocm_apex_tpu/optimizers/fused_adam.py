"""Fused Adam/AdamW over packed buffers.

TPU-native rebuild of `FusedAdam` (reference:
apex/optimizers/fused_adam.py:4-173 + csrc/multi_tensor_adam.cu:24-171):
one Pallas launch per dtype bucket, fp32 math, `adam_w_mode` switching
between L2 and decoupled decay, optional bias correction, and bf16/fp16
param support (reference fused_adam.py:134-145 — the ROCm fork's bf16
path is primary here).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import optax

from rocm_apex_tpu.ops import optim_kernels
from rocm_apex_tpu.optimizers import _common as c

__all__ = ["fused_adam", "FusedAdam", "FusedAdamState"]


class FusedAdamState(NamedTuple):
    count: jnp.ndarray  # i32 step counter
    m: Tuple[jnp.ndarray, ...]  # fp32 exp_avg group buffers
    v: Tuple[jnp.ndarray, ...]  # fp32 exp_avg_sq group buffers


def fused_adam(
    learning_rate: c.ScalarOrSchedule = 1e-3,
    *,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    weight_decay_mask: Optional[Any] = None,
    grad_scale: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Build the fused Adam gradient transformation.

    Hyperparameter semantics match the reference exactly
    (reference: apex/optimizers/fused_adam.py:20-60): `adam_w_mode=True`
    is AdamW (decoupled decay), False folds decay into the gradient.
    `grad_scale` (1/loss_scale) fuses gradient unscaling into the update
    kernel. `weight_decay_mask` replaces torch param groups for
    decay-exempting biases/norm params.
    """
    beta1, beta2 = betas

    def init_fn(params):
        spec = c.build_pack_spec(params)
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            m=c.zero_group_buffers(spec),
            v=c.zero_group_buffers(spec),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params in update()")
        spec, pp, pg = c.pack_params_and_grads(params, grads)
        count = state.count + 1
        lr = c.resolve_lr(learning_rate, count)
        t = count.astype(jnp.float32)
        if bias_correction:  # reference fused_adam.py:117-127
            bc1 = 1.0 - beta1**t
            bc2 = 1.0 - beta2**t
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        gs = 1.0 if grad_scale is None else grad_scale
        wd_cols = c.wd_columns(spec, weight_decay, weight_decay_mask)

        deltas, new_m, new_v = [], [], []
        for pbuf, gbuf, mbuf, vbuf, wd in zip(
            pp.buffers, pg.buffers, state.m, state.v, wd_cols
        ):
            d, m2, v2 = optim_kernels.adam_update(
                pbuf,
                gbuf,
                mbuf,
                vbuf,
                wd,
                [lr, beta1, beta2, eps, bc1, bc2, gs],
                adam_w_mode,
            )
            deltas.append(d)
            new_m.append(m2)
            new_v.append(v2)

        updates = c.deltas_to_updates(spec, deltas)
        return updates, FusedAdamState(count=count, m=tuple(new_m), v=tuple(new_v))

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdam(c.FusedOptimizer):
    """Class facade mirroring the reference constructor signature
    (reference: apex/optimizers/fused_adam.py:4-80). `amsgrad` is
    rejected exactly like the reference (:79-80)."""

    def __init__(
        self,
        lr: c.ScalarOrSchedule = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        weight_decay_mask: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        super().__init__(
            fused_adam(
                lr,
                bias_correction=bias_correction,
                betas=betas,
                eps=eps,
                adam_w_mode=adam_w_mode,
                weight_decay=weight_decay,
                weight_decay_mask=weight_decay_mask,
            )
        )
