"""ctypes binding for the host-native runtime ops (csrc/host_ops.cpp).

The analogue of importing the reference's compiled extensions with
python fallbacks on failure (reference: apex/parallel/distributed.py:
13-33 imports apex_C.flatten and falls back to torch._utils). The
shared library is built on first import with g++ (cached next to the
source); any failure leaves the numpy fallbacks active and
``available = False`` (the multi_tensor_applier.available pattern,
apex/multi_tensor_apply/multi_tensor_apply.py:3-30).
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = [
    "available",
    "flatten",
    "unflatten",
    "fast_collate",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "..", "..", "csrc", "host_ops.cpp")
_SO = os.path.join(_HERE, "_host_ops.so")
_lib = None
_lock = threading.Lock()
available = False


def _build_and_load():
    global _lib, available
    with _lock:
        if _lib is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            ):
                subprocess.run(
                    [
                        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        "-pthread", _SRC, "-o", _SO,
                    ],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
            lib.apex_tpu_flatten.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int,
            ]
            lib.apex_tpu_unflatten.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
            ]
            lib.apex_tpu_fast_collate.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ]
            _lib = lib
            available = True
        except Exception:
            _lib = False  # build failed: numpy fallbacks stay active
            available = False
    return _lib


_DEFAULT_THREADS = min(8, os.cpu_count() or 1)


def _ptr_array(arrays):
    ptrs = (ctypes.c_void_p * len(arrays))()
    for i, a in enumerate(arrays):
        ptrs[i] = a.ctypes.data_as(ctypes.c_void_p)
    return ptrs


def flatten(arrays, threads: int = _DEFAULT_THREADS) -> np.ndarray:
    """Concatenate same-dtype numpy arrays into one flat buffer
    (reference apex_C.flatten)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    if not arrays:
        return np.empty((0,), np.float32)
    dtype = arrays[0].dtype
    if any(a.dtype != dtype for a in arrays):
        raise TypeError("flatten requires uniform dtype")
    lib = _build_and_load()
    total = sum(a.size for a in arrays)
    out = np.empty((total,), dtype)
    if not lib:
        np.concatenate([a.ravel() for a in arrays], out=out)
        return out
    sizes = (ctypes.c_int64 * len(arrays))(*[a.size for a in arrays])
    lib.apex_tpu_flatten(
        _ptr_array(arrays), sizes, len(arrays), dtype.itemsize,
        out.ctypes.data_as(ctypes.c_void_p), threads,
    )
    return out


def unflatten(flat: np.ndarray, shapes, threads: int = _DEFAULT_THREADS):
    """Split a flat buffer back into arrays of `shapes`
    (reference apex_C.unflatten)."""
    flat = np.ascontiguousarray(flat)
    outs = [np.empty(s, flat.dtype) for s in shapes]
    lib = _build_and_load()
    if not lib:
        off = 0
        for o in outs:
            o.ravel()[:] = flat[off : off + o.size]
            off += o.size
        return outs
    sizes = (ctypes.c_int64 * len(outs))(*[o.size for o in outs])
    lib.apex_tpu_unflatten(
        flat.ctypes.data_as(ctypes.c_void_p), sizes, len(outs),
        flat.dtype.itemsize, _ptr_array(outs), threads,
    )
    return outs


def fast_collate(
    images,
    mean=None,
    std=None,
    threads: int = _DEFAULT_THREADS,
) -> np.ndarray:
    """uint8 HWC images -> float32 NHWC batch, optional per-channel
    (x/255 - mean)/std (reference: examples/imagenet fast_collate +
    normalization deferred to the prefetcher)."""
    images = [np.ascontiguousarray(im, np.uint8) for im in images]
    n = len(images)
    if n == 0:
        return np.empty((0,), np.float32)
    h, w, c = images[0].shape
    if any(im.shape != (h, w, c) for im in images):
        raise ValueError("fast_collate requires uniform image shapes")
    out = np.empty((n, h, w, c), np.float32)
    lib = _build_and_load()
    if not lib:
        batch = np.stack(images).astype(np.float32)
        if mean is not None and std is not None:
            batch = (batch / 255.0 - np.asarray(mean, np.float32)) / np.asarray(
                std, np.float32
            )
        out[...] = batch
        return out
    mean_p = std_p = None
    if mean is not None and std is not None:
        mean_a = np.ascontiguousarray(mean, np.float32)
        std_a = np.ascontiguousarray(std, np.float32)
        mean_p = mean_a.ctypes.data_as(ctypes.c_void_p)
        std_p = std_a.ctypes.data_as(ctypes.c_void_p)
    lib.apex_tpu_fast_collate(
        _ptr_array(images), n, h, w, c,
        out.ctypes.data_as(ctypes.c_void_p), mean_p, std_p, threads,
    )
    return out
