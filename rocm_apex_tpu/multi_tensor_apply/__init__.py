"""multi_tensor_applier: the reference's kernel-glue entry point.

Reference: apex/multi_tensor_apply/multi_tensor_apply.py:3-30 —
``multi_tensor_applier(op, noop_flag_buffer, tensor_lists, *args)``
dispatching chunked CUDA launches, with ``available`` set by the amp_C
import. Here `op` is one of the packed-pytree ops from
ops/multi_tensor.py (which subsume the chunking: one Pallas call over
the whole packed set) and the noop flag is the returned overflow flag
— carried functionally instead of written into a caller buffer.

The op registry mirrors the amp_C pybind list
(csrc/amp_C_frontend.cpp:147-174) where a TPU equivalent exists:
    multi_tensor_scale, multi_tensor_axpby, multi_tensor_l2norm
(the optimizer functors live behind rocm_apex_tpu.optimizers instead).
"""

from typing import Any, Sequence

from rocm_apex_tpu.ops import multi_tensor as _mt

__all__ = [
    "multi_tensor_applier",
    "MultiTensorApply",
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "available",
]

available = True  # no extension import to fail: Pallas ships in-tree


def multi_tensor_scale(tensor_lists: Sequence[Any], scale):
    """[src_list, dst_list] -> (dst_tree, overflow_flag)
    (reference: csrc/multi_tensor_scale_kernel.cu semantics — dst dtype
    follows the dst list; inf/nan sets the flag)."""
    src, dst = tensor_lists
    out_dtype = None
    import jax

    leaves = jax.tree_util.tree_leaves(dst)
    if leaves:
        out_dtype = leaves[0].dtype
    return _mt.scale(src, scale, out_dtype=out_dtype)


def multi_tensor_axpby(tensor_lists: Sequence[Any], a, b):
    """[x_list, y_list, out_list] -> (out_tree, overflow_flag)."""
    x, y, _ = tensor_lists
    return _mt.axpby(x, y, a, b)


def multi_tensor_l2norm(tensor_lists: Sequence[Any], per_tensor: bool = False):
    """[list] -> (global_norm, per_tensor_norms | None)
    (reference: csrc/multi_tensor_l2norm_kernel.cu)."""
    (xs,) = tensor_lists
    return _mt.l2norm(xs, per_tensor=per_tensor)


def multi_tensor_applier(op, noop_flag_buffer, tensor_lists, *args):
    """Dispatch `op` over the tensor lists (reference signature kept;
    `noop_flag_buffer` is ignored — the overflow flag is returned by
    the op, chunk_size bookkeeping does not exist on TPU)."""
    del noop_flag_buffer
    return op(tensor_lists, *args)


class MultiTensorApply:
    """Class form (reference multi_tensor_apply.py:10-30)."""

    available = True

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size  # accepted for parity; unused

    def __call__(self, op, noop_flag_buffer, tensor_lists, *args):
        return multi_tensor_applier(op, noop_flag_buffer, tensor_lists, *args)
