"""Weight-norm reparameterization.

Reference: apex/reparameterization/ — `apply_weight_norm`
(__init__.py:4), `WeightNorm` (weight_norm.py:22), `Reparameterization`
(reparameterization.py), implemented there as fp16-aware forward
pre-hooks rewriting module weights. Functionally: a parameter tree is
split into direction ``v`` and magnitude ``g`` with
``w = g * v / ||v||`` (norm over all dims but `dim`), reconstructed
before each apply — the hook becomes an explicit transform pair, which
is also autodiff-correct for free.

    wn_params = apply_weight_norm(params, names=["kernel"])
    params    = remove_weight_norm(wn_params)   # -> plain w tree
    # train on wn_params; inside the loss:
    #   model.apply(reconstruct(wn_params), x)
"""

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "apply_weight_norm",
    "remove_weight_norm",
    "reconstruct",
    "weight_norm",
]

_EPS = 1e-12


def _norm_keep(v: jnp.ndarray, dim: int) -> jnp.ndarray:
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2, axis=axes, keepdims=True))


def weight_norm(v: jnp.ndarray, g: jnp.ndarray, dim: int = 0) -> jnp.ndarray:
    """w = g * v / ||v|| (reference weight_norm.py:22-80; norms in fp32
    like the fp16-aware hook)."""
    return (g * (v.astype(jnp.float32) / (_norm_keep(v, dim) + _EPS))).astype(
        v.dtype
    )


def _is_target(path, names: Optional[Sequence[str]]):
    if names is None:
        return True
    last = str(path[-1].key) if hasattr(path[-1], "key") else str(path[-1])
    return last in names


def apply_weight_norm(
    params: Any, names: Optional[Sequence[str]] = None, dim: int = 0
) -> Any:
    """Split matching >=2D leaves into {"v", "g"} subtrees
    (reference: apply_weight_norm's recursive hook installation,
    reparameterization.py)."""

    def one(path, leaf):
        if (
            hasattr(leaf, "ndim")
            and leaf.ndim >= 2
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and _is_target(path, names)
        ):
            return {"v": leaf, "g": _norm_keep(leaf, dim).astype(leaf.dtype)}
        return leaf

    return jax.tree_util.tree_map_with_path(one, params)


def _is_wn_leaf(x):
    return isinstance(x, dict) and set(x.keys()) == {"v", "g"}


def reconstruct(wn_params: Any, dim: int = 0) -> Any:
    """{"v","g"} subtrees -> plain weights (called inside the loss; the
    analogue of the forward pre-hook recomputing w each forward)."""
    return jax.tree_util.tree_map(
        lambda x: weight_norm(x["v"], x["g"], dim) if _is_wn_leaf(x) else x,
        wn_params,
        is_leaf=_is_wn_leaf,
    )


def remove_weight_norm(wn_params: Any, dim: int = 0) -> Any:
    """Collapse back to plain weights (reference remove_weight_norm)."""
    return reconstruct(wn_params, dim)
