"""Fused dense layers (reference: apex/fused_dense/)."""

from rocm_apex_tpu.fused_dense.fused_dense import (  # noqa: F401
    FusedDense,
    FusedDenseGeluDense,
    fused_dense_function,
    fused_dense_gelu_dense_function,
)

__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense_function",
    "fused_dense_gelu_dense_function",
]
