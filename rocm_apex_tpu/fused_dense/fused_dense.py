"""Fused linear(+bias) and linear+bias+GeLU+linear.

Rebuild of the reference fused_dense (reference:
apex/fused_dense/fused_dense.py:53-86; kernels
csrc/fused_dense_cuda.cu:18-260, whose perf path is cuBLASLt fused
epilogues `CUBLASLT_EPILOGUE_BIAS` / `_GELU`). XLA emits the same
fusion from the plain expression: the bias add and GeLU ride the MXU
matmul epilogue, and `jax.grad` of the chain reproduces the hand-rolled
`linear_gelu_linear_backward`. The module layer carries the reference
API (weight layout (out, in), bias flags and their constraints).
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense_function",
    "fused_dense_gelu_dense_function",
]


def fused_dense_function(x, weight, bias: Optional[jnp.ndarray] = None):
    """x @ W^T + b (reference fused_dense.py fused_dense_function)."""
    y = jnp.dot(x, weight.T, preferred_element_type=x.dtype)
    return y if bias is None else y + bias


def fused_dense_gelu_dense_function(x, w1, b1, w2, b2):
    """linear+bias -> GeLU -> linear+bias (reference
    FusedDenseGeluDenseFunc)."""
    h = jax.nn.gelu(jnp.dot(x, w1.T, preferred_element_type=x.dtype) + b1)
    return jnp.dot(h, w2.T, preferred_element_type=x.dtype) + b2


class FusedDense(nn.Module):
    """Reference: apex/fused_dense/fused_dense.py:53-68."""

    in_features: int
    out_features: int
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param(
            "weight",
            nn.initializers.lecun_normal(),
            (self.out_features, self.in_features),
            self.param_dtype,
        )
        b = (
            self.param(
                "bias",
                nn.initializers.zeros_init(),
                (self.out_features,),
                self.param_dtype,
            )
            if self.use_bias
            else None
        )
        x = x.astype(self.dtype)
        return fused_dense_function(
            x, w.astype(self.dtype), None if b is None else b.astype(self.dtype)
        )


class FusedDenseGeluDense(nn.Module):
    """Reference: apex/fused_dense/fused_dense.py:71-86 (bias
    mandatory there too)."""

    in_features: int
    intermediate_features: int
    out_features: int
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if not self.use_bias:
            raise AssertionError(
                "DenseGeluDense module without bias is currently not supported"
            )
        w1 = self.param(
            "weight1",
            nn.initializers.lecun_normal(),
            (self.intermediate_features, self.in_features),
            self.param_dtype,
        )
        b1 = self.param(
            "bias1", nn.initializers.zeros_init(),
            (self.intermediate_features,), self.param_dtype,
        )
        w2 = self.param(
            "weight2",
            nn.initializers.lecun_normal(),
            (self.out_features, self.intermediate_features),
            self.param_dtype,
        )
        b2 = self.param(
            "bias2", nn.initializers.zeros_init(),
            (self.out_features,), self.param_dtype,
        )
        x = x.astype(self.dtype)
        return fused_dense_gelu_dense_function(
            x,
            w1.astype(self.dtype), b1.astype(self.dtype),
            w2.astype(self.dtype), b2.astype(self.dtype),
        )
