"""Functional loss scaler.

TPU-native redesign of the reference `LossScaler`
(reference: apex/amp/scaler.py:42-226). The reference mutates a python
object and reads a device-side overflow buffer with `.item()` (a D2H
sync); here the scaler state is a tiny pytree carried in the train state
so the whole unscale/check/update/skip sequence stays inside one jitted
step — no host sync, and the skip-step is a `lax.cond` instead of the
reference's runtime `optimizer.step` patching (apex/amp/handle.py:128-154).

Constants match the reference exactly (scaler.py:47-63, 206-226):
init_scale=2**16, scale_factor=2, scale_window=2000 unskipped steps,
backoff ÷2 on overflow, max_loss_scale=2**24, optional min clamp.

The overflow probe fuses into the unscale as a `jnp.isfinite` reduction —
the analogue of the fused `multi_tensor_scale` kernel's noop_flag
(reference: csrc/multi_tensor_scale_kernel.cu:30-136); see also
ops/multi_tensor.py for the Pallas fused path.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

__all__ = ["LossScaler", "ScalerState", "all_finite"]


class ScalerState(NamedTuple):
    """Dynamic scaler state; a pytree of three scalars."""

    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray  # i32: consecutive non-overflow steps
    overflows: jnp.ndarray  # i32: total skipped steps (observability)


def all_finite(tree: Any) -> jnp.ndarray:
    """True iff every element of every floating leaf is finite.

    Probes via per-leaf fp32 sums — any inf/nan poisons the total (inf
    meeting -inf yields nan, still non-finite). This is the reference's
    own probe (reference: scaler.py:6-19 `float(t.sum())` overflow
    check) and is a single bandwidth-bound reduction, where a literal
    `isfinite().all()` materializes a bool tensor per leaf (measured
    ~20 ms on a 134M-param grad set vs <1 ms for the sums).
    """
    leaves = [
        x
        for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)
    ]
    if not leaves:
        return jnp.asarray(True)
    total = sum(jnp.sum(x.astype(jnp.float32)) for x in leaves)
    return jnp.isfinite(total)


class LossScaler:
    """Static scaler config; all methods are pure and jit-safe.

    ``loss_scale`` is a float for static scaling or "dynamic"
    (reference: scaler.py:47-63).
    """

    def __init__(
        self,
        loss_scale="dynamic",
        init_scale=2.0**16,
        scale_factor=2.0,
        scale_window=2000,
        min_loss_scale=None,
        max_loss_scale=2.0**24,
    ):
        self.dynamic = loss_scale == "dynamic"
        self._init_scale = (
            min(max_loss_scale, init_scale) if self.dynamic else float(loss_scale)
        )
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = max_loss_scale

    def init(self) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.asarray(self._init_scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            overflows=jnp.asarray(0, jnp.int32),
        )

    # -- the four pure operations --------------------------------------

    def scale(self, state: ScalerState, loss: jnp.ndarray) -> jnp.ndarray:
        """`loss.float() * loss_scale` (reference: handle.py:113)."""
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(self, state: ScalerState, grads: Any) -> Tuple[Any, jnp.ndarray]:
        """Unscale grads (out dtype fp32) and probe for inf/nan.

        Fuses the 1/scale multiply with the finite check, like the fused
        `multi_tensor_scale` unscale (reference: scaler.py:114-126).
        Returns ``(unscaled_grads, found_inf)``. For grads already in
        packed dtype-group buffers use `unscale_packed`, which folds the
        probe into the same Pallas pass as the multiply.
        """
        inv = 1.0 / state.loss_scale

        def _unscale(g):
            if jnp.issubdtype(g.dtype, jnp.inexact):
                return g.astype(jnp.float32) * inv
            return g

        unscaled = jax.tree_util.tree_map(_unscale, grads)
        found_inf = jnp.logical_not(all_finite(unscaled))
        return unscaled, found_inf

    def unscale_packed(
        self, state: ScalerState, packed_grads: Any
    ) -> Tuple[Any, jnp.ndarray]:
        """`unscale` over a `PackedTree` of grad buffers — exactly one
        fused Pallas pass per dtype buffer, emitting the fp32 unscaled
        buffer AND the inf/nan flag from the same read
        (ops/multi_tensor.py `scale_packed`). Unlike the tree `unscale`,
        there is no second `all_finite` reduction over the output: the
        probe rides the multiply, one reduction per dtype buffer total
        (the noop_flag contract of the fused multi_tensor_scale kernel,
        reference: csrc/multi_tensor_scale_kernel.cu:30-136).
        Returns ``(unscaled_packed_f32, found_inf)``.
        """
        from rocm_apex_tpu.ops.multi_tensor import scale_packed

        inv = 1.0 / state.loss_scale
        return scale_packed(packed_grads, inv, jnp.float32)

    def unscale_with_stashed(
        self, state: ScalerState, stashed: Any, grads: Any
    ) -> Tuple[Any, jnp.ndarray]:
        """out = stashed + grads/scale — the gradient-accumulation merge.

        Analogue of the fused axpby path used when fp32 grads from a
        previous backward are stashed (reference: scaler.py:160-198,
        apex/amp/_process_optimizer.py:142-207).
        """
        inv = 1.0 / state.loss_scale
        out = jax.tree_util.tree_map(
            lambda s, g: s.astype(jnp.float32) + g.astype(jnp.float32) * inv,
            stashed,
            grads,
        )
        found_inf = jnp.logical_not(all_finite(out))
        return out, found_inf

    def update(
        self, state: ScalerState, found_inf: jnp.ndarray
    ) -> Tuple[ScalerState, jnp.ndarray]:
        """Post-step scale update; returns ``(new_state, should_skip)``.

        Semantics of `update_scale` (reference: scaler.py:206-226): on
        overflow halve (clamped at min) and reset the window; after
        `scale_window` consecutive clean steps double (clamped at max).
        For a static scaler the scale never changes and steps are never
        skipped (matching the reference, which only skips when dynamic).
        """
        if not self.dynamic:
            return state, jnp.asarray(False)

        found_inf = jnp.asarray(found_inf)

        def on_overflow(s):
            new_scale = s.loss_scale / self.scale_factor
            if self.min_loss_scale is not None:
                new_scale = jnp.maximum(new_scale, self.min_loss_scale)
            return ScalerState(
                loss_scale=new_scale,
                unskipped=jnp.asarray(0, jnp.int32),
                overflows=s.overflows + 1,
            )

        def on_clean(s):
            unskipped = s.unskipped + 1
            grow = unskipped >= self.scale_window
            new_scale = jnp.where(
                grow,
                jnp.minimum(s.loss_scale * self.scale_factor, self.max_loss_scale),
                s.loss_scale,
            )
            return ScalerState(
                loss_scale=new_scale,
                unskipped=jnp.where(grow, 0, unskipped).astype(jnp.int32),
                overflows=s.overflows,
            )

        # select between the two branches instead of lax.cond: both are
        # a handful of scalar ops (evaluating both costs nothing), and
        # cond inside shard_map trips jax 0.4.37's branch-replication
        # checker ("mismatched replication types") when found_inf comes
        # off a collective — e.g. the model-parallel GradScaler's psum
        new_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(found_inf, a, b),
            on_overflow(state),
            on_clean(state),
        )
        return new_state, found_inf

    def loss_scale(self, state: ScalerState) -> jnp.ndarray:
        return state.loss_scale

    def telemetry(self, state: ScalerState, found_inf=None):
        """name→fp32-scalar dict of the scaler's observable state —
        the `monitor.Metrics.merge` / `monitor.FlightRecorder` input
        format (``overflows`` is already in `MetricsLogger`'s default
        ``last_value`` counter set). Pass the step's ``found_inf`` to
        make the skip decision itself part of the record: the flight
        recorder treats a set ``found_inf`` as an anomaly trigger and
        its dump then names the offending param group next to the
        scale the scaler is about to halve. Jit-safe (all entries are
        scalars riding the step outputs; no host sync here)."""
        out = {
            "loss_scale": state.loss_scale.astype(jnp.float32),
            "overflows": state.overflows.astype(jnp.float32),
            "unskipped": state.unskipped.astype(jnp.float32),
        }
        if found_inf is not None:
            out["found_inf"] = jnp.asarray(
                found_inf
            ).astype(jnp.float32)
        return out
