from rocm_apex_tpu.amp.lists import functional_overrides, jnp_overrides
