"""Alias namespace for parity with the reference's per-namespace lists
(reference: apex/amp/lists/functional_overrides.py). In JAX there is a
single op namespace, so this re-exports the canonical lists."""

from rocm_apex_tpu.amp.lists.jnp_overrides import (  # noqa: F401
    BANNED_FUNCS,
    BFLOAT16_FUNCS,
    CASTS,
    FP16_FUNCS,
    FP32_FUNCS,
    SEQUENCE_CASTS,
    is_fp32_op,
    is_low_precision_op,
)
