"""Cast lists: which op families run in low precision vs fp32.

The TPU analogue of the reference's per-namespace cast lists
(reference: apex/amp/lists/torch_overrides.py:7-48,
functional_overrides.py:17-37). In JAX the lists are *data consumed by
module implementations and the policy decorators*, not a patch target:
every fused module in this framework consults `is_low_precision_op` /
`is_fp32_op` to decide its compute dtype under an O1/O4 policy.

Low-precision list = MXU-friendly ops (matmul/conv families — exactly the
Tensor-Core list in the reference, torch_overrides.py:7-27 plus the bf16
list at :29-48). FP32 list = reductions and numerically-sensitive ops
(softmax/norm/loss families, torch_overrides.py:50-82).
"""

# MXU-eligible ops: run in policy compute dtype (fp16 under O1, bf16 under O4).
FP16_FUNCS = [
    "conv1d", "conv2d", "conv3d", "conv_transpose",
    "dot", "dot_general", "matmul", "einsum", "tensordot",
    "conv_general_dilated",
    "linear", "dense",
    "attention", "scaled_dot_product_attention",
]

# The ROCm fork's bf16 list mirrors the fp16 one (torch_overrides.py:29-48).
BFLOAT16_FUNCS = list(FP16_FUNCS)

# Numerically-sensitive ops: always fp32 inputs under O1/O4.
FP32_FUNCS = [
    "softmax", "log_softmax", "logsumexp",
    "layer_norm", "group_norm", "batch_norm", "normalize", "rms_norm",
    "cross_entropy", "nll_loss", "l1_loss", "mse_loss", "kl_div",
    "smooth_l1_loss", "cosine_similarity",
    "exp", "expm1", "log", "log1p", "log2", "log10",
    "pow", "rsqrt", "sqrt", "reciprocal",
    "sum", "mean", "prod", "cumsum", "cumprod", "var", "std", "norm",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "erf", "erfc", "erfinv", "gelu",
]

# Multi-arg promotion (widest dtype wins) — reference CASTS list.
CASTS = [
    "add", "subtract", "multiply", "divide", "true_divide",
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "maximum", "minimum", "atan2", "hypot", "nextafter",
    "where",
]

# Sequence promotion (cat/stack in the reference).
SEQUENCE_CASTS = ["concatenate", "stack", "hstack", "vstack", "dstack"]

# Ops that error under mixed precision in the reference (BANNED_FUNCS,
# functional_overrides.py). In JAX these simply require fp32 inputs; we
# record them so the policy layer can raise a helpful error.
BANNED_FUNCS = [
    ("binary_cross_entropy",
     "amp does not work out-of-the-box with binary_cross_entropy on "
     "low-precision logits: it requires the output of sigmoid and is "
     "unsafe to run in fp16/bf16. Use a fused sigmoid+BCE-with-logits "
     "formulation (optax.sigmoid_binary_cross_entropy) instead."),
]

_LOW = frozenset(FP16_FUNCS)
_F32 = frozenset(FP32_FUNCS)


def is_low_precision_op(name: str) -> bool:
    return name in _LOW


def is_fp32_op(name: str) -> bool:
    return name in _F32
