"""Function-level precision casting: the decorator/registry API.

The reference patches `torch` / `torch.Tensor` / `torch.nn.functional` in
place to insert casts (reference: apex/amp/amp.py:75-198) and offers
`half_function` / `float_function` / `promote_function` decorators for
user functions (amp.py:29-44). JAX has no mutable op registry — and needs
none: casting is explicit dataflow. This module provides the decorator
half of the API with identical semantics, driven by the *active policy*:

* `half_function(fn)`     — run fn with floating args cast to fp16
* `bfloat16_function(fn)` — ... cast to bf16 (ROCm-fork extension)
* `float_function(fn)`    — ... cast to fp32 (the "blacklist" behavior)
* `promote_function(fn)`  — args promoted to the widest floating dtype
  (the reference's multi-arg type-promotion wrapper, apex/amp/wrap.py)

Decorated functions are no-ops until a policy with ``cast_functions=True``
(O1/O4) is activated via `amp.init(policy)` / `amp.initialize(...)`, and
inside a `disable_casts()` scope (the reference's ctx manager at
handle.py:163-167).

Weight-cast caching (reference: apex/amp/utils.py:54-130) is unnecessary:
XLA CSEs repeated casts of the same array inside a jitted step, which is
the compiler-native version of the reference's cache.
"""

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "init",
    "current_policy",
    "disable_casts",
    "half_function",
    "bfloat16_function",
    "float_function",
    "promote_function",
    "register_half_function",
    "register_bfloat16_function",
    "register_float_function",
    "register_promote_function",
]

# Module-level active policy: the analogue of the reference's `_amp_state`
# singleton holding the active handle (apex/amp/_amp_state.py). This is
# *static* configuration (dtypes), never traced state — safe under jit.
_active_policy = None
_casts_disabled = False


def init(policy=None, enabled: bool = True):
    """Activate `policy` for decorator-based casting (reference amp.init,
    apex/amp/amp.py:75-198). Called by `amp.initialize` for O1/O4."""
    global _active_policy
    _active_policy = policy if enabled else None
    return policy


def current_policy():
    return _active_policy


@contextlib.contextmanager
def disable_casts():
    """Scope within which decorated functions run uncast
    (reference: apex/amp/handle.py:163-167)."""
    global _casts_disabled
    prev = _casts_disabled
    _casts_disabled = True
    try:
        yield
    finally:
        _casts_disabled = prev


def _casting_active():
    p = _active_policy
    return p is not None and p.enabled and p.cast_functions and not _casts_disabled


def _cast_args(dtype, args, kwargs):
    def c(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(c, (args, kwargs))


def _make_cast_decorator(target_dtype: Optional[str]):
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _casting_active():
                return fn(*args, **kwargs)
            if target_dtype == "policy":
                dtype = _active_policy.cast_functions_dtype
            else:
                dtype = target_dtype
            cargs, ckwargs = _cast_args(dtype, args, kwargs)
            return fn(*cargs, **ckwargs)

        return wrapper

    return decorator


# `half_function` always casts to fp16, matching the reference's hard-coded
# `utils.maybe_half` (reference: apex/amp/amp.py:29-31) — only the cast
# *lists* switch dtype per level. Use `policy_function` to follow the active
# policy's compute dtype (fp16 under O1, bf16 under O4).
half_function = _make_cast_decorator(jnp.float16)
bfloat16_function = _make_cast_decorator(jnp.bfloat16)
float_function = _make_cast_decorator(jnp.float32)
# Cast to whatever the active policy's compute dtype is (what a function
# on the fp16/bf16 whitelist effectively receives under O1/O4).
policy_function = _make_cast_decorator("policy")


def promote_function(fn):
    """Promote all floating args to the widest floating dtype among them
    (reference promote/sequence_promote wrappers, apex/amp/wrap.py)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not _casting_active():
            return fn(*args, **kwargs)
        leaves = [
            x
            for x in jax.tree_util.tree_leaves((args, kwargs))
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        ]
        if not leaves:
            return fn(*args, **kwargs)
        widest = functools.reduce(jnp.promote_types, (x.dtype for x in leaves))
        cargs, ckwargs = _cast_args(widest, args, kwargs)
        return fn(*cargs, **ckwargs)

    return wrapper


# Registry-style aliases matching the reference's module-function API
# (reference: apex/amp/amp.py:48-71). In JAX there is no module object to
# patch, so these take and return the function directly.
def register_half_function(module, name):
    setattr(module, name, half_function(getattr(module, name)))


def register_bfloat16_function(module, name):
    setattr(module, name, bfloat16_function(getattr(module, name)))


def register_float_function(module, name):
    setattr(module, name, float_function(getattr(module, name)))


def register_promote_function(module, name):
    setattr(module, name, promote_function(getattr(module, name)))
