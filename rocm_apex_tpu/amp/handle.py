"""Amp state + the loss-scaling training flow.

Functional redesign of the reference's `scale_loss` context manager and
`AmpHandle` (reference: apex/amp/handle.py:16-252). The reference's
context manager mutates optimizers on exit and patches `optimizer.step`
to skip on overflow (handle.py:128-154); in JAX the same sequence is a
pure dataflow:

    scaled = amp.scale_loss(loss, amp_state)                 # fwd
    grads  = jax.grad(...)                                   # bwd on scaled loss
    grads, found_inf = amp.unscale_grads(grads, amp_state)   # fused unscale+probe
    amp_state, skip  = amp.update_scale(amp_state, found_inf)
    new = amp.skip_step(skip, new_tree, old_tree)            # lax.cond analogue

`AmpState` is a pytree (scaler states are traced; policy/scaler config are
static aux data) so it lives inside a jitted train state.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "AmpState",
    "scale_loss",
    "unscale_grads",
    "update_scale",
    "skip_step",
    "master_params",
]


class AmpState:
    """Carries the policy (static), scaler config (static) and per-loss
    scaler states (traced pytree leaves).

    The analogue of the reference's global `_amp_state` singleton
    (reference: apex/amp/_amp_state.py) — but explicit and functional.
    """

    def __init__(self, policy, scaler, scaler_states):
        self.policy = policy
        self.scaler = scaler
        self.scaler_states = tuple(scaler_states)

    def replace(self, **kw):
        d = dict(policy=self.policy, scaler=self.scaler, scaler_states=self.scaler_states)
        d.update(kw)
        return AmpState(**d)

    @property
    def loss_scale(self):
        return self.scaler_states[0].loss_scale

    def __repr__(self):
        return (
            f"AmpState(opt_level={self.policy.opt_level}, "
            f"num_losses={len(self.scaler_states)})"
        )


def _amp_state_flatten(s):
    return (s.scaler_states,), (s.policy, s.scaler)


def _amp_state_unflatten(aux, children):
    policy, scaler = aux
    return AmpState(policy, scaler, children[0])


jax.tree_util.register_pytree_node(AmpState, _amp_state_flatten, _amp_state_unflatten)


def scale_loss(loss, amp_state: AmpState, loss_id: int = 0):
    """Return `loss.float() * current_scale` (reference: handle.py:113).

    If amp is disabled this is the identity (reference `NoOpHandle`,
    handle.py:254-281).
    """
    if not amp_state.policy.enabled:
        return loss
    return amp_state.scaler.scale(amp_state.scaler_states[loss_id], loss)


def unscale_grads(grads, amp_state: AmpState, loss_id: int = 0, stashed=None):
    """Unscale grads to fp32 and probe for inf/nan in one pass.

    Returns ``(grads_fp32, found_inf)``. With ``stashed`` (fp32 grads from
    an earlier backward) performs the axpby accumulate-merge instead
    (reference: apex/amp/_process_optimizer.py:161-207).
    """
    scaler, state = amp_state.scaler, amp_state.scaler_states[loss_id]
    if stashed is not None:
        return scaler.unscale_with_stashed(state, stashed, grads)
    return scaler.unscale(state, grads)


def update_scale(amp_state: AmpState, found_inf, loss_id: int = 0):
    """Advance the dynamic scale; returns ``(amp_state, should_skip)``."""
    scaler = amp_state.scaler
    states = list(amp_state.scaler_states)
    states[loss_id], should_skip = scaler.update(states[loss_id], found_inf)
    return amp_state.replace(scaler_states=tuple(states)), should_skip


def skip_step(should_skip, new_tree: Any, old_tree: Any) -> Any:
    """Select old state when the step must be skipped.

    The jit-safe analogue of patching `optimizer.step` to a no-op
    (reference: handle.py:128-154). `jnp.where` keeps both branches
    fusible; XLA turns this into selects, which on TPU is cheaper than
    divergent control flow.
    """
    return jax.tree_util.tree_map(
        lambda new, old: jnp.where(should_skip, old, new), new_tree, old_tree
    )


def master_params(opt_state):
    """Yield fp32 master params from a processed optimizer state
    (reference: apex/amp/_amp_state.py:60-69)."""
    from rocm_apex_tpu.amp._process_optimizer import MasterWeightsState

    for s in jax.tree_util.tree_leaves(
        opt_state, is_leaf=lambda x: isinstance(x, MasterWeightsState)
    ):
        if isinstance(s, MasterWeightsState):
            yield from jax.tree_util.tree_leaves(s.master)
