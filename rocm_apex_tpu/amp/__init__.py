"""Automatic mixed precision for TPU — policy levels O0–O5.

Public surface mirrors the reference `apex.amp`
(reference: apex/amp/__init__.py): `initialize`, `scale_loss`,
`state_dict`/`load_state_dict`, the function decorators, plus the
TPU-native functional pieces (`LossScaler`, `AmpState`, `unscale_grads`,
`update_scale`, `skip_step`).
"""

from rocm_apex_tpu.amp.amp import (
    bfloat16_function,
    current_policy,
    disable_casts,
    float_function,
    half_function,
    init,
    policy_function,
    promote_function,
    register_bfloat16_function,
    register_float_function,
    register_half_function,
    register_promote_function,
)
from rocm_apex_tpu.amp.frontend import (
    AmpError,
    Properties,
    build_policy,
    initialize,
    load_state_dict,
    opt_levels,
    state_dict,
)
from rocm_apex_tpu.amp.handle import (
    AmpState,
    master_params,
    scale_loss,
    skip_step,
    unscale_grads,
    update_scale,
)
from rocm_apex_tpu.amp._process_optimizer import (
    MasterWeightsState,
    process_optimizer,
    with_master_weights,
)
from rocm_apex_tpu.amp.scaler import LossScaler, ScalerState, all_finite

__all__ = [
    "initialize", "build_policy", "Properties", "opt_levels", "AmpError",
    "state_dict", "load_state_dict",
    "AmpState", "scale_loss", "unscale_grads", "update_scale", "skip_step",
    "master_params",
    "LossScaler", "ScalerState", "all_finite",
    "process_optimizer", "with_master_weights", "MasterWeightsState",
    "init", "current_policy", "disable_casts",
    "half_function", "bfloat16_function", "float_function",
    "promote_function", "policy_function",
    "register_half_function", "register_bfloat16_function",
    "register_float_function", "register_promote_function",
]
