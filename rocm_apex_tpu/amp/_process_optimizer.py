"""Master-weight optimizer wrapping.

TPU-native redesign of the reference's `_process_optimizer`
(reference: apex/amp/_process_optimizer.py). The reference monkey-patches
an optimizer *instance*, lazily swapping fp16 params for fresh fp32
masters inside `param_groups` (:28-90) and copying masters back to the
model with one fused `multi_tensor_scale` launch (:14-25). Here the same
capability is an optax gradient-transformation wrapper:

* `with_master_weights(tx)` — holds an fp32 master copy of the params in
  its state; incoming grads are cast to fp32, the inner transform updates
  the masters, and the emitted updates are exactly
  ``cast(new_master, param_dtype) - params`` so that
  `optax.apply_updates` reproduces the reference's master→model copy.
  (The subtraction and add cancel exactly: both sides are the same
  low-precision value, so `params + (q - params)` with q,params identical
  dtype is exact for the IEEE formats used here when computed in fp32 —
  we compute the delta in fp32 and rely on apply_updates' dtype cast.)

Use `amp.initialize(..., optimizer=tx)` or wrap explicitly.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["with_master_weights", "process_optimizer", "MasterWeightsState"]


class MasterWeightsState(NamedTuple):
    master: Any  # fp32 master params
    inner: Any  # inner transform state


def _to_f32(tree):
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


def with_master_weights(tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """Wrap `tx` to update fp32 masters and emit low-precision param deltas.

    Semantics of `lazy_init_with_master_weights` +
    `post_backward_with_master_weights`
    (reference: apex/amp/_process_optimizer.py:28-90,161-207): the inner
    optimizer only ever sees fp32 params and fp32 grads; the model params
    receive the rounded master values each step.
    """

    def init_fn(params):
        master = _to_f32(params)
        return MasterWeightsState(master=master, inner=tx.init(master))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("with_master_weights requires params in update()")
        grads32 = _to_f32(updates)
        inner_updates, inner_state = tx.update(grads32, state.inner, state.master)
        new_master = optax.apply_updates(state.master, inner_updates)

        def delta(m, p):
            if jnp.issubdtype(p.dtype, jnp.floating):
                # master→model copy, expressed as an additive update kept in
                # fp32: optax.apply_updates promotes p + delta to fp32, giving
                # exactly round(master) after its final cast back to p.dtype
                # (reference: _process_optimizer.py:14-25).
                q = m.astype(p.dtype)
                return q.astype(jnp.float32) - p.astype(jnp.float32)
            return m - p

        new_updates = jax.tree_util.tree_map(delta, new_master, params)
        return new_updates, MasterWeightsState(master=new_master, inner=inner_state)

    return optax.GradientTransformation(init_fn, update_fn)


def process_optimizer(tx: optax.GradientTransformation, policy) -> optax.GradientTransformation:
    """Apply the policy's optimizer-side behavior to an optax transform.

    With ``policy.master_weights`` the transform is wrapped with fp32
    master management; otherwise grads are still cast to fp32 before the
    inner update when the model runs in low precision, matching the
    reference's `post_backward_models_are_masters` path
    (reference: apex/amp/_process_optimizer.py:93-140).
    """
    if policy.master_weights:
        return with_master_weights(tx)
    return tx
