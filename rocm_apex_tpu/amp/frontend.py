"""Precision-policy frontend: opt-levels O0–O5.

TPU-native redesign of the reference amp frontend
(reference: apex/amp/frontend.py:7-254). The reference mutates torch op
registries and module dtypes in place; here a `Properties` policy object is
*data* that threads through pure functions:

* ``cast_model_dtype``     — dtype model params are stored/cast to
  (reference ``cast_model_type``; O2 fp16 / O3 fp16 / O5 bf16).
* ``cast_functions``       — whether compute-level casting is active
  (reference ``patch_torch_functions``; O1/O4). In JAX nothing is patched:
  modules and the `half_function`/`bfloat16_function` decorators consult
  the policy (see amp/amp.py).
* ``cast_functions_dtype`` — the compute dtype for O1 (fp16) / O4 (bf16)
  (reference ``patch_torch_functions_type``).
* ``keep_batchnorm_fp32``  — exempt batch-norm leaves from the model cast.
* ``master_weights``       — keep an fp32 master copy in optimizer state
  (reference builds fp32 masters lazily, apex/amp/_process_optimizer.py:28-90).
* ``loss_scale``           — float or "dynamic"
  (bf16 levels O4/O5 default to 1: same exponent range as fp32, so no
  scaling needed — reference frontend.py:207-246).

O4/O5 (bf16) are the *primary* TPU paths; fp16 levels exist for parity.
"""

import warnings
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from rocm_apex_tpu.utils.tree import is_batchnorm_path, tree_cast

__all__ = [
    "Properties",
    "opt_levels",
    "build_policy",
    "initialize",
    "state_dict",
    "load_state_dict",
    "AmpError",
]


class AmpError(ValueError):
    pass


def warn_or_err(msg, strict=True):
    # Mirrors the behavior switch in the reference's `warn_or_err`
    # (reference: apex/amp/_amp_state.py): hard error by default.
    if strict:
        raise AmpError(msg)
    warnings.warn(msg)


_OPTION_NAMES = (
    "enabled",
    "opt_level",
    "cast_model_dtype",
    "cast_functions",
    "cast_functions_dtype",
    "keep_batchnorm_fp32",
    "master_weights",
    "loss_scale",
)


class Properties:
    """Policy option struct with per-option consistency checks.

    Same role and validation semantics as the reference `Properties`
    (reference: apex/amp/frontend.py:7-113), rebuilt as plain data: routes
    attribute sets through checks so inconsistent combinations
    (e.g. master_weights with O1/O4) raise/warn.
    """

    def __init__(self):
        self.__dict__["options"] = {
            "enabled": False,
            "opt_level": None,
            "cast_model_dtype": None,
            "cast_functions": False,
            "cast_functions_dtype": None,
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
        }

    def _update_options_dict(self, new_options):
        for k, v in new_options.items():
            if k not in self.options:
                raise AmpError(f"Tried to set unexpected option {k}")
            self.options[k] = v

    def __getattr__(self, name):
        options = self.__dict__.get("options")
        if options is not None and name in options:
            return options[name]
        raise AttributeError(f"'Properties' object has no attribute '{name}'")

    def __setattr__(self, name, value):
        if name not in self.options:
            super().__setattr__(name, value)
            return
        if name == "cast_model_dtype":
            if self.opt_level in ("O1", "O4") and value not in (None, False):
                if value != jnp.float32:
                    warn_or_err(
                        "O1/O4 insert casts around functions rather than model "
                        "weights; with O1/O4 the model weights should remain "
                        "FP32. Use opt_level='O2'/'O3' (fp16) or 'O5' (bf16) "
                        f"to cast the model. cast_model_dtype was {value}"
                    )
            self.options[name] = value
        elif name == "cast_functions":
            if self.opt_level not in ("O1", "O4") and value:
                warn_or_err(
                    "cast_functions=True should only be set by selecting "
                    "opt_level='O1' or 'O4'."
                )
            self.options[name] = value
        elif name == "cast_functions_dtype":
            if self.opt_level not in ("O1", "O4") and value is not None:
                warn_or_err(
                    "cast_functions_dtype should only be set by selecting "
                    "opt_level='O1' or 'O4'."
                )
            elif self.opt_level == "O1" and value != jnp.float16:
                warn_or_err("cast_functions_dtype must be float16 for opt_level='O1'.")
            elif self.opt_level == "O4" and value != jnp.bfloat16:
                warn_or_err("cast_functions_dtype must be bfloat16 for opt_level='O4'.")
            else:
                self.options[name] = value
        elif name == "keep_batchnorm_fp32":
            if self.opt_level in ("O1", "O4") and value is not None:
                warn_or_err(
                    "With opt_level O1/O4 batch-norm runs in FP32 via the "
                    "policy cast lists, so keep_batchnorm_fp32 should be None. "
                    f"keep_batchnorm_fp32 was {value}"
                )
            if value == "False":
                value = False
            elif value == "True":
                value = True
            if value not in (True, False, None):
                raise AmpError(
                    "keep_batchnorm_fp32 must be a bool, the string 'True' or "
                    f"'False', or None; found {value}"
                )
            self.options[name] = value
        elif name == "master_weights":
            if self.opt_level in ("O1", "O4") and value is not None:
                warn_or_err(
                    "master_weights does not make sense with O1/O4 — model "
                    "weights are already FP32."
                )
            self.options[name] = value
        elif name == "loss_scale":
            self.options[name] = value if value == "dynamic" else float(value)
        else:
            self.options[name] = value

    # -- derived views used throughout the framework --------------------

    @property
    def compute_dtype(self):
        """Dtype matmul-heavy compute should run in under this policy."""
        if self.cast_functions and self.cast_functions_dtype is not None:
            return self.cast_functions_dtype
        if self.cast_model_dtype not in (None, False):
            return self.cast_model_dtype
        return jnp.float32

    @property
    def param_dtype(self):
        """Dtype model params are stored in under this policy."""
        if self.cast_model_dtype not in (None, False):
            return self.cast_model_dtype
        return jnp.float32

    def __repr__(self):
        opts = ", ".join(f"{k}={v!r}" for k, v in self.options.items())
        return f"Properties({opts})"


class O0:
    brief = "O0: Pure FP32 training."

    def __call__(self, p):
        p.enabled = True
        p.opt_level = "O0"
        p.cast_model_dtype = jnp.float32
        p.cast_functions = False
        p.cast_functions_dtype = None
        p.keep_batchnorm_fp32 = None
        p.master_weights = False
        p.loss_scale = 1.0
        return p


class O1:
    brief = "O1: Policy casts around functions (FP16 compute, FP32 weights)."

    def __call__(self, p):
        p.enabled = True
        p.opt_level = "O1"
        p.cast_model_dtype = None
        p.cast_functions = True
        p.cast_functions_dtype = jnp.float16
        p.keep_batchnorm_fp32 = None
        p.master_weights = None
        p.loss_scale = "dynamic"
        return p


class O2:
    brief = "O2: FP16 training with FP32 batchnorm and FP32 master weights."

    def __call__(self, p):
        p.enabled = True
        p.opt_level = "O2"
        p.cast_model_dtype = jnp.float16
        p.cast_functions = False
        p.cast_functions_dtype = None
        p.keep_batchnorm_fp32 = True
        p.master_weights = True
        p.loss_scale = "dynamic"
        return p


class O3:
    brief = "O3: Pure FP16 training."

    def __call__(self, p):
        p.enabled = True
        p.opt_level = "O3"
        p.cast_model_dtype = jnp.float16
        p.cast_functions = False
        p.cast_functions_dtype = None
        p.keep_batchnorm_fp32 = False
        p.master_weights = False
        p.loss_scale = 1.0
        return p


class O4:
    brief = "O4: Policy casts around functions (BF16 compute, FP32 weights)."

    def __call__(self, p):
        p.enabled = True
        p.opt_level = "O4"
        p.cast_model_dtype = None
        p.cast_functions = True
        p.cast_functions_dtype = jnp.bfloat16
        p.keep_batchnorm_fp32 = None
        p.master_weights = None
        p.loss_scale = 1
        return p


class O5:
    brief = "O5: BF16 training with FP32 batchnorm and FP32 master weights."

    def __call__(self, p):
        p.enabled = True
        p.opt_level = "O5"
        p.cast_model_dtype = jnp.bfloat16
        p.cast_functions = False
        p.cast_functions_dtype = None
        p.keep_batchnorm_fp32 = True
        p.master_weights = True
        p.loss_scale = 1
        return p


opt_levels = {
    "O0": O0(),
    "O1": O1(),
    "O2": O2(),
    "O3": O3(),
    "O4": O4(),
    "O5": O5(),
}


def build_policy(
    opt_level: str = "O1",
    cast_model_dtype=None,
    cast_functions=None,
    cast_functions_dtype=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
) -> Properties:
    """Resolve an opt-level plus user overrides into a `Properties` policy.

    Mirrors the override flow of `amp.initialize`
    (reference: apex/amp/frontend.py:373-419): the opt-level establishes
    defaults, then explicit keyword overrides are applied through the
    consistency checks.
    """
    if opt_level not in opt_levels:
        raise AmpError(
            f"Unexpected optimization level {opt_level}. Options are 'O0', "
            "'O1', 'O2', 'O3', 'O4', 'O5'. Note the use of the letter O, not "
            "the number zero."
        )
    p = opt_levels[opt_level](Properties())
    overrides = {
        "cast_model_dtype": cast_model_dtype,
        "cast_functions": cast_functions,
        "cast_functions_dtype": cast_functions_dtype,
        "keep_batchnorm_fp32": keep_batchnorm_fp32,
        "master_weights": master_weights,
        "loss_scale": loss_scale,
    }
    for k, v in overrides.items():
        if v is not None:
            setattr(p, k, v)
    return p


def initialize(
    params: Any,
    optimizer=None,
    opt_level: str = "O1",
    num_losses: int = 1,
    is_batchnorm: Optional[Callable] = None,
    verbosity: int = 1,
    **overrides,
):
    """Apply an amp policy to a param pytree (+ optionally an optax optimizer).

    Functional analogue of `amp.initialize`
    (reference: apex/amp/frontend.py:258-425 and apex/amp/_initialize.py):

    * casts the param pytree to ``cast_model_dtype``, exempting batch-norm
      leaves when ``keep_batchnorm_fp32`` (reference keeps `_BatchNorm`
      modules fp32, _initialize.py:176-182);
    * wraps the optax optimizer with master-weight management + loss-scaled
      update skipping (reference patches optimizer instances in place,
      _process_optimizer.py);
    * builds ``num_losses`` independent `LossScaler` configs
      (reference: _initialize.py:227-231).

    Returns ``(params, optimizer, amp_state)`` where ``amp_state`` is an
    `AmpState` carrying the policy and scaler states; it is a pytree and can
    live inside a jitted train state.
    """
    from rocm_apex_tpu.amp.handle import AmpState
    from rocm_apex_tpu.amp.scaler import LossScaler

    policy = build_policy(opt_level, **overrides)
    if verbosity:
        from rocm_apex_tpu import logger

        logger.info("amp.initialize: opt_level=%s → %r", opt_level, policy)

    if policy.cast_model_dtype not in (None, False):
        keep = None
        if policy.keep_batchnorm_fp32:
            keep = is_batchnorm or is_batchnorm_path
        params = tree_cast(params, policy.cast_model_dtype, keep_fp32_predicate=keep)

    # Activate (or deactivate) the decorator-based casting path — the
    # analogue of the reference's amp_init patching for O1/O4
    # (_initialize.py:233-237). Unconditional so re-initializing with a
    # non-casting level clears any previously active policy.
    from rocm_apex_tpu.amp import amp as _amp_mod

    _amp_mod.init(policy if policy.cast_functions else None)

    scaler = LossScaler(policy.loss_scale)
    amp_state = AmpState(
        policy=policy,
        scaler=scaler,
        scaler_states=tuple(scaler.init() for _ in range(num_losses)),
    )

    if optimizer is not None:
        from rocm_apex_tpu.amp._process_optimizer import process_optimizer

        optimizer = process_optimizer(optimizer, policy)

    return params, optimizer, amp_state


def state_dict(amp_state) -> dict:
    """Serializable scaler state: `{loss_scaler0: {loss_scale, unskipped}, …}`.

    Same schema as the reference (reference: apex/amp/frontend.py:428-437).
    """
    out = {}
    for i, s in enumerate(amp_state.scaler_states):
        out[f"loss_scaler{i}"] = {
            "loss_scale": float(s.loss_scale),
            "unskipped": int(s.unskipped),
        }
    return out


def load_state_dict(amp_state, state: dict):
    """Restore scaler states saved by `state_dict` (reference frontend.py:440-467)."""
    if len(state) != len(amp_state.scaler_states):
        warnings.warn(
            f"Loading state_dict containing {len(state)} entries, but "
            f"AmpState has {len(amp_state.scaler_states)} scalers"
        )
    new_states = list(amp_state.scaler_states)
    for key, value in state.items():
        i = int(key.replace("loss_scaler", ""))
        if i < len(new_states):
            new_states[i] = new_states[i]._replace(
                loss_scale=jnp.asarray(value["loss_scale"], jnp.float32),
                unskipped=jnp.asarray(value["unskipped"], jnp.int32),
            )
    return amp_state.replace(scaler_states=tuple(new_states))
