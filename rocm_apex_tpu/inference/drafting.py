"""N-gram self-drafting for speculative decoding.

Decode is memory-bound: every emitted token pays one full pass over the
slot's K/V prefix (the PR-5 measurement — per-token cache access
dominates the mixed tick). Speculative decoding (arXiv 2211.17192)
amortizes that read: propose k cheap draft tokens, score all of them in
ONE forward pass, keep the longest prefix the model agrees with. The
verification kernel already exists here — the chunked mixed step scores
an arbitrary multi-token span against a slot's cache prefix and samples
every packed position — so the only missing piece is a proposer.

`NGramDrafter` is the zero-cost proposer: instead of a separate draft
model it suffix-matches the slot's own history (prompt + generated
tokens). If the final n-gram occurred earlier, the tokens that followed
that earlier occurrence are proposed as the continuation — the
"prompt lookup" / self-drafting scheme. This is

* deterministic (pure function of the history window, so greedy
  speculative output can be asserted token-identical to baseline),
* model-free (no extra params, no extra trace), and
* jit-able with static shapes (the engine calls one compiled program
  per tick regardless of which slots match).

The engine treats the drafter as a pluggable hook with the protocol

    drafts, counts = drafter(histories, lengths)

where ``histories`` is ``(num_slots, window)`` int32, LEFT-padded with
``-1`` (each row right-aligned so the suffix — the match anchor — sits
at a static offset), ``lengths`` is ``(num_slots,)`` int32 live-token
counts, and the result is ``(num_slots, k)`` int32 proposals with
``(num_slots,)`` valid counts. A learned draft model can be dropped in
by wrapping its own propose step in the same signature.
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["NGramDrafter"]


class NGramDrafter:
    """Propose up to ``k`` continuation tokens by suffix n-gram match.

    For each ``n`` in ``ngrams`` (tried longest-first), the final ``n``
    tokens of the history are searched for an earlier occurrence inside
    the last ``window`` tokens. On a hit, the tokens FOLLOWING the
    matched occurrence are proposed. Among candidate occurrences the
    drafter prefers ones with at least ``k`` following tokens (a full
    proposal beats a truncated one), breaking ties by recency —
    repetitive tails (the high-acceptance regime) then lock onto the
    most recent period.

    ``window`` bounds the search (and the engine's history-packing
    cost) — matching is O(window · n) compares, fully vectorized.
    """

    def __init__(
        self,
        k: int,
        *,
        window: int = 64,
        ngrams: Sequence[int] = (3, 2),
    ):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if window < max(ngrams) + k:
            raise ValueError(
                f"window={window} too small for ngrams={tuple(ngrams)} "
                f"with k={k}"
            )
        if any(n < 1 for n in ngrams):
            raise ValueError(f"ngrams must be >= 1, got {tuple(ngrams)}")
        self.k = int(k)
        self.window = int(window)
        self.ngrams = tuple(int(n) for n in ngrams)
        self._propose_jit = jax.jit(self.propose)

    # -- pure core (unit-testable, jit-able) ----------------------------

    def _match_n(
        self, hist: jnp.ndarray, lengths: jnp.ndarray, n: int
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One cascade rung: match the final ``n``-gram.

        Returns ``(found (S,) bool, drafts (S, k), counts (S,))``.
        """
        S, W = hist.shape
        k = self.k
        m = W - n  # candidate start positions [0, m); i == m is the suffix
        pattern = hist[:, W - n :]  # (S, n)
        eq = jnp.ones((S, m), dtype=bool)
        for j in range(n):
            eq = eq & (hist[:, j : j + m] == pattern[:, j : j + 1])
        starts = jnp.arange(m)[None, :]  # (1, m)
        # a candidate is valid only if its whole n-gram lies inside the
        # live region (left pad is -1 and can false-match short
        # histories without this mask)
        valid = eq & (starts >= (W - lengths)[:, None])
        # prefer occurrences with >= k following tokens, then recency
        follow = m - starts  # tokens after the occurrence, >= 1
        score = jnp.where(valid, starts + jnp.where(follow >= k, W, 0), -1)
        best = jnp.argmax(score, axis=1)  # (S,)
        found = jnp.any(valid, axis=1)
        start = best + n  # first proposed token
        count = jnp.minimum(k, W - start)
        idx = jnp.clip(start[:, None] + jnp.arange(k)[None, :], 0, W - 1)
        drafts = jnp.take_along_axis(hist, idx, axis=1)
        keep = jnp.arange(k)[None, :] < count[:, None]
        return found, jnp.where(keep & found[:, None], drafts, 0), jnp.where(
            found, count, 0
        )

    def propose(
        self, histories: jnp.ndarray, lengths: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Pure proposal: ``(S, window)`` histories → ``(S, k)`` drafts
        + ``(S,)`` counts. Longest n-gram in the cascade wins."""
        hist = histories.astype(jnp.int32)
        lengths = jnp.minimum(lengths.astype(jnp.int32), self.window)
        S = hist.shape[0]
        drafts = jnp.zeros((S, self.k), jnp.int32)
        counts = jnp.zeros((S,), jnp.int32)
        done = jnp.zeros((S,), bool)
        for n in self.ngrams:
            found, d_n, c_n = self._match_n(hist, lengths, n)
            take = found & ~done
            drafts = jnp.where(take[:, None], d_n, drafts)
            counts = jnp.where(take, c_n, counts)
            done = done | found
        return drafts, counts

    # -- engine-facing numpy wrapper ------------------------------------

    def __call__(
        self, histories: np.ndarray, lengths: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        drafts, counts = self._propose_jit(
            jnp.asarray(histories, jnp.int32), jnp.asarray(lengths, jnp.int32)
        )
        return np.asarray(drafts), np.asarray(counts)
