"""Deterministic fault injection for the serving engine.

The robustness layer's chaos harness: a `FaultPlan` names the engine's
failure sites and schedules WHEN each one misbehaves — by engine tick,
by nth call to the site, by period, or with a seeded coin flip — so a
test (or ``bench.py serve --chaos=SEED``) can replay the exact same
failure sequence on every run and assert that the non-faulted requests
come out bitwise identical to a fault-free run.

Sites the engine threads through (see `InferenceEngine`):

``page_alloc``
    The host page allocator "fails" to supply a page: the call site
    takes its ordinary backpressure path (the token is not scheduled
    this tick) — exactly what a genuinely exhausted pool does.
``device_step``
    Raises `FaultInjected` in place of the compiled mixed/decode call
    — exercises the retry/backoff and preempt-and-requeue paths.
``logits``
    Poisons ONE slot's logits with NaN/Inf for the tick (payload picks
    the slot and value): the in-graph nonfinite flags fire and the
    engine quarantines that slot only.
``host_fetch``
    Raises `FaultInjected` between the device call and the value
    fetch — same retry path, different failure point.
``page_ship``
    Drops a page-shipping migration payload at import time (as if the
    KV transfer was lost mid-flight): the destination engine falls back
    to token-replay recovery — token-identical, just slower — and the
    already-released source pages simply stay freed, so neither
    allocator can leak.

Replica-scoped sites the `ReplicaRouter` consults (the ``payload``
names the target: ``{"replica": i}``; the router consults each site
once per ROUTER tick, so ``tick`` schedules are in the router's tick
domain, not any engine's):

``replica_kill``
    The replica is treated as crashed: the router quarantines it and
    resubmits every request it held — from the router's own token
    mirror, never the dead engine's state — to the healthy fleet.
``replica_stall``
    The replica stops being stepped for ``payload["ticks"]`` router
    ticks (default 3): its requests make no progress, so the router's
    zero-progress detector must notice and migrate them.
``replica_slow``
    Injected latency: ``payload["seconds"]`` of host sleep before
    each of the replica's next ``payload["ticks"]`` steps (defaults
    0.01 s × 1 tick) — skews that replica's TTFT/TPOT streams so the
    merged-registry percentiles have something to reproduce.

Hot-path contract: ``NO_FAULTS`` is the shared disabled plan (the
`NULL_TRACER` idiom) — every call site gates on ``faults.enabled``
first, so a fault-free engine pays one attribute check per site and
nothing else. Scheduling is pure host bookkeeping; the compiled
programs never change shape (``mixed_trace_count`` stays 1 under any
plan).

Determinism: ``tick``/``nth``/``every`` schedules are exact;
probabilistic faults (``p``) draw from a `numpy` generator seeded in
the plan, so the same seed replays the same failures. Call counters
live in the plan — build a fresh plan (or `reset()`) per run.
"""

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Fault", "FaultPlan", "FaultInjected", "NO_FAULTS", "SITES"]

#: The injection sites a plan may name — a typoed site must not
#: silently never fire. The first four are consulted by the engine
#: (per engine tick); the ``replica_*`` sites by the `ReplicaRouter`
#: (per router tick, payload ``{"replica": i}``).
SITES = (
    "page_alloc", "device_step", "logits", "host_fetch", "page_ship",
    "replica_kill", "replica_stall", "replica_slow",
)


class FaultInjected(RuntimeError):
    """Raised by an injected ``device_step``/``host_fetch`` fault.

    A `RuntimeError` subclass so handlers written for real device
    failures treat it identically; `isinstance` checks let tests tell
    injected failures from genuine ones.
    """


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled misbehaviour at one site.

    Exactly when it fires is the OR of the schedules given:

    ``tick``   fire on this engine tick (0-based `step()` count)
    ``nth``    fire on the nth call to the site (1-based)
    ``every``  fire on every ``every``-th call to the site
    ``p``      fire with probability p per call (plan-seeded RNG)

    ``times`` caps the total fires of THIS fault (default 1; ``None``
    = unlimited). ``payload`` carries site-specific detail — for
    ``logits`` a dict like ``{"slot": 1, "value": float("nan")}``.
    """

    site: str
    tick: Optional[int] = None
    nth: Optional[int] = None
    every: Optional[int] = None
    p: float = 0.0
    times: Optional[int] = 1
    payload: Any = None

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; engine sites are "
                f"{SITES}"
            )
        if (
            self.tick is None and self.nth is None
            and self.every is None and self.p <= 0.0
        ):
            raise ValueError(
                f"fault at {self.site!r} has no schedule: set tick, "
                f"nth, every, or p"
            )
        if self.every is not None and self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")
        if self.nth is not None and self.nth < 1:
            raise ValueError(f"nth is 1-based, got {self.nth}")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {self.p}")


class FaultPlan:
    """A seeded schedule of `Fault`s plus per-site call counters.

    ``fire(site, tick=...)`` advances the site's call counter and
    returns the first scheduled fault that matches (at most ONE fault
    per site per call — the engine consults each site once per place
    it can fail), or None. ``fires`` tallies what actually fired, for
    completion-accounting asserts.
    """

    enabled: bool = True

    def __init__(self, faults: Sequence[Fault] = (), seed: int = 0):
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self.seed = int(seed)
        self.enabled = bool(self.faults)
        self.reset()

    def reset(self) -> None:
        """Rewind every counter and the RNG — replay from scratch."""
        self._rng = np.random.RandomState(self.seed)
        self._calls: Dict[str, int] = {s: 0 for s in SITES}
        self._fired: Dict[int, int] = {
            i: 0 for i in range(len(self.faults))
        }
        self.fires: Dict[str, int] = {s: 0 for s in SITES}

    def calls(self, site: str) -> int:
        return self._calls[site]

    def fire(
        self, site: str, tick: Optional[int] = None, **ctx
    ) -> Optional[Fault]:
        """One consultation of ``site``; returns the fault that fires
        now (and books it), else None. ``ctx`` is accepted so call
        sites can pass slot/request detail without the plan caring."""
        self._calls[site] += 1
        n = self._calls[site]
        for i, f in enumerate(self.faults):
            if f.site != site:
                continue
            if f.times is not None and self._fired[i] >= f.times:
                continue
            hit = (
                (f.tick is not None and tick == f.tick)
                or (f.nth is not None and n == f.nth)
                or (f.every is not None and n % f.every == 0)
                or (f.p > 0.0 and self._rng.random_sample() < f.p)
            )
            if hit:
                self._fired[i] += 1
                self.fires[site] += 1
                return f
        return None


#: Shared null plan (the `NULL_TRACER` idiom): call sites check
#: ``faults.enabled`` and skip the schedule walk entirely.
NO_FAULTS = FaultPlan(())
