"""Serving tier: KV-cached decoding + continuous batching.

Opens the inference workload over the training stack — every training
subsystem (amp dtypes, Pallas attention kernels, profiler) is reused,
nothing is forked:

    kv_cache   preallocated slot-paged KV cache pytree (bf16 default,
               in-place dynamic_update_slice writes, per-slot lengths)
    paging     vLLM-style paged cache: shared page pool + per-slot
               block tables (`PagedKVCache`), host free-list/ref-count
               `PageAllocator`, and the copy-on-write `PrefixStore`
               that shares materialized prompt pages across requests;
               optional int8 pools with per-(page, head) scales
    adapters   multi-LoRA `AdapterPool`: rank-padded packed adapter
               factors in fixed-shape paged device buffers (the
               `PageAllocator` idiom — ref-counts, LRU park on idle
               tenants, reclaim on pressure), host registry keyed by
               tenant; `ops/lora.py` contracts per-token deltas out of
               it inside the one mixed serving trace
    sampling   greedy / temperature / top-k / top-p, jit-able and
               seed-deterministic
    drafting   n-gram self-drafter for speculative decoding: proposes
               up to k continuation tokens per slot by suffix-matching
               the slot's own history (no draft model); pluggable hook
               protocol for learned drafters
    faults     deterministic chaos harness: seeded `FaultPlan`
               schedules (tick / nth-call / periodic / probabilistic)
               over the engine's failure sites — page allocation,
               device step, logits (NaN/Inf poisoning), host fetch —
               with the shared `NO_FAULTS` null plan on the hot path
    engine     continuous-batching serving loop: fixed slot grid,
               request queue, per-step admit/evict, and the chunked-
               prefill token-budget scheduler — ONE compiled mixed
               chunk+decode step per tick (plus a decode-only fast
               path), donated cache buffers, no prompt-length ceiling;
               ``paged=True`` swaps in the block-table cache
    router     multi-replica serving fabric: `ReplicaRouter` owns N
               engines behind one surface — prefix-affinity placement
               via the cross-replica `SharedPrefixRegistry`,
               least-loaded otherwise, replica failover with
               token-identical in-flight recovery (page-shipping
               migration on paged caches, prompt + emitted tokens as
               the replay fallback), disaggregated prefill/decode
               replica classes with per-class TTFT/TPOT, rolling
               drain/rejoin, fleet chaos sites, merged fleet telemetry

The model side lives in `models/gpt.py` (``cache=`` on `GPTModel`) and
`ops/flash_attention.py` (`flash_attention_decode`); this package owns
the cache layout and the serving loop. See docs/inference.md.
"""

from rocm_apex_tpu.inference.adapters import (  # noqa: F401
    BASE_ADAPTER_ID,
    AdapterPool,
)
from rocm_apex_tpu.inference.drafting import NGramDrafter  # noqa: F401
from rocm_apex_tpu.inference.engine import (  # noqa: F401
    FINISH_REASONS,
    GenerationResult,
    InferenceEngine,
    Request,
    SamplingParams,
    shard_tp1_params,
)
from rocm_apex_tpu.inference.faults import (  # noqa: F401
    NO_FAULTS,
    Fault,
    FaultInjected,
    FaultPlan,
)
from rocm_apex_tpu.inference.kv_cache import KVCache  # noqa: F401
from rocm_apex_tpu.inference.paging import (  # noqa: F401
    PageAllocator,
    PagedKVCache,
    PrefixStore,
)
from rocm_apex_tpu.inference.router import (  # noqa: F401
    REPLICA_CLASSES,
    REPLICA_STATES,
    ReplicaRouter,
    SharedPrefixRegistry,
)
from rocm_apex_tpu.inference.sampling import (  # noqa: F401
    greedy,
    sample,
    top_k_logits,
    top_p_logits,
)

__all__ = [
    "AdapterPool",
    "BASE_ADAPTER_ID",
    "KVCache",
    "PagedKVCache",
    "PageAllocator",
    "PrefixStore",
    "InferenceEngine",
    "ReplicaRouter",
    "SharedPrefixRegistry",
    "REPLICA_STATES",
    "REPLICA_CLASSES",
    "shard_tp1_params",
    "NGramDrafter",
    "Fault",
    "FaultPlan",
    "FaultInjected",
    "NO_FAULTS",
    "FINISH_REASONS",
    "Request",
    "GenerationResult",
    "SamplingParams",
    "greedy",
    "sample",
    "top_k_logits",
    "top_p_logits",
]
