"""Paged KV cache: block tables, int8 per-page scales, prefix sharing.

The contiguous `KVCache` leases one ``capacity``-row lane per slot, so
HBM scales with ``max_length × slots`` whether or not the tokens exist
— ROADMAP's "real ceiling on concurrent users". This module is the
vLLM-style answer (PagedAttention, arXiv 2309.06180), three
independently A/B-able rungs:

1. **Block tables** — all slots draw fixed-size pages from ONE shared
   pool; a ``(num_slots, pages_per_slot)`` int32 table maps each
   slot's logical positions onto pool pages. Memory in use scales
   with LIVE tokens; the decode read is bounded by pages actually
   mapped (`flash_attention_decode_paged`).
2. **int8 per-page quantization** — pools store int8 with one fp32
   scale per (page, head) (EQuARX's per-chunk-scale design, arXiv
   2506.17615, applied to cache bytes): cache HBM and decode DMA
   halve; dequantization happens inside the kernels' fp32
   accumulators (ops/paging.py owns the write-side requantize math).
3. **Copy-on-write prefix sharing** — `PrefixStore` hashes chains of
   page-aligned prompt blocks; a request whose prompt extends an
   already-materialized chain maps the shared pages by reference
   (no re-prefill — TTFT collapses for shared-system-prompt traffic)
   and `paged_fork` copies a page only when the borrower would WRITE
   into it.

Split of responsibilities: `PageAllocator`/`PrefixStore` are pure
host-side bookkeeping (no jax); `PagedKVCache` is the device pytree
whose write/advance methods keep the contiguous cache's signatures —
the engine (engine.py) is the only place the two halves meet, and
models/gpt.py keeps consuming a duck-typed cache pytree (it shares
the scatter/view math via ops/paging.py, never this package).
"""

import collections
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from rocm_apex_tpu.ops.paging import (
    paged_fork,
    paged_scatter,
    quantized_paged_scatter,
)

__all__ = ["PageAllocator", "PrefixStore", "PagedKVCache"]


class PageAllocator:
    """Host-side free-list + ref-count bookkeeping for the page pool.

    Pages are integers in ``[0, num_pages)``. A mapped page holds one
    ref per slot whose table points at it (prefix sharing = ref > 1).
    When the last ref drops the page either returns to the free list
    or — if it is registered in a `PrefixStore` — is PARKED on a
    reclaimable LRU: its bytes stay valid so a later request with the
    same prefix can revive it for free, but allocation pressure may
    reclaim it at any time (``on_evict`` fires so the store entry is
    dropped in the same motion). Allocation NEVER raises on
    exhaustion: ``alloc`` returns None and the engine backpressures
    (the request waits in prefill; nothing crashes).
    """

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = num_pages
        self._free: collections.deque = collections.deque(range(num_pages))
        self._ref = [0] * num_pages
        # insertion order = LRU order (parked pages re-park at the end)
        self._parked: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )
        # called with the page id when a PARKED page is reclaimed for a
        # fresh allocation (the engine unregisters it from the store)
        self.on_evict = None

    @property
    def available(self) -> int:
        return len(self._free) + len(self._parked)

    @property
    def pages_used(self) -> int:
        """Pages currently holding a reference (live mappings only —
        parked prefix-cache pages are reclaimable, not 'used')."""
        return self.num_pages - self.available

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """n fresh pages (ref = 1 each), or None if fewer than n are
        available — all-or-nothing, so a partial grab never deadlocks
        two half-satisfied requests."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if self.available < n:
            return None
        out = []
        for _ in range(n):
            if self._free:
                page = self._free.popleft()
            else:
                page, _ = self._parked.popitem(last=False)  # LRU
                if self.on_evict is not None:
                    self.on_evict(page)
            self._ref[page] = 1
            out.append(page)
        return out

    def ref(self, page: int) -> None:
        """Add a reference — reviving the page off the parked LRU if a
        prefix match picked it up there."""
        if self._ref[page] == 0:
            if page not in self._parked:
                raise ValueError(
                    f"page {page} is free, not shareable; alloc() it"
                )
            del self._parked[page]
        self._ref[page] += 1

    def decref(self, page: int, park: bool = False) -> None:
        """Drop one reference. At zero the page returns to the free
        list, or parks on the reclaimable LRU when ``park`` (the
        engine parks store-registered pages). Refs can never go
        negative — that is a corrupted table, not a recoverable
        state."""
        if self._ref[page] <= 0:
            raise RuntimeError(
                f"page {page} decref below zero (double free)"
            )
        self._ref[page] -= 1
        if self._ref[page] == 0:
            if park:
                self._parked[page] = None
            else:
                self._free.append(page)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    def snapshot(self) -> Dict[str, int]:
        """Counters for leak checks: a drained engine must return to
        the baseline snapshot (every page free or parked, no refs)."""
        return {
            "free": len(self._free),
            "parked": len(self._parked),
            "available": self.available,
            "refs": sum(self._ref),
        }

    def assert_consistent(self) -> None:
        """The PR-7 allocator invariants, as one assertable check —
        the robustness tests run it after EVERY teardown path
        (cancel, deadline, quarantine, preempt, requeue, drain):

        * free, parked, and referenced pages partition the pool
          (no page in two states, none lost);
        * no parked or free page holds a reference;
        * no referenced page sits on the free list or the parked LRU.

        Raises AssertionError naming the corrupted page otherwise."""
        free = set(self._free)
        parked = set(self._parked)
        assert len(free) == len(self._free), (
            f"free list holds duplicates: {sorted(self._free)}"
        )
        assert not (free & parked), (
            f"pages both free and parked: {sorted(free & parked)}"
        )
        for page in range(self.num_pages):
            refs = self._ref[page]
            assert refs >= 0, f"page {page} has negative refs ({refs})"
            if page in free or page in parked:
                assert refs == 0, (
                    f"page {page} is free/parked with refs={refs}"
                )
            else:
                assert refs > 0, (
                    f"page {page} leaked: not free, not parked, "
                    f"refs=0"
                )


class _StoreEntry:
    __slots__ = ("key", "parent", "tokens", "page")

    def __init__(self, key, parent, tokens, page):
        self.key = key
        self.parent = parent
        self.tokens = tokens
        self.page = page


class PrefixStore:
    """Chain-hash registry of immutable, fully-written prompt pages.

    A page is registerable once it holds ``page_size`` PROMPT tokens
    (appends only ever land past a full page, so its bytes are final;
    pages mixing prompt and generated tokens are never registered).
    The key of a page is the chain ``(parent_key, its page_size token
    ids)`` — two requests share a page only if their ENTIRE token
    history up to that page matches, which is exactly the condition
    under which the K/V bytes are identical (absolute positions).

    `match` walks a prompt down the chain: full-page hits map by
    reference; after the last full hit, the longest token-level prefix
    of any CHILD page is matched PARTIALLY — the borrower reads the
    shared page's first j rows and must copy-on-write before its own
    tokens land in that page. At least one prompt token is always left
    unmatched (the final token must run through the model to produce
    the first sampled logits).
    """

    def __init__(self, page_size: int):
        self.page_size = page_size
        self._by_chain: Dict[Any, _StoreEntry] = {}
        self._children: Dict[Any, Set[_StoreEntry]] = {}
        self._by_page: Dict[int, _StoreEntry] = {}
        # optional pub/sub hooks, called as ``hook(chain_key, page)``
        # when a registration appears/disappears in THIS store — the
        # router's cross-replica SharedPrefixRegistry subscribes here.
        # Chain keys are pure token tuples, so a subscriber can index
        # them without holding any store state.
        self.on_register = None
        self.on_unregister = None

    def __len__(self) -> int:
        return len(self._by_page)

    def is_registered(self, page: int) -> bool:
        return page in self._by_page

    def register(
        self, parent_key, tokens: Sequence[int], page: int
    ):
        """Register a full page (its ``page_size`` token ids) under
        ``parent_key`` (None for the first page of a prompt); returns
        the new chain key for the NEXT page's parent. First
        registration wins: a duplicate chain keeps the existing page
        (the caller's page simply stays private)."""
        tokens = tuple(int(t) for t in tokens)
        if len(tokens) != self.page_size:
            raise ValueError(
                f"register needs exactly page_size={self.page_size} "
                f"tokens, got {len(tokens)}"
            )
        key = (parent_key, tokens)
        if key in self._by_chain:
            return key
        entry = _StoreEntry(key, parent_key, tokens, page)
        self._by_chain[key] = entry
        self._children.setdefault(parent_key, set()).add(entry)
        self._by_page[page] = entry
        if self.on_register is not None:
            self.on_register(key, page)
        return key

    def chain_key(self, parent_key, tokens: Sequence[int]):
        """The key `register` would produce — lets a slot continue a
        chain it is re-walking without registering anything."""
        return (parent_key, tuple(int(t) for t in tokens))

    def unregister_page(self, page: int) -> None:
        entry = self._by_page.pop(page, None)
        if entry is None:
            return
        del self._by_chain[entry.key]
        if self.on_unregister is not None:
            self.on_unregister(entry.key, page)
        kids = self._children.get(entry.parent)
        if kids is not None:
            kids.discard(entry)
            if not kids:
                del self._children[entry.parent]
        # orphaned descendants (their parent chain is gone) can no
        # longer be matched — drop them so they do not pin pages
        for child in list(self._children.get(entry.key, ())):
            self.unregister_page(child.page)

    def match(
        self, prompt: Sequence[int]
    ) -> Tuple[List[int], int, int, Any]:
        """Longest shared prefix of ``prompt`` already materialized.

        Returns ``(pages, matched_tokens, partial_tokens, chain_key)``:
        the shared pages in order, how many prompt tokens they cover
        (``< len(prompt)``), how many of those are a PARTIAL borrow of
        the last page (0 = every matched page is fully covered), and
        the chain key of the last FULL page matched (the parent under
        which the borrower registers its next full page).
        """
        ps = self.page_size
        limit = len(prompt) - 1  # leave >= 1 token to prefill
        pages: List[int] = []
        key = None
        m = 0
        while m + ps <= limit:
            entry = self._by_chain.get(
                (key, tuple(int(t) for t in prompt[m:m + ps]))
            )
            if entry is None:
                break
            pages.append(entry.page)
            key = entry.key
            m += ps
        best = None
        best_len = 0
        rest = [int(t) for t in prompt[m:limit]]
        if rest:
            for child in self._children.get(key, ()):
                n = 0
                for a, b in zip(child.tokens, rest):
                    if a != b:
                        break
                    n += 1
                if n > best_len:
                    best, best_len = child, n
        if best is not None:
            pages.append(best.page)
            m += best_len
        return pages, m, best_len, key


@struct.dataclass
class PagedKVCache:
    """Device half of the paged cache; a jit-friendly pytree.

    ``k``/``v``: per-layer POOLS, ``(num_pages, heads_local,
    page_size, head_dim)`` (heads ahead of the page rows so a
    (page, head) tile is the trailing-two-dims block the Pallas paged
    kernel fetches natively). ``k_scale``/``v_scale``: per-layer
    ``(num_pages, heads_local)`` fp32 when the pools are int8, else
    None. ``page_table``: ``(num_slots, pages_per_slot)`` int32 —
    unmapped entries hold the sentinel ``num_pages`` (writes there
    drop; the host engine owns the mapping and mirrors it).
    ``lengths`` as in `KVCache`.

    `write`/`write_at` keep the contiguous cache's signatures — the
    indirection is resolved inside (ops/paging.py) — so the model's
    cached attention calls the same protocol either way.
    """

    k: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]
    k_scale: Optional[Tuple[jnp.ndarray, ...]]
    v_scale: Optional[Tuple[jnp.ndarray, ...]]
    page_table: jnp.ndarray
    lengths: jnp.ndarray
    page_size: int = struct.field(pytree_node=False, default=16)

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @classmethod
    def create(
        cls,
        num_layers: int,
        num_slots: int,
        capacity: int,
        num_heads: int,
        head_dim: int,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        dtype: Any = jnp.bfloat16,
        quantized: bool = False,
        validate_tpu_layout: Optional[bool] = None,
    ) -> "PagedKVCache":
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        pool_dtype = jnp.int8 if quantized else dtype
        if validate_tpu_layout is None:
            validate_tpu_layout = jax.default_backend() == "tpu"
        if validate_tpu_layout:
            # TPU-silicon constraint (deferred from the paged-kernel
            # PR): the paged flash kernels DMA (page, head) tiles whose
            # second-minor dim is page_size, so it must be a sublane
            # multiple for the pool dtype — 8 rows × 4 bytes packed,
            # i.e. 8 for fp32, 16 for bf16, 32 for int8. A non-multiple
            # page relayouts every pool tile on each read.
            sublanes = 32 // jnp.dtype(pool_dtype).itemsize
            if page_size % sublanes != 0:
                raise ValueError(
                    f"page_size={page_size} is not a sublane multiple "
                    f"for {jnp.dtype(pool_dtype).name} pools: the TPU "
                    f"paged kernels need page_size % {sublanes} == 0 "
                    f"(8 for fp32, 16 for bf16, 32 for int8)"
                )
        pages_per_slot = -(-capacity // page_size)  # ceil
        if num_pages is None:
            # worst-case default: every slot full — safe, but the
            # memory win comes from sizing num_pages to expected LIVE
            # tokens (see docs/inference.md)
            num_pages = num_slots * pages_per_slot
        shape = (num_pages, num_heads, page_size, head_dim)
        scales = (
            tuple(
                jnp.zeros((num_pages, num_heads), jnp.float32)
                for _ in range(num_layers)
            )
            if quantized else None
        )
        return cls(
            k=tuple(jnp.zeros(shape, pool_dtype) for _ in range(num_layers)),
            v=tuple(jnp.zeros(shape, pool_dtype) for _ in range(num_layers)),
            k_scale=scales,
            v_scale=None if scales is None else tuple(
                jnp.zeros((num_pages, num_heads), jnp.float32)
                for _ in range(num_layers)
            ),
            page_table=jnp.full(
                (num_slots, pages_per_slot), num_pages, jnp.int32
            ),
            lengths=jnp.zeros((num_slots,), jnp.int32),
            page_size=page_size,
        )

    @classmethod
    def for_model(
        cls,
        cfg,
        num_slots: int,
        capacity: Optional[int] = None,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        dtype: Any = None,
        quantized: bool = False,
        full_heads: bool = False,
    ) -> "PagedKVCache":
        """Paged cache sized for a `GPTConfig`-shaped config (same
        duck-typing as `KVCache.for_model`; heads are the LOCAL
        per-TP-rank count). ``full_heads=True`` keeps the GLOBAL head
        count instead — the tp>1 serving engine builds the pools at
        full heads and lays them out with a head-sharded
        `NamedSharding`, so each chip holds 1/tp of the heads while
        host-side fetches still see full-head arrays (which is what
        makes shipped pages tp-agnostic)."""
        tp = 1 if full_heads else (cfg.tensor_parallel_size or 1)
        return cls.create(
            cfg.num_layers,
            num_slots,
            capacity or cfg.max_position_embeddings,
            cfg.num_attention_heads // tp,
            cfg.head_dim,
            page_size=page_size,
            num_pages=num_pages,
            dtype=dtype if dtype is not None else cfg.dtype,
            quantized=quantized,
        )

    # ------------------------------------------------------------------
    # shape facts
    # ------------------------------------------------------------------

    @property
    def num_layers(self) -> int:
        return len(self.k)

    @property
    def num_slots(self) -> int:
        return self.page_table.shape[0]

    @property
    def pages_per_slot(self) -> int:
        return self.page_table.shape[1]

    @property
    def num_pages(self) -> int:
        return self.k[0].shape[0]

    @property
    def capacity(self) -> int:
        """Rows addressable per slot. May exceed a requested capacity
        that does not divide page_size (the engine's host bound stays
        authoritative)."""
        return self.pages_per_slot * self.page_size

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def cache_bytes(self) -> int:
        """Device bytes this cache actually allocates (pools + scales
        + table + lengths) — the number the bench's cache-bytes line
        reports against the contiguous equivalent."""
        total = 0
        for arrs in (self.k, self.v, self.k_scale or (), self.v_scale or ()):
            for a in arrs:
                total += a.size * a.dtype.itemsize
        total += self.page_table.size * self.page_table.dtype.itemsize
        total += self.lengths.size * self.lengths.dtype.itemsize
        return total

    # ------------------------------------------------------------------
    # functional updates (all jit-safe)
    # ------------------------------------------------------------------

    def _scatter(self, layer, slots, positions, k_new, v_new):
        k = list(self.k)
        v = list(self.v)
        if self.quantized:
            ks = list(self.k_scale)
            vs = list(self.v_scale)
            k[layer], ks[layer] = quantized_paged_scatter(
                self.k[layer], self.k_scale[layer], self.page_table,
                slots, positions, k_new,
            )
            v[layer], vs[layer] = quantized_paged_scatter(
                self.v[layer], self.v_scale[layer], self.page_table,
                slots, positions, v_new,
            )
            return self.replace(
                k=tuple(k), v=tuple(v),
                k_scale=tuple(ks), v_scale=tuple(vs),
            )
        k[layer] = paged_scatter(
            self.k[layer], self.page_table, slots, positions, k_new
        )
        v[layer] = paged_scatter(
            self.v[layer], self.page_table, slots, positions, v_new
        )
        return self.replace(k=tuple(k), v=tuple(v))

    def write(self, layer: int, k_new: jnp.ndarray, v_new: jnp.ndarray
              ) -> "PagedKVCache":
        """`KVCache.write` semantics — ``(num_slots, t, heads, hd)``
        new rows land at each slot's current length — scattered
        through the page table. Positions at/past capacity DROP
        (where the contiguous cache clamped onto its last row, a
        paged write must never land in somebody else's page); lengths
        do not advance here."""
        num_slots, t = k_new.shape[0], k_new.shape[1]
        slots = jnp.repeat(jnp.arange(num_slots, dtype=jnp.int32), t)
        positions = (
            self.lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        ).reshape(-1)
        h, hd = k_new.shape[2], k_new.shape[3]
        return self._scatter(
            layer, slots, positions,
            k_new.reshape(num_slots * t, h, hd),
            v_new.reshape(num_slots * t, h, hd),
        )

    def write_at(
        self,
        layer: int,
        slots: jnp.ndarray,
        positions: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
    ) -> "PagedKVCache":
        """`KVCache.write_at` semantics (packed chunk at explicit
        per-token destinations; pad tokens carry slot id >= num_slots
        and drop) routed through the page table. The drop path is
        what lets speculative drafts defer their commit: a rejected
        draft row is simply never scattered, so it can never have
        touched a shared (CoW) page or grown an int8 page scale —
        the engine's post-verification commit re-issues only the
        accepted rows."""
        return self._scatter(layer, slots, positions, k_new, v_new)

    def advance(self, t: int, active: Optional[jnp.ndarray] = None
                ) -> "PagedKVCache":
        """`KVCache.advance` semantics. The clamp only keeps idle
        slots from drifting — the ENGINE is responsible for never
        letting a live request reach capacity (it raises host-side
        with the slot id; see `InferenceEngine`), and the paged write
        path independently drops at-capacity writes instead of
        clamping them into a live page."""
        new = jnp.minimum(self.lengths + t, self.capacity)
        if active is not None:
            new = jnp.where(active, new, self.lengths)
        return self.replace(lengths=new)

    def reset_slot(self, slot) -> "PagedKVCache":
        """Forget a slot's length. The page-table row is HOST state —
        the engine sentinels its mirror and pushes it with the next
        step (stale device entries are unreachable meanwhile: every
        read is bounded by lengths)."""
        return self.replace(
            lengths=jax.lax.dynamic_update_slice(
                self.lengths, jnp.zeros((1,), jnp.int32), (slot,)
            )
        )

    def fork_page(self, src, dst) -> "PagedKVCache":
        """Copy-on-write device half: duplicate page ``src`` onto
        ``dst`` in every layer's pools (and scales). ``src``/``dst``
        may be traced — the engine jits this once and calls it for
        every fork."""
        k = tuple(paged_fork(b, src, dst) for b in self.k)
        v = tuple(paged_fork(b, src, dst) for b in self.v)
        if not self.quantized:
            return self.replace(k=k, v=v)
        return self.replace(
            k=k, v=v,
            k_scale=tuple(
                s.at[dst].set(s[src]) for s in self.k_scale
            ),
            v_scale=tuple(
                s.at[dst].set(s[src]) for s in self.v_scale
            ),
        )
