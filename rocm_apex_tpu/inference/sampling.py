"""Token sampling: greedy, temperature, top-k, top-p.

All transforms are pure jnp on ``(..., vocab)`` logits with STATIC
configuration (python floats/ints), so they trace once inside the
engine's compiled `decode_step` and never branch on device values.
Randomness is functional (`jax.random`): a fixed engine seed replays
the exact token stream — the serving analogue of the training side's
deterministic functional dropout.

Filters compose in the conventional order (temperature → top-k →
top-p), matching the sampling stacks of the serving engines this
reproduces the semantics of.
"""

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "greedy",
    "top_k_logits",
    "top_p_logits",
    "sample",
]

# Large-negative instead of -inf for masked logits: -inf - (-inf) in a
# downstream shift would NaN; -1e30 survives every softmax/categorical
# path identically (exp underflows to exactly 0).
_MASKED = -1e30


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """Argmax token ids, int32."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def top_k_logits(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask everything below the k-th largest logit per row."""
    if k <= 0:
        raise ValueError(f"top_k must be positive, got {k}")
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _MASKED, logits)


def top_p_logits(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filter: keep the smallest prefix of the
    probability-sorted vocabulary whose mass reaches ``p``.

    A sorted token is kept iff the mass strictly BEFORE it is < p, so
    the first token is always kept (even when it alone exceeds p) and
    the kept set is the minimal one with total mass >= p.
    """
    if not 0.0 < p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {p}")
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep = mass_before < p
    # smallest kept logit = the admission threshold
    thresh = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < thresh, _MASKED, logits)


def sample(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jnp.ndarray:
    """Draw int32 token ids from ``(..., vocab)`` logits.

    ``temperature == 0.0`` is exact greedy (no rng consumed on the
    value path — the draw is bypassed at trace time). Config is static:
    changing it recompiles the caller, which is the engine's contract
    (sampling params are fixed per engine).
    """
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return greedy(logits)
    logits = logits / float(temperature)
    if top_k is not None:
        logits = top_k_logits(logits, int(top_k))
    if top_p is not None and top_p < 1.0:
        logits = top_p_logits(logits, float(top_p))
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
