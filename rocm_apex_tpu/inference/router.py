"""Multi-replica serving fabric: the host-level `ReplicaRouter`.

One stalled engine must never be a total outage. The router owns N
independent `InferenceEngine` replicas behind the engine's own surface
(`add_request` / `step` / `generate` / `stats` / `drain`) and adds the
fleet behaviours the single engine cannot express:

**Routing & admission.** A bounded global queue feeds per-replica
admission: each router tick dispatches pending requests to in-rotation
replicas, prefix-affinity first — the `PrefixStore` chain hash routes
a prompt to the replica already holding its prefix pages via the
fleet-wide `SharedPrefixRegistry` (each store's register/unregister
hooks publish its chains, so placement is one chain walk instead of N
engine consults), so CoW sharing keeps working across the fleet —
then least-loaded by the replica's live signals (queue depth, slot
occupancy, ``pages_used``). Per-replica backlogs stay shallow
(``replica_queue_depth``) so work left in the GLOBAL queue can still
be placed anywhere when a replica dies.

**Disaggregated prefill/decode (replica classes).** Pass
``replica_classes=["prefill", "decode", ...]`` and placement
specializes: fresh prompts land on prefill-class replicas (chunk-heavy
ticks), and the moment a request's first token is out the prefill
replica evacuates it WITH its KV pages
(`InferenceEngine.evacuate_request(ship_pages=True)`) for a
decode-class replica, which imports the pages directly into its own
pool — no re-prefill — and runs near-pure decode grids at full
occupancy. Per-class TTFT/TPOT land in the labeled
``router_ttft_ms``/``router_tpot_ms`` histogram families. Class
preference never costs availability: with no decode capacity the
request keeps decoding where it is, and a failed page import falls
back to token replay — token-identical either way.

**Failure detection & recovery.** Three detectors run every tick:
consecutive `step()` failures (device faults, watchdog raises),
`engine_health`-style probes (watchdog-fire count), and a
zero-progress probe over `progress_marker` for replicas that have work
but move no tokens. A replica crossing its threshold is QUARANTINED
and every request it held is resubmitted to the rest of the fleet as
prompt + tokens emitted so far — the vLLM recompute transition (arXiv
2309.06180) generalized to replica death. On a paged cache the
quarantine/drain paths additionally SHIP each slot's KV page blocks
with the record (``evacuate(ship_pages=True)``): the destination
imports them straight into its `PageAllocator` and skips the
recompute. Either way continuation is greedy decode through the
destination's chunked prefill (arXiv 2403.02310), so recovered
outputs are token-identical to an undisturbed run and no token is
ever emitted twice: the router delivers each request's result
exactly once (`_deliver` enforces it). For `replica_kill` the engine's
state is presumed LOST — recovery reads the router's own per-request
token mirror (refreshed from `outstanding()` after every successful
replica tick), never the dead engine; the carcass is then evacuated so
its pages and slots provably free. A quarantined replica is re-probed
after ``rejoin_after`` ticks: `InferenceEngine.reopen()` verifies the
clean state and the replica rejoins rotation.

**Rolling drain.** `drain_replica(i)` migrates the replica's queue and
in-flight work to the fleet and takes it out of rotation —
restart-without-downtime; `rejoin_replica(i)` is the return path.
`drain()` drains the whole fleet.

**Fleet chaos & telemetry.** The same seeded `FaultPlan` that drives
engine-level chaos gains replica-scoped sites (``replica_kill`` /
``replica_stall`` / ``replica_slow``, consulted once per router tick;
``fault_log`` records the (site, tick, replica) sequence so `reset()`
replays bit-identically). Router events land in a router-local
`MetricRegistry` and `merged_registry()` folds it with every replica's
registry via ``merge_from`` — bucket-wise histogram merge is exact, so
fleet `/metrics` percentiles reproduce the combined per-replica
completion streams (serve it per-scrape through the exporter's
zero-arg registry provider).

Everything here is host bookkeeping: the compiled programs never see
the router, each replica's ``mixed_trace_count`` stays 1, and the
graphlint fingerprints are unchanged.
"""

import collections
import time
from typing import Any, Dict, List, Optional, Sequence

from rocm_apex_tpu.inference.engine import (
    GenerationResult,
    InferenceEngine,
)
from rocm_apex_tpu.inference.faults import NO_FAULTS, FaultPlan
from rocm_apex_tpu.monitor.trace import (
    NULL_TRACER,
    merge_traces,
    mint_trace_id,
)

__all__ = [
    "ReplicaRouter", "SharedPrefixRegistry", "REPLICA_STATES",
    "REPLICA_CLASSES",
]

#: Replica rotation states: ``up`` serves traffic; ``quarantined`` was
#: failed out and awaits a rejoin probe; ``drained`` was rolled out on
#: purpose (`drain_replica`) and waits for `rejoin_replica`.
REPLICA_STATES = ("up", "quarantined", "drained")

#: Replica placement classes: ``mixed`` takes anything (the default —
#: a classic homogeneous fleet); ``prefill`` prefers fresh prompts and
#: hands each request off (with its KV pages) once its first token is
#: out; ``decode`` prefers carried requests — pure decode grids at
#: full occupancy.
REPLICA_CLASSES = ("mixed", "prefill", "decode")


class SharedPrefixRegistry:
    """Cross-replica index of materialized prefix chains.

    Each replica's `PrefixStore` keys pages by the pure chain hash
    ``(parent_key, page tokens)`` — a value any party can recompute
    from the tokens alone, no store needed. This registry subscribes to
    every store's register/unregister hooks and maintains
    ``chain key -> {replica indices holding that chain}``, so placement
    answers "who already holds this prompt's prefix pages?" with one
    O(prompt pages) walk instead of consulting N engines per request.
    Host bookkeeping only; the stores remain the page owners — the
    registry never pins a page."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self._holders: Dict[Any, set] = {}

    def __len__(self) -> int:
        return len(self._holders)

    def publish(self, replica: int, key) -> None:
        self._holders.setdefault(key, set()).add(replica)

    def unpublish(self, replica: int, key) -> None:
        holders = self._holders.get(key)
        if holders is None:
            return
        holders.discard(replica)
        if not holders:
            del self._holders[key]

    def holders(self, key) -> frozenset:
        return frozenset(self._holders.get(key, ()))

    def best(self, prompt: Sequence[int]) -> Dict[int, int]:
        """``replica index -> matched prefix tokens`` over the full
        pages of ``prompt`` (leaving >= 1 token unmatched, the store's
        own contract). Chain containment makes per-replica matches
        contiguous, so each replica's entry is simply the deepest
        chain it still holds."""
        ps = self.page_size
        limit = len(prompt) - 1
        key = None
        m = 0
        matched: Dict[int, int] = {}
        while m + ps <= limit:
            key = (key, tuple(int(t) for t in prompt[m:m + ps]))
            holders = self._holders.get(key)
            if not holders:
                break
            m += ps
            for idx in holders:
                matched[idx] = m
        return matched


class _Replica:
    """Router-side bookkeeping for one engine."""

    def __init__(
        self, index: int, engine: InferenceEngine,
        replica_class: str = "mixed",
    ):
        self.index = index
        self.engine = engine
        self.replica_class = replica_class
        self.completions_seen = 0
        self.state = "up"
        self.consecutive_failures = 0
        self.no_progress_ticks = 0
        self.progress_mark = engine.progress_marker
        self.quarantined_at = -1
        self.last_error = ""
        # injected-fault latches (replica_stall / replica_slow)
        self.stall_ticks = 0
        self.slow_ticks = 0
        self.slow_seconds = 0.0

    @property
    def in_rotation(self) -> bool:
        return self.state == "up"


class ReplicaRouter:
    """N `InferenceEngine` replicas behind one serving surface.

    Build replicas from a model (each with a private registry, the
    shared fault plan, and identical ``engine_kwargs`` — identical
    configs keep greedy outputs replica-independent)::

        router = ReplicaRouter(model, params, replicas=2,
                               engine_kwargs=dict(num_slots=2, ...))

    or wrap engines you built yourself (``engines=[...]``; they must
    be chunked — migration recomputes through the prefill budget).

    ``max_queue`` bounds the GLOBAL queue (shed-newest, ``queue_full``
    results delivered through `step()`, exactly like the engine's
    bounded admission). ``failure_threshold`` consecutive step
    failures, any watchdog fire, or ``stall_grace`` zero-progress
    ticks quarantine a replica; after ``rejoin_after`` router ticks a
    quarantine is probed for rejoin (`reopen()` + health). Pass
    ``faults`` to drive fleet chaos (see module docstring).
    """

    def __init__(
        self,
        model=None,
        params=None,
        *,
        replicas: int = 2,
        engines: Optional[Sequence[InferenceEngine]] = None,
        engine_kwargs: Optional[Dict[str, Any]] = None,
        replica_classes: Optional[Sequence[str]] = None,
        max_queue: Optional[int] = None,
        replica_queue_depth: int = 2,
        faults: Optional[FaultPlan] = None,
        failure_threshold: int = 2,
        stall_grace: int = 3,
        rejoin_after: int = 8,
        registry=None,
        tracer=None,
        retrace_policy: Optional[str] = None,
        timeseries=None,
    ):
        self.faults = faults if faults is not None else NO_FAULTS
        self.tracer = tracer if tracer is not None else NULL_TRACER
        if engines is not None:
            engines = list(engines)
        else:
            if model is None or params is None:
                raise ValueError(
                    "pass model+params (the router builds the "
                    "replicas) or engines=[...]"
                )
            kw = dict(engine_kwargs or {})
            if "prefill_token_budget" not in kw:
                raise ValueError(
                    "engine_kwargs must set prefill_token_budget: "
                    "migration recomputes prompt + emitted tokens "
                    "through the chunked prefill"
                )
            kw.pop("registry", None)  # each replica scrapes privately
            kw.setdefault("faults", self.faults)
            kw.pop("step_source", None)
            # identical configs -> replicas 1..N adopt replica 0's
            # compiled step programs: the fleet traces (and warms up)
            # once, not N times
            engines = [InferenceEngine(model, params, **kw)]
            for _ in range(1, int(replicas)):
                engines.append(
                    InferenceEngine(
                        model, params, step_source=engines[0], **kw
                    )
                )
        if not engines:
            raise ValueError("need at least one replica")
        for i, eng in enumerate(engines):
            if not eng.chunked:
                raise ValueError(
                    f"replica {i} is a whole-prompt engine; the "
                    f"router needs chunked engines "
                    f"(prefill_token_budget) so migrated requests can "
                    f"recompute their carried tokens"
                )
        if replica_classes is None:
            replica_classes = ["mixed"] * len(engines)
        replica_classes = [str(c) for c in replica_classes]
        if len(replica_classes) != len(engines):
            raise ValueError(
                f"replica_classes has {len(replica_classes)} entries "
                f"for {len(engines)} replicas"
            )
        for c in replica_classes:
            if c not in REPLICA_CLASSES:
                raise ValueError(
                    f"unknown replica class {c!r}; classes are "
                    f"{REPLICA_CLASSES}"
                )
        if "prefill" in replica_classes and (
            "decode" not in replica_classes
        ):
            raise ValueError(
                "a prefill-class replica needs at least one "
                "decode-class replica to hand finished prompts to"
            )
        self._has_classes = any(
            c != "mixed" for c in replica_classes
        )
        if self._has_classes:
            for i, eng in enumerate(engines):
                if not eng.paged:
                    raise ValueError(
                        f"replica {i}: prefill/decode classes need "
                        f"paged engines — the handoff ships KV pages"
                    )
        self._replicas = [
            _Replica(i, eng, replica_classes[i])
            for i, eng in enumerate(engines)
        ]
        # cross-replica shared prefix registry: subscribe to every
        # compatible PrefixStore's register/unregister hooks so
        # placement sees the whole fleet's materialized chains
        self._prefix_registry: Optional[SharedPrefixRegistry] = None
        stores = [
            (rep.index, rep.engine._store) for rep in self._replicas
            if getattr(rep.engine, "_store", None) is not None
        ]
        if stores:
            page_size = stores[0][1].page_size
            registry_ = SharedPrefixRegistry(page_size)
            for idx, store in stores:
                if store.page_size != page_size:
                    continue  # incompatible chain geometry: skip
                store.on_register = (
                    lambda key, page, i=idx: registry_.publish(i, key)
                )
                store.on_unregister = (
                    lambda key, page, i=idx: registry_.unpublish(i, key)
                )
            self._prefix_registry = registry_
        self.capacity = min(eng.capacity for eng in engines)
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        if replica_queue_depth < 0:
            raise ValueError(
                f"replica_queue_depth must be >= 0, got "
                f"{replica_queue_depth}"
            )
        self.replica_queue_depth = int(replica_queue_depth)
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got "
                f"{failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        if stall_grace < 1:
            raise ValueError(
                f"stall_grace must be >= 1, got {stall_grace}"
            )
        self.stall_grace = int(stall_grace)
        if rejoin_after < 1:
            raise ValueError(
                f"rejoin_after must be >= 1, got {rejoin_after}"
            )
        self.rejoin_after = int(rejoin_after)
        # the global queue: migration records (prompt + carried
        # tokens), dispatched to replicas via resume_request — one
        # admission path for fresh AND recovered requests
        self._pending: collections.deque = collections.deque()
        self._assigned: Dict[int, int] = {}  # rid -> replica index
        # the router's OWN copy of every live request's emitted
        # tokens, refreshed after each successful replica tick — the
        # recovery source when an engine dies without warning
        self._mirror: Dict[int, Dict[str, Any]] = {}
        self._shed_results: List[GenerationResult] = []
        self._done: set = set()
        self._next_id = 0
        self._tick = 0
        self._draining = False
        self._submitted = 0
        self._shed = 0
        self._migrations = 0
        self._quarantines = 0
        self._rejoins = 0
        self._affinity_hits = 0
        self._adapter_affinity_hits = 0
        self._kills = 0
        self._handoffs = 0
        self._page_migrations = 0
        self._finished: Dict[str, int] = {}
        #: every replica-scoped fault that fired, as (site, tick,
        #: replica) — the `FaultPlan.reset()` replay witness
        self.fault_log: List[tuple] = []
        if registry is None:
            from rocm_apex_tpu.monitor.telemetry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        self._c_events = registry.counter(
            "router_events_total",
            "Fleet lifecycle events (migration, page_migration, "
            "handoff, quarantine, rejoin, affinity_hit, "
            "adapter_affinity_hit, kill, shed, "
            "drain_replica).",
            labelnames=("event",),
        )
        self._g_healthy = registry.gauge(
            "router_healthy_replicas", "Replicas in rotation."
        )
        self._g_pending = registry.gauge(
            "router_queue_depth", "Requests in the global queue."
        )
        # per-class latency attribution (PR-14 labeled families): a
        # request observes under the class of the replica it FINISHED
        # on — in a disaggregated fleet that is the decode class for
        # every handed-off request, which is exactly the class whose
        # TTFT/TPOT SLO the disaggregation is supposed to protect
        self._h_class_ttft = registry.histogram(
            "router_ttft_ms",
            "Time to first token (enqueue -> first token), ms, by the "
            "finishing replica's class.",
            labelnames=("replica_class",),
        )
        self._h_class_tpot = registry.histogram(
            "router_tpot_ms",
            "Mean inter-token time after the first token, ms, by the "
            "finishing replica's class.",
            labelnames=("replica_class",),
        )
        self._g_healthy.set(len(self._replicas))
        # runtime retrace sentinel (ISSUE 19): jax compile events are
        # process-global, so ONE router-held sentinel guards the whole
        # fleet — arm it after warmup (`arm_retrace_sentinel()`, or any
        # replica's reset_stats when per-replica sentinels are used);
        # "raise" fails the next fleet tick on a post-warmup compile
        self.retrace_sentinel = None
        if retrace_policy is not None:
            from rocm_apex_tpu.monitor.trace import RetraceSentinel

            self.retrace_sentinel = RetraceSentinel(
                registry, policy=retrace_policy, tracer=self.tracer
            )
        # sensor plane: the ring samples the ROUTER registry (its own
        # families); pass TimeSeriesStore(router.merged_registry) for
        # fleet-wide series — snapshot() on a merged registry costs a
        # merge per sample, so pick the interval accordingly
        self.timeseries = timeseries

    # ------------------------------------------------------------------
    # public surface (mirrors InferenceEngine)
    # ------------------------------------------------------------------

    @property
    def num_replicas(self) -> int:
        return len(self._replicas)

    @property
    def tick_count(self) -> int:
        return self._tick

    @property
    def draining(self) -> bool:
        return self._draining

    def replica(self, i: int) -> InferenceEngine:
        return self._replicas[i].engine

    def replica_state(self, i: int) -> str:
        return self._replicas[i].state

    @property
    def healthy_replicas(self) -> int:
        return sum(1 for rep in self._replicas if rep.in_rotation)

    def has_work(self) -> bool:
        return bool(
            self._pending or self._shed_results or self._assigned
            or any(
                rep.engine.has_work() for rep in self._replicas
            )
        )

    def add_request(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        request_id: Optional[int] = None,
        *,
        timeout: Optional[float] = None,
        queue_ttl: Optional[float] = None,
        adapter_id: int = 0,
        tenant: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        """Queue a prompt with the fleet; same contract as
        `InferenceEngine.add_request` (ids, deadlines, bounded
        admission with shed-newest ``queue_full`` results delivered by
        the next `step()`, raises once draining). Placement happens at
        the next tick's dispatch; non-base ``adapter_id`` requests
        prefer replicas where the adapter is already resident.

        Admission mints the request's fleet-causal ``trace_id`` (one
        per admitted request, NOT per attempt): it rides every
        dispatch, migration, failover, and handoff hop so
        `merged_trace` renders the whole lifeline under one id."""
        if self._draining:
            raise RuntimeError(
                "router is draining: admission is closed "
                "(drain() was called)"
            )
        adapter_id = int(adapter_id)
        if adapter_id != 0:
            pools = [
                rep.engine.adapter_pool for rep in self._replicas
                if rep.engine.adapter_pool is not None
            ]
            if not pools:
                raise ValueError(
                    "adapter_id requires replicas built with an "
                    "AdapterPool"
                )
            if not any(p.known(adapter_id) for p in pools):
                raise KeyError(
                    f"adapter {adapter_id} is not registered with any "
                    f"replica's pool"
                )
            if tenant is None:
                for p in pools:
                    if p.known(adapter_id):
                        tenant = p.tenant_of(adapter_id)
                        break
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if len(prompt) > self.capacity:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the fleet cache "
                f"capacity {self.capacity} (rows per slot)"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if timeout is not None and timeout <= 0:
            raise ValueError(f"timeout must be > 0 s, got {timeout}")
        if queue_ttl is not None and queue_ttl <= 0:
            raise ValueError(
                f"queue_ttl must be > 0 s, got {queue_ttl}"
            )
        if request_id is None:
            request_id = self._next_id
        self._next_id = max(self._next_id, request_id) + 1
        if trace_id is None:
            trace_id = mint_trace_id()
        now = time.perf_counter()
        self._submitted += 1
        if (
            self.max_queue is not None
            and len(self._pending) >= self.max_queue
        ):
            self._shed += 1
            self._count_event("shed")
            self._shed_results.append(GenerationResult(
                request_id=request_id, prompt=prompt, tokens=[],
                finish_reason="queue_full",
            ))
            if self.tracer.enabled:
                self.tracer.instant(
                    "shed", ts=now, track=f"req{request_id}",
                    queue_depth=len(self._pending),
                    request_id=request_id, trace_id=trace_id,
                )
            return request_id
        self._pending.append({
            "request_id": request_id,
            "prompt": prompt,
            "max_new_tokens": int(max_new_tokens),
            "generated": [],
            "enqueued_at": now,
            "deadline": (now + timeout) if timeout is not None else None,
            "queue_deadline": (
                (now + queue_ttl) if queue_ttl is not None else None
            ),
            "first_token_at": 0.0,
            "chunks": 0,
            "adapter_id": adapter_id,
            "tenant": tenant,
            "trace_id": trace_id,
        })
        if self.tracer.enabled:
            self.tracer.instant(
                "admit", ts=now, track=f"req{request_id}",
                prompt_tokens=len(prompt),
                request_id=request_id, trace_id=trace_id,
            )
        return request_id

    def step(self) -> List[GenerationResult]:
        """One fleet tick: consult the replica fault sites, expire
        global-queue deadlines, dispatch pending work, step every
        in-rotation replica (collecting finishes and refreshing the
        token mirror), then run the failure detectors and rejoin
        probes. Returns every request that finished this tick —
        exactly once each, whichever replica(s) it lived on."""
        now = time.perf_counter()
        out: List[GenerationResult] = []
        if self._shed_results:
            out.extend(self._shed_results)
            for r in self._shed_results:
                self._mark_done(r)
            self._shed_results = []
        self._consult_faults()
        self._expire_pending(now, out)
        self._dispatch(now)
        for rep in self._replicas:
            if not rep.in_rotation:
                continue
            if rep.stall_ticks > 0:
                # injected stall: the replica is not stepped — its
                # requests sit, and the zero-progress probe below is
                # what must notice
                rep.stall_ticks -= 1
                continue
            if rep.slow_ticks > 0 and rep.engine.has_work():
                rep.slow_ticks -= 1
                time.sleep(rep.slow_seconds)
            if not rep.engine.has_work():
                rep.consecutive_failures = 0
                rep.no_progress_ticks = 0
                rep.progress_mark = rep.engine.progress_marker
                continue
            try:
                results = rep.engine.step()
            except Exception as exc:  # noqa: BLE001 - fault isolation
                rep.consecutive_failures += 1
                rep.last_error = f"{type(exc).__name__}: {exc}"
                if (
                    rep.consecutive_failures >= self.failure_threshold
                ):
                    self._quarantine_replica(
                        rep, why=f"step failures: {rep.last_error}"
                    )
                continue
            rep.consecutive_failures = 0
            for r in results:
                self._deliver(r, out)
            self._refresh_mirror(rep)
            self._record_class_latency(rep)
        if self._has_classes:
            self._handoff_prefill()
        self._probe_health()
        self._probe_progress()
        self._probe_rejoin()
        self._tick += 1
        if self.registry.enabled:
            self._g_healthy.set(self.healthy_replicas)
            self._g_pending.set(len(self._pending))
        if self.timeseries is not None:
            self.timeseries.tick()
        if self.retrace_sentinel is not None:
            # tick-boundary enforcement: a post-warmup compile
            # anywhere in the process fails HERE under "raise"
            self.retrace_sentinel.check()
        return out

    def cancel(self, request_id: int) -> Optional[GenerationResult]:
        """Cancel one request wherever it lives — global queue or any
        replica — returning the partial result, or None if unknown or
        already finished."""
        for rec in self._pending:
            if rec["request_id"] == request_id:
                self._pending.remove(rec)
                r = self._pending_result(rec, "cancelled")
                self._mark_done(r)
                return r
        idx = self._assigned.get(request_id)
        if idx is None:
            return None
        r = self._replicas[idx].engine.cancel(request_id)
        if r is not None:
            self._mark_done(r)
        return r

    #: consecutive zero-finish/zero-progress fleet ticks tolerated by
    #: the bounded loops (`generate`/`drain`) before diagnosing a
    #: wedged fleet — mirrors InferenceEngine._GENERATE_STALL_TICKS
    _STALL_TICKS = 1000

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
    ) -> List[GenerationResult]:
        """Batch convenience: queue every prompt, run the fleet dry,
        return results in prompt order (same contract as the
        engine's `generate`). Bounded: a long run of ticks with no
        progress raises a diagnostic instead of spinning."""
        ids = [self.add_request(p, max_new_tokens) for p in prompts]
        done: Dict[int, GenerationResult] = {}
        self._run_dry(done)
        return [done[i] for i in ids]

    def drain(self, shed_queue: bool = False) -> List[GenerationResult]:
        """Fleet shutdown: close admission, run every replica dry
        (migrating off any that fail on the way down), close each
        engine's own admission, and return the remaining results.
        ``shed_queue=True`` cancels the still-pending global queue up
        front. Idempotent."""
        already, self._draining = self._draining, True
        out: List[GenerationResult] = []
        if shed_queue:
            while self._pending:
                rec = self._pending.popleft()
                r = self._pending_result(rec, "cancelled")
                self._mark_done(r)
                out.append(r)
        done: Dict[int, GenerationResult] = {}
        self._run_dry(done)
        out.extend(done.values())
        if not already:
            for rep in self._replicas:
                if rep.in_rotation:
                    rep.engine.drain()
        return out

    def drain_replica(self, i: int) -> None:
        """Rolling restart, step 1: migrate replica ``i``'s queue and
        in-flight work to the rest of the fleet and take it out of
        rotation (state ``drained``, engine admission closed). The
        fleet keeps serving throughout — survivors' decodes never
        stall on this. `rejoin_replica(i)` is step 2."""
        rep = self._replicas[i]
        if rep.state == "drained":
            return
        recs = rep.engine.evacuate(ship_pages=rep.engine.paged)
        self._requeue(recs)
        rep.engine.drain()  # idempotent; closes the engine's admission
        rep.state = "drained"
        self._count_event("drain_replica")
        if self.tracer.enabled:
            # name every migrated request so the merged timeline can
            # group this replica-scoped event into each lifeline
            self.tracer.instant(
                "drain_replica", track="router", replica=i,
                migrated=len(recs),
                request_ids=[r["request_id"] for r in recs],
                trace_ids=[r.get("trace_id", "") for r in recs],
            )

    def rejoin_replica(self, i: int) -> None:
        """Rolling restart, step 2: `reopen()` the drained (or
        quarantined) replica — the clean-state proof lives there —
        and put it back in rotation."""
        rep = self._replicas[i]
        if rep.in_rotation:
            return
        rep.engine.reopen()
        rep.state = "up"
        rep.consecutive_failures = 0
        rep.no_progress_ticks = 0
        rep.progress_mark = rep.engine.progress_marker
        self._rejoins += 1
        self._count_event("rejoin")
        if self.tracer.enabled:
            # a rejoining replica is provably empty (reopen() checked)
            # — state what it rejoins AS rather than omitting context
            self.tracer.instant(
                "rejoin", track="router", replica=i,
                replica_class=rep.replica_class,
                after_ticks=self._tick - rep.quarantined_at,
            )

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """Fleet counters (one flat dict, the engine `stats()` shape):
        router-level lifecycle events plus per-reason finish counts
        (``finished_<reason>``; delivered shed requests count under
        ``finished_queue_full``). The fleet accounting identity —
        every submitted request is accounted exactly once:
        ``completed + undelivered-shed + pending + in_flight ==
        submitted`` at any tick boundary, and after `drain()` simply
        ``completed == submitted``."""
        out: Dict[str, float] = {
            "replicas": float(self.num_replicas),
            "healthy_replicas": float(self.healthy_replicas),
            "pending_depth": float(len(self._pending)),
            "in_flight": float(len(self._assigned)),
            "submitted": float(self._submitted),
            "completed": float(len(self._done)),
            "shed": float(self._shed),
            "migrations": float(self._migrations),
            "replica_quarantines": float(self._quarantines),
            "replica_rejoins": float(self._rejoins),
            "affinity_hits": float(self._affinity_hits),
            "adapter_affinity_hits": float(
                self._adapter_affinity_hits
            ),
            "replica_kills": float(self._kills),
            "handoffs": float(self._handoffs),
            "page_migrations": float(self._page_migrations),
        }
        if self._prefix_registry is not None:
            out["shared_prefix_chains"] = float(
                len(self._prefix_registry)
            )
        for reason, n in sorted(self._finished.items()):
            out[f"finished_{reason}"] = float(n)
        return out

    def merged_registry(self):
        """One fresh `MetricRegistry` holding the router's own series
        merged with EVERY replica's registry (``merge_from`` — counter
        and histogram-bucket adds are exact and associative), so
        fleet-level percentiles reproduce the combined per-replica
        completion streams. Build per scrape: pass this METHOD (not
        its result) to the exporter as the zero-arg registry
        provider."""
        from rocm_apex_tpu.monitor.telemetry import MetricRegistry

        merged = MetricRegistry()
        merged.merge_from(self.registry)
        for rep in self._replicas:
            if rep.engine.registry.enabled:
                merged.merge_from(rep.engine.registry)
        return merged

    def merged_trace(self, labels: Optional[List[str]] = None
                     ) -> Dict[str, Any]:
        """ONE Perfetto-loadable body for the whole fleet: the
        router's tracer plus every replica's, folded by
        `monitor.trace.merge_traces` — the router renders as process
        1, replica ``i`` as process ``i+2``, and a migrated request's
        hops line up as a single ``trace_id`` lifeline. Default
        labels: ``router``, ``replica<i>:<class>``."""
        tracers = [self.tracer] + [
            rep.engine.tracer for rep in self._replicas
        ]
        if labels is None:
            labels = ["router"] + [
                f"replica{rep.index}:{rep.replica_class}"
                for rep in self._replicas
            ]
        return merge_traces(tracers, labels)

    def export_merged_trace(self, path: str) -> int:
        """`merged_trace` to disk; returns the event count."""
        import json

        body = self.merged_trace()
        with open(path, "w") as f:
            json.dump(body, f)
        return len(body["traceEvents"])

    def arm_retrace_sentinel(self) -> None:
        """Mark the fleet's warmup boundary (no-op without a
        ``retrace_policy=``): compiles after this are retraces —
        counted, or fatal at the next tick under "raise"."""
        if self.retrace_sentinel is not None:
            self.retrace_sentinel.arm()

    def health(self) -> Dict[str, Any]:
        """Fleet liveness for `/healthz`: healthy while ANY replica
        remains in rotation — one dead replica is the fabric working,
        zero is the outage a load balancer must see as 503.
        Per-replica detail lives in `varz()`."""
        return {
            "healthy": self.healthy_replicas > 0,
            "replicas": self.num_replicas,
            "healthy_replicas": self.healthy_replicas,
            "draining": self._draining,
            "queue_depth": len(self._pending),
            "ticks": self._tick,
        }

    def varz(self) -> Dict[str, Any]:
        """Per-replica detail for `/varz`: rotation state, failure
        latches, and each engine's own health signals — plus the
        retrace sentinel's status when one is armed on the fleet."""
        out: Dict[str, Any] = {
            "router": self.stats(),
            "replica_detail": [
                {
                    "replica": rep.index,
                    "class": rep.replica_class,
                    "state": rep.state,
                    "consecutive_failures": rep.consecutive_failures,
                    "no_progress_ticks": rep.no_progress_ticks,
                    "last_error": rep.last_error,
                    "watchdog_fires": int(
                        getattr(rep.engine, "_watchdog_fires", 0)
                    ),
                    "draining": rep.engine.draining,
                    "queue_depth": rep.engine.num_queued,
                    "slots_active": rep.engine.num_active,
                    "pages_used": rep.engine.pages_used,
                }
                for rep in self._replicas
            ],
        }
        if self.retrace_sentinel is not None:
            out["retrace_sentinel"] = self.retrace_sentinel.status()
        return out

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _count_event(self, event: str) -> None:
        if self.registry.enabled:
            self._c_events.inc(event=event)

    def _run_dry(self, done: Dict[int, GenerationResult]) -> None:
        stale = 0
        mark = (len(self._done), self._progress_signature())
        while self.has_work():
            results = self.step()
            for r in results:
                done[r.request_id] = r
            work = (len(self._done), self._progress_signature())
            if results or work != mark:
                stale, mark = 0, work
                continue
            stale += 1
            if stale >= self._STALL_TICKS:
                states = {
                    rep.index: rep.state for rep in self._replicas
                }
                raise RuntimeError(
                    f"fleet stalled: {stale} consecutive ticks with "
                    f"no progress; pending={len(self._pending)} "
                    f"in_flight={len(self._assigned)} "
                    f"replicas={states}"
                )

    def _progress_signature(self):
        return tuple(
            rep.engine.progress_marker for rep in self._replicas
        )

    def _expire_pending(
        self, now: float, out: List[GenerationResult]
    ) -> None:
        """Deadline sweep over the GLOBAL queue (requests a dead fleet
        could not place still expire on time)."""
        if not self._pending:
            return
        keep: collections.deque = collections.deque()
        for rec in self._pending:
            expired = (
                (rec["queue_deadline"] is not None
                 and now > rec["queue_deadline"])
                or (rec["deadline"] is not None
                    and now > rec["deadline"])
            )
            if expired:
                r = self._pending_result(rec, "deadline")
                self._mark_done(r)
                out.append(r)
            else:
                keep.append(rec)
        self._pending = keep

    def _pending_result(
        self, rec: Dict[str, Any], reason: str
    ) -> GenerationResult:
        # a recovered request waiting in the global queue keeps the
        # tokens it already emitted — they were delivered work
        return GenerationResult(
            request_id=rec["request_id"], prompt=list(rec["prompt"]),
            tokens=list(rec["generated"]), finish_reason=reason,
        )

    def _dispatch(self, now: float) -> None:
        """Drain the global queue into the fleet: prefix-affinity
        first, least-loaded otherwise, bounded per-replica backlog."""
        while self._pending:
            candidates = [
                rep for rep in self._replicas
                if rep.in_rotation and rep.stall_ticks == 0
                and (
                    rep.engine.num_active < rep.engine.num_slots
                    or rep.engine.num_queued < self.replica_queue_depth
                )
            ]
            if not candidates:
                return
            rec = self._pending.popleft()
            rep = self._place(rec, candidates)
            rid = rec["request_id"]
            rep.engine.resume_request(
                rec["prompt"], rec["max_new_tokens"], rid,
                generated=rec["generated"],
                enqueued_at=rec["enqueued_at"],
                deadline=rec["deadline"],
                queue_deadline=rec["queue_deadline"],
                first_token_at=rec["first_token_at"],
                chunks=rec["chunks"],
                pages=rec.pop("pages", None),
                adapter_id=rec.get("adapter_id", 0),
                tenant=rec.get("tenant"),
                trace_id=rec.get("trace_id"),
            )
            self._assigned[rid] = rep.index
            self._mirror[rid] = rec
            if self.tracer.enabled:
                self.tracer.instant(
                    "dispatch", ts=now, track=f"req{rid}",
                    replica=rep.index, carried=len(rec["generated"]),
                    request_id=rid, trace_id=rec.get("trace_id"),
                )

    def _place(
        self, rec: Dict[str, Any], candidates: List[_Replica]
    ) -> _Replica:
        # replica classes: fresh prompts prefer the prefill class,
        # carried requests (recoveries, handoffs) the decode class;
        # the mixed class backstops either, and when no preferred
        # replica has room ANY candidate beats queueing — class purity
        # never costs availability
        if self._has_classes:
            preferred = "decode" if rec["generated"] else "prefill"
            classed = [
                rep for rep in candidates
                if rep.replica_class == preferred
            ] or [
                rep for rep in candidates
                if rep.replica_class == "mixed"
            ]
            if classed:
                candidates = classed
        # adapter affinity: a replica where the request's adapter is
        # already resident skips the host->device upload (and spares
        # some other tenant an eviction); narrow to those replicas
        # when any exist, then let prefix affinity / least-loaded pick
        # within them
        aid = rec.get("adapter_id", 0)
        if aid:
            resident = [
                rep for rep in candidates
                if rep.engine.adapter_pool is not None
                and rep.engine.adapter_pool.resident(aid)
            ]
            if resident:
                candidates = resident
                self._adapter_affinity_hits += 1
                self._count_event("adapter_affinity_hit")
                if self.tracer.enabled:
                    self.tracer.instant(
                        "adapter_affinity_hit",
                        track=f"req{rec['request_id']}",
                        adapter=aid,
                        request_id=rec["request_id"],
                        trace_id=rec.get("trace_id"),
                    )
        # prefix affinity: the replica already holding the longest
        # materialized prefix of this prompt skips that much prefill
        # (recovered requests carry tokens and re-prefill anyway, so
        # affinity only scores fresh prompts)
        if not rec["generated"]:
            best, best_tokens = None, 0
            if self._prefix_registry is not None:
                # one chain walk against the fleet-wide registry
                # instead of N per-engine store consults
                matched = self._prefix_registry.best(rec["prompt"])
                for rep in candidates:
                    n = matched.get(rep.index, 0)
                    if n > best_tokens:
                        best, best_tokens = rep, n
            else:
                for rep in candidates:
                    n = rep.engine.prefix_match_tokens(rec["prompt"])
                    if n > best_tokens:
                        best, best_tokens = rep, n
            if best is not None:
                self._affinity_hits += 1
                self._count_event("affinity_hit")
                if self.tracer.enabled:
                    self.tracer.instant(
                        "affinity_hit",
                        track=f"req{rec['request_id']}",
                        replica=best.index, tokens=best_tokens,
                        request_id=rec["request_id"],
                        trace_id=rec.get("trace_id"),
                    )
                return best
        # least-loaded: fewest owned requests, then fewest live pages,
        # then lowest index (deterministic tie-break)
        return min(
            candidates,
            key=lambda rep: (
                rep.engine.num_active + rep.engine.num_queued,
                rep.engine.pages_used,
                rep.index,
            ),
        )

    def _deliver(
        self, r: GenerationResult, out: List[GenerationResult]
    ) -> None:
        self._mark_done(r)
        out.append(r)

    def _mark_done(self, r: GenerationResult) -> None:
        rid = r.request_id
        if rid in self._done:
            # the no-duplicate guarantee is the recovery contract;
            # a second result for one id means migration double-owned
            # a request — refuse to deliver it silently
            raise RuntimeError(
                f"request {rid} finished twice "
                f"(second finish_reason={r.finish_reason!r})"
            )
        self._done.add(rid)
        self._finished[r.finish_reason] = (
            self._finished.get(r.finish_reason, 0) + 1
        )
        self._assigned.pop(rid, None)
        self._mirror.pop(rid, None)

    def _refresh_mirror(self, rep: _Replica) -> None:
        for rec in rep.engine.outstanding():
            mine = self._mirror.get(rec["request_id"])
            if mine is not None:
                mine["generated"] = rec["generated"]
                mine["first_token_at"] = rec["first_token_at"]
                mine["chunks"] = rec["chunks"]

    def _record_class_latency(self, rep: _Replica) -> None:
        """Fold the replica's NEW completion records into the
        class-labeled TTFT/TPOT families — the per-class attribution
        the disaggregated fleet is judged by."""
        if not self.registry.enabled:
            return
        records = rep.engine.completions
        if len(records) < rep.completions_seen:
            rep.completions_seen = 0  # engine reset_stats
        fresh = records[rep.completions_seen:]
        rep.completions_seen = len(records)
        for c in fresh:
            if c.get("new_tokens", 0) <= 0:
                continue  # shed/cancelled before any token: no latency
            self._h_class_ttft.observe(
                c["ttft_ms"], replica_class=rep.replica_class
            )
            self._h_class_tpot.observe(
                c["tpot_ms"], replica_class=rep.replica_class
            )

    def _handoff_prefill(self) -> None:
        """The disaggregation transfer: a prefill-class replica keeps
        a request only until its prompt is materialized (>= 1 token
        emitted); it is then evacuated WITH its KV pages and requeued
        — `_place` lands carried requests on the decode class, where
        the payload imports and decode continues without re-prefill.
        Skipped entirely while no decode-class replica has room: the
        request keeps decoding where it is (availability over class
        purity), and a dropped/failed page import degrades to token
        replay — token-identical either way."""
        decode_ready = any(
            rep.in_rotation and rep.replica_class == "decode"
            and rep.stall_ticks == 0
            and (
                rep.engine.num_active < rep.engine.num_slots
                or rep.engine.num_queued < self.replica_queue_depth
            )
            for rep in self._replicas
        )
        if not decode_ready:
            return
        for rep in self._replicas:
            if not rep.in_rotation or rep.replica_class != "prefill":
                continue
            for rec0 in rep.engine.outstanding():
                if not rec0["generated"]:
                    continue
                rec = rep.engine.evacuate_request(
                    rec0["request_id"], ship_pages=True
                )
                if rec is None:
                    continue
                self._handoffs += 1
                self._count_event("handoff")
                if self.tracer.enabled:
                    self.tracer.instant(
                        "handoff", track=f"req{rec['request_id']}",
                        replica=rep.index,
                        shipped="pages" in rec,
                        request_id=rec["request_id"],
                        trace_id=rec.get("trace_id"),
                    )
                self._requeue([rec])

    def _requeue(self, recs: List[Dict[str, Any]]) -> None:
        """Resubmit migration records at the HEAD of the global queue
        (preserving their order ahead of fresh arrivals)."""
        for rec in reversed(recs):
            rid = rec["request_id"]
            self._assigned.pop(rid, None)
            self._mirror.pop(rid, None)
            self._pending.appendleft(rec)
            self._migrations += 1
            self._count_event("migration")
            if "pages" in rec:
                self._page_migrations += 1
                self._count_event("page_migration")
            if self.tracer.enabled:
                self.tracer.instant(
                    "migrate", track=f"req{rid}",
                    carried=len(rec["generated"]),
                    shipped="pages" in rec,
                    request_id=rid, trace_id=rec.get("trace_id"),
                )

    def _quarantine_replica(self, rep: _Replica, why: str) -> None:
        """Failure path for a replica whose ENGINE is still intact
        (step failures, watchdog, zero progress): evacuate its exact
        request inventory — WITH its KV pages on a paged cache, so the
        destination can resume by page import instead of re-prefill —
        and put it back on the global queue."""
        recs = rep.engine.evacuate(ship_pages=rep.engine.paged)
        self._requeue(recs)
        rep.state = "quarantined"
        rep.quarantined_at = self._tick
        rep.last_error = why
        self._quarantines += 1
        self._count_event("quarantine")
        if self.tracer.enabled:
            self.tracer.instant(
                "quarantine_replica", track="router",
                replica=rep.index, why=why, migrated=len(recs),
                request_ids=[r["request_id"] for r in recs],
                trace_ids=[r.get("trace_id", "") for r in recs],
            )

    def _kill_replica(self, rep: _Replica) -> None:
        """`replica_kill`: the engine is presumed crashed — recover
        every request it held from the ROUTER's token mirror (the
        engine's own state is not trusted), then evacuate the carcass
        so its pages and slots provably free before any rejoin."""
        recs = [
            dict(self._mirror[rid], generated=list(
                self._mirror[rid]["generated"]
            ))
            for rid, idx in sorted(self._assigned.items())
            if idx == rep.index and rid in self._mirror
        ]
        rep.engine.evacuate()  # discard — recovery used the mirror
        self._requeue(recs)
        rep.state = "quarantined"
        rep.quarantined_at = self._tick
        rep.last_error = "replica_kill (chaos)"
        self._kills += 1
        self._quarantines += 1
        self._count_event("kill")
        self._count_event("quarantine")
        if self.tracer.enabled:
            self.tracer.instant(
                "kill_replica", track="router", replica=rep.index,
                recovered=len(recs),
                request_ids=[r["request_id"] for r in recs],
                trace_ids=[r.get("trace_id", "") for r in recs],
            )

    def _consult_faults(self) -> None:
        if not self.faults.enabled:
            return
        for site in ("replica_kill", "replica_stall", "replica_slow"):
            f = self.faults.fire(site, tick=self._tick)
            if f is None:
                continue
            payload = dict(f.payload or {})
            idx = int(payload.get("replica", 0)) % self.num_replicas
            self.fault_log.append((site, self._tick, idx))
            rep = self._replicas[idx]
            if site == "replica_kill":
                if rep.in_rotation:
                    self._kill_replica(rep)
            elif site == "replica_stall":
                rep.stall_ticks += int(payload.get("ticks", 3))
                self._count_event("stall")
            else:  # replica_slow
                rep.slow_ticks += int(payload.get("ticks", 1))
                rep.slow_seconds = float(
                    payload.get("seconds", 0.01)
                )
                self._count_event("slow")

    def _probe_health(self) -> None:
        """The `engine_health` probe, inlined: any watchdog fire on an
        in-rotation replica quarantines it this tick."""
        for rep in self._replicas:
            if not rep.in_rotation:
                continue
            if int(getattr(rep.engine, "_watchdog_fires", 0)) > 0:
                self._quarantine_replica(rep, why="watchdog fired")

    def _probe_progress(self) -> None:
        """Zero-progress detector: a replica that OWNS work but moved
        no tokens for `stall_grace` consecutive ticks is wedged
        (injected stall, deadlocked pool, hung host thread) —
        quarantine and migrate."""
        for rep in self._replicas:
            if not rep.in_rotation:
                continue
            if not rep.engine.has_work():
                rep.no_progress_ticks = 0
                rep.progress_mark = rep.engine.progress_marker
                continue
            mark = rep.engine.progress_marker
            if mark != rep.progress_mark:
                rep.no_progress_ticks = 0
                rep.progress_mark = mark
                continue
            rep.no_progress_ticks += 1
            if rep.no_progress_ticks >= self.stall_grace:
                self._quarantine_replica(rep, why="zero progress")

    def _probe_rejoin(self) -> None:
        """Quarantined replicas are probed back: after `rejoin_after`
        ticks (and any injected stall has lapsed), `reopen()` proves
        the clean state and the replica rejoins rotation; a failed
        probe leaves it quarantined for the next round."""
        for rep in self._replicas:
            if rep.state != "quarantined":
                continue
            if rep.stall_ticks > 0:
                rep.stall_ticks -= 1
                continue
            if self._tick - rep.quarantined_at < self.rejoin_after:
                continue
            try:
                self.rejoin_replica(rep.index)
            except RuntimeError as exc:
                rep.last_error = f"rejoin probe failed: {exc}"
