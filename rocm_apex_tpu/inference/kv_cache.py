"""Preallocated slot-paged KV cache for autoregressive decoding.

The serving-side analogue of the training stack's packed buffers: all
device memory the decoder will ever touch is allocated ONCE, up front,
as per-layer ``(num_slots, capacity, heads, head_dim)`` key/value
buffers plus one ``(num_slots,)`` int32 length vector. A "slot" is a
fixed batch lane the continuous-batching engine (engine.py) leases to
one in-flight request at a time; eviction is just the length
bookkeeping forgetting the slot — the stale keys beyond a new
request's live prefix are never attended (the decode kernel bounds
every row at ``lengths``) and are overwritten position by position as
the new sequence grows.

Writes are per-slot `lax.dynamic_update_slice` at each slot's current
length — under jit with donated buffers XLA performs them in place, so
a decode step's cache traffic is O(layers · heads · head_dim) writes
plus the attention reads, never a copy of the cache itself. bf16 is
the default cache dtype (the O4/O5 story: matmul operands in bf16,
fp32 only where accumulation demands it).

The model layer (models/gpt.py) deliberately does NOT import this
class: it consumes any pytree with ``.k``/``.v``/``.lengths`` and a
``.replace`` method, so the dependency points inference → models only.
"""

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

__all__ = ["KVCache"]


@struct.dataclass
class KVCache:
    """Per-layer K/V buffers + per-slot lengths; a jit-friendly pytree.

    ``k``/``v``: tuples (one entry per transformer layer) of
    ``(num_slots, capacity, heads_local, head_dim)`` arrays.
    ``lengths``: ``(num_slots,)`` int32 — tokens currently materialized
    in each slot; also the write offset for the next token and the
    attention bound (the decode path attends keys
    ``[0, lengths + t)`` after writing ``t`` new tokens).
    """

    k: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]
    lengths: jnp.ndarray

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @classmethod
    def create(
        cls,
        num_layers: int,
        num_slots: int,
        capacity: int,
        num_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
    ) -> "KVCache":
        shape = (num_slots, capacity, num_heads, head_dim)
        return cls(
            k=tuple(jnp.zeros(shape, dtype) for _ in range(num_layers)),
            v=tuple(jnp.zeros(shape, dtype) for _ in range(num_layers)),
            lengths=jnp.zeros((num_slots,), jnp.int32),
        )

    @classmethod
    def for_model(
        cls,
        cfg,
        num_slots: int,
        capacity: Optional[int] = None,
        dtype: Any = None,
    ) -> "KVCache":
        """Cache sized for a `GPTConfig`-shaped config (duck-typed:
        num_layers / num_attention_heads / head_dim /
        max_position_embeddings / tensor_parallel_size / dtype). Heads
        are the LOCAL (per-TP-rank) count, matching what
        `ParallelAttention` writes."""
        tp = cfg.tensor_parallel_size or 1
        return cls.create(
            cfg.num_layers,
            num_slots,
            capacity or cfg.max_position_embeddings,
            cfg.num_attention_heads // tp,
            cfg.head_dim,
            dtype if dtype is not None else cfg.dtype,
        )

    # ------------------------------------------------------------------
    # shape facts
    # ------------------------------------------------------------------

    @property
    def num_layers(self) -> int:
        return len(self.k)

    @property
    def num_slots(self) -> int:
        return self.k[0].shape[0]

    @property
    def capacity(self) -> int:
        return self.k[0].shape[1]

    # ------------------------------------------------------------------
    # functional updates (all jit-safe)
    # ------------------------------------------------------------------

    def write(self, layer: int, k_new: jnp.ndarray, v_new: jnp.ndarray
              ) -> "KVCache":
        """Write ``(num_slots, t, heads, head_dim)`` new keys/values
        into ``layer`` at each slot's current length. Does NOT advance
        ``lengths`` — one model forward writes every layer at the same
        offsets, then advances once (`advance`)."""

        def _row(buf, new, start):
            return jax.lax.dynamic_update_slice(
                buf, new.astype(buf.dtype), (start, 0, 0)
            )

        k = list(self.k)
        v = list(self.v)
        k[layer] = jax.vmap(_row)(self.k[layer], k_new, self.lengths)
        v[layer] = jax.vmap(_row)(self.v[layer], v_new, self.lengths)
        return self.replace(k=tuple(k), v=tuple(v))

    def write_at(
        self,
        layer: int,
        slots: jnp.ndarray,
        positions: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
    ) -> "KVCache":
        """Scatter a PACKED token chunk into ``layer`` at explicit
        per-token ``(slot, position)`` destinations — the chunked-
        prefill write: one tick's budget of prompt tokens lands at each
        slot's prefill cursor in one scatter, no per-request dispatch.

        ``slots``/``positions``: (budget,) int32; ``k_new``/``v_new``:
        (budget, heads, head_dim). Padding tokens carry an
        out-of-range slot id (>= num_slots) and are DROPPED by the
        scatter (``mode="drop"``), so a partially filled chunk never
        touches live rows. Does not advance ``lengths`` — the engine
        commits cursors once per tick.

        The drop semantics double as speculative decoding's deferred
        commit: draft rows ride the chunk with the pad sentinel (so
        the in-trace scatter skips them), and the engine replays the
        SAME ``write_at`` post-verification with only the accepted
        rows' real slot ids — rollback is "never written", not
        "undone"."""
        k = list(self.k)
        v = list(self.v)
        k[layer] = self.k[layer].at[slots, positions].set(
            k_new.astype(self.k[layer].dtype), mode="drop"
        )
        v[layer] = self.v[layer].at[slots, positions].set(
            v_new.astype(self.v[layer].dtype), mode="drop"
        )
        return self.replace(k=tuple(k), v=tuple(v))

    def advance(self, t: int, active: Optional[jnp.ndarray] = None
                ) -> "KVCache":
        """Advance lengths by ``t``, clamped to capacity. The clamp
        exists ONLY to keep stale/idle slots from drifting out of
        bounds — it is not a liveness mechanism: the engine evicts a
        sequence before its length hits capacity
        (``finish_reason='capacity'``), suppresses the fused decode of
        a prompt that exactly fills capacity, and RAISES a host-side
        error (with the slot id) if a live slot ever reaches the clamp
        (`InferenceEngine._guard_capacity`) — a silently wedged length
        would re-attend a stale last row forever. ``active`` masks
        which slots advance."""
        new = jnp.minimum(self.lengths + t, self.capacity)
        if active is not None:
            new = jnp.where(active, new, self.lengths)
        return self.replace(lengths=new)

    def reset_slot(self, slot) -> "KVCache":
        """Free a slot: forget its length. The stale K/V stay in HBM
        but are unreachable (every read is bounded by lengths) and get
        overwritten as the next leaseholder grows."""
        return self.replace(
            lengths=jax.lax.dynamic_update_slice(
                self.lengths, jnp.zeros((1,), jnp.int32), (slot,)
            )
        )

    def slot_view(self, slot) -> "KVCache":
        """A single-slot (num_slots == 1) view — the prefill unit. The
        engine runs one request's prompt through the model against
        this view, then scatters it back with `write_back`; ``slot``
        may be a traced int32 (slot choice does not retrace)."""
        return KVCache(
            k=tuple(
                jax.lax.dynamic_slice_in_dim(b, slot, 1, 0) for b in self.k
            ),
            v=tuple(
                jax.lax.dynamic_slice_in_dim(b, slot, 1, 0) for b in self.v
            ),
            lengths=jax.lax.dynamic_slice_in_dim(self.lengths, slot, 1, 0),
        )

    def write_back(self, slot, sub: "KVCache") -> "KVCache":
        """Scatter a `slot_view` result back into the full cache."""
        return KVCache(
            k=tuple(
                jax.lax.dynamic_update_slice_in_dim(b, s, slot, 0)
                for b, s in zip(self.k, sub.k)
            ),
            v=tuple(
                jax.lax.dynamic_update_slice_in_dim(b, s, slot, 0)
                for b, s in zip(self.v, sub.v)
            ),
            lengths=jax.lax.dynamic_update_slice_in_dim(
                self.lengths, sub.lengths, slot, 0
            ),
        )
