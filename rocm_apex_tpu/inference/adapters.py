"""Paged multi-LoRA adapter pool: thousands of registered tenants,
a fixed-shape device residency window.

`ops/lora.py` made adapter ids DATA — the one mixed trace contracts
per-token factors gathered from packed ``(L, P, h, r)`` / ``(L, P, r,
o)`` device buffers, so the program never depends on WHICH adapters
are resident, only on the pool geometry. That leaves exactly one
problem: thousands of registered fine-tunes cannot all live in HBM,
and this module solves it with the machinery the KV cache already
proved out — buffer slots are pages of a `PageAllocator`:

* ref-counts — one ref per in-flight request using the adapter
  (admission `acquire`s, every teardown path `release`s once);
* LRU park — an idle tenant's slot keeps its bytes (`decref(park=
  True)`), so the next request from that tenant revives it for free
  (`ref`) with NO re-upload and NO retrace;
* reclaim on pressure — a fresh tenant's `alloc` evicts the
  least-recently-parked slot (`on_evict` unmaps it here); when every
  slot is pinned by in-flight work `acquire` returns None and the
  engine backpressures at admission — token-level, never a deadlock,
  because finishing requests always release refs.

Slot 0 is the base model: allocated at construction (the allocator's
free list is ``deque(range(n))``, so the first ``alloc(1)`` is
deterministically ``[0]``), zero-filled forever, its ref never
dropped. ``adapter_id == 0`` therefore means "no adapter" end to end
— the gather reads zeros and `apply_lora`'s skip branch never fires a
FLOP on pure-base batches.

Host side, the registry keyed by tenant keeps rank-padded fp32 copies
(`ops.lora.pad_rank` folds alpha/rank into B at registration — exact,
since padding rank columns with zeros adds ``x @ 0``), plus the
admission `tier` each tenant bought. The device buffers themselves
are a plain pytree the engine donates through its jits and re-binds
each tick (`buffers` is assignable for exactly that reason).
"""

import collections
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from rocm_apex_tpu.inference.paging import PageAllocator
from rocm_apex_tpu.ops.lora import pad_rank

__all__ = ["AdapterPool", "BASE_ADAPTER_ID"]

# adapter_id 0 = base model everywhere: requests default to it,
# buffer slot 0 holds zeros, acquire/release are free no-ops.
BASE_ADAPTER_ID = 0

# projection targets carrying deltas, in model order. "qkv" hooks the
# fused query_key_value projection (h -> 3h), "dense" the attention
# output projection (h -> h).
TARGETS = ("qkv", "dense")


class AdapterPool:
    """Fixed-shape paged device buffers + host registry for LoRA
    adapters (see module docstring for the residency protocol)."""

    def __init__(
        self,
        num_layers: int,
        hidden: int,
        *,
        max_resident: int = 8,
        max_rank: int = 8,
        qkv_out: Optional[int] = None,
    ):
        if num_layers < 1 or hidden < 1:
            raise ValueError(
                f"bad pool geometry: layers={num_layers} hidden={hidden}"
            )
        if max_resident < 2:
            # slot 0 is the base; a pool that can hold zero actual
            # adapters admits nothing and deadlocks admission.
            raise ValueError(
                f"max_resident must be >= 2 (slot 0 is the base), "
                f"got {max_resident}"
            )
        if max_rank < 1:
            raise ValueError(f"max_rank must be >= 1, got {max_rank}")
        self.num_layers = int(num_layers)
        self.hidden = int(hidden)
        self.max_resident = int(max_resident)
        self.max_rank = int(max_rank)
        self.out_dims = {
            "qkv": int(qkv_out) if qkv_out is not None else 3 * hidden,
            "dense": int(hidden),
        }

        L, P, h, r = num_layers, max_resident, hidden, max_rank
        self._buffers: Dict[str, Tuple[Any, Any]] = {
            t: (
                jnp.zeros((L, P, h, r), jnp.float32),
                jnp.zeros((L, P, r, self.out_dims[t]), jnp.float32),
            )
            for t in TARGETS
        }

        self._alloc = PageAllocator(max_resident)
        self._alloc.on_evict = self._on_evict
        base = self._alloc.alloc(1)
        assert base == [0], f"base slot must be 0, allocator gave {base}"

        # host registry: adapter_id -> padded fp32 factors / metadata
        self._host: Dict[int, Dict[str, Tuple[np.ndarray, np.ndarray]]] = {}
        self._tenant: Dict[int, str] = {BASE_ADAPTER_ID: "base"}
        self._tier: Dict[int, int] = {BASE_ADAPTER_ID: 0}
        self._rank: Dict[int, int] = {BASE_ADAPTER_ID: 0}
        self._by_tenant: Dict[str, int] = {}
        self._slot_of: Dict[int, int] = {BASE_ADAPTER_ID: 0}
        self._aid_at: Dict[int, int] = {0: BASE_ADAPTER_ID}
        self._next_id = 1
        # observability: park/reclaim economics for tests + stats()
        self.uploads = 0
        self.evictions = 0
        self.revivals = 0

    # ------------------------------------------------------------- #
    # device buffers (the engine donates these through its jits and
    # re-binds the aliased outputs every tick)
    # ------------------------------------------------------------- #

    @property
    def buffers(self) -> Dict[str, Tuple[Any, Any]]:
        """{"qkv": (A, B), "dense": (A, B)} device pytree; A is
        (L, P, h, r), B is (L, P, r, out)."""
        return self._buffers

    @buffers.setter
    def buffers(self, value: Dict[str, Tuple[Any, Any]]) -> None:
        if set(value) != set(TARGETS):
            raise ValueError(f"buffer pytree keys {set(value)}")
        self._buffers = {t: (value[t][0], value[t][1]) for t in TARGETS}

    # ------------------------------------------------------------- #
    # registry
    # ------------------------------------------------------------- #

    def register(
        self,
        tenant: str,
        weights: List[Dict[str, Tuple[Any, Any]]],
        *,
        rank: int,
        alpha: Optional[float] = None,
        tier: int = 0,
    ) -> int:
        """Register a tenant's adapter; returns its adapter_id (>= 1).

        ``weights`` is one dict per layer, each mapping a target in
        ``TARGETS`` to its ``(A: (h, r), B: (r, out))`` factors; a
        target missing from a layer's dict contributes no delta there
        (zeros). Factors are rank-padded and alpha-scaled here, once
        — registration is the cold path."""
        if not tenant or tenant == "base":
            raise ValueError(f"bad tenant name {tenant!r}")
        if tenant in self._by_tenant:
            raise ValueError(f"tenant {tenant!r} already registered")
        if len(weights) != self.num_layers:
            raise ValueError(
                f"expected {self.num_layers} per-layer weight dicts, "
                f"got {len(weights)}"
            )
        packed: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for t in TARGETS:
            o = self.out_dims[t]
            a_l = np.zeros(
                (self.num_layers, self.hidden, self.max_rank), np.float32
            )
            b_l = np.zeros((self.num_layers, self.max_rank, o), np.float32)
            for li, layer in enumerate(weights):
                if t not in layer:
                    continue
                a, b = layer[t]
                if np.asarray(a).shape != (self.hidden, rank):
                    raise ValueError(
                        f"layer {li} {t} A shape "
                        f"{np.asarray(a).shape} != ({self.hidden}, {rank})"
                    )
                if np.asarray(b).shape != (rank, o):
                    raise ValueError(
                        f"layer {li} {t} B shape "
                        f"{np.asarray(b).shape} != ({rank}, {o})"
                    )
                a_l[li], b_l[li] = pad_rank(a, b, self.max_rank, alpha)
            packed[t] = (a_l, b_l)
        aid = self._next_id
        self._next_id += 1
        self._host[aid] = packed
        self._tenant[aid] = tenant
        self._tier[aid] = int(tier)
        self._rank[aid] = int(rank)
        self._by_tenant[tenant] = aid
        return aid

    def lookup(self, tenant: str) -> Optional[int]:
        return self._by_tenant.get(tenant)

    def tenant_of(self, adapter_id: int) -> str:
        return self._tenant[adapter_id]

    def tier_of(self, adapter_id: int) -> int:
        return self._tier[adapter_id]

    def rank_of(self, adapter_id: int) -> int:
        return self._rank[adapter_id]

    def known(self, adapter_id: int) -> bool:
        return adapter_id == BASE_ADAPTER_ID or adapter_id in self._host

    @property
    def num_registered(self) -> int:
        """Registered adapters, base excluded."""
        return len(self._host)

    # ------------------------------------------------------------- #
    # residency
    # ------------------------------------------------------------- #

    def resident(self, adapter_id: int) -> bool:
        return adapter_id in self._slot_of

    def slot_of(self, adapter_id: int) -> Optional[int]:
        return self._slot_of.get(adapter_id)

    def acquire(self, adapter_id: int) -> Optional[int]:
        """One admission ref on the adapter; returns its buffer slot,
        or None when every slot is pinned (token-level backpressure —
        the caller skips this request and retries next tick). Never
        raises on pressure, only on unknown ids."""
        if adapter_id == BASE_ADAPTER_ID:
            return 0
        if adapter_id not in self._host:
            raise KeyError(f"unknown adapter_id {adapter_id}")
        slot = self._slot_of.get(adapter_id)
        if slot is not None:
            if self._alloc.refcount(slot) == 0:
                self.revivals += 1  # parked -> live, bytes reused
            self._alloc.ref(slot)
            return slot
        got = self._alloc.alloc(1)
        if got is None:
            return None
        slot = got[0]
        self._upload(adapter_id, slot)
        self._slot_of[adapter_id] = slot
        self._aid_at[slot] = adapter_id
        return slot

    def release(self, adapter_id: int) -> None:
        """Drop one admission ref. The slot PARKS at refcount zero —
        bytes stay resident for revival until allocation pressure
        reclaims the LRU slot."""
        if adapter_id == BASE_ADAPTER_ID:
            return
        slot = self._slot_of.get(adapter_id)
        if slot is None:
            raise RuntimeError(
                f"release of non-resident adapter {adapter_id} "
                f"(double release?)"
            )
        self._alloc.decref(slot, park=True)

    def refs(self, adapter_id: int) -> int:
        slot = self._slot_of.get(adapter_id)
        return 0 if slot is None else self._alloc.refcount(slot)

    def _on_evict(self, slot: int) -> None:
        aid = self._aid_at.pop(slot)
        del self._slot_of[aid]
        self.evictions += 1

    def _upload(self, adapter_id: int, slot: int) -> None:
        packed = self._host[adapter_id]
        for t in TARGETS:
            A, B = self._buffers[t]
            a_h, b_h = packed[t]
            self._buffers[t] = (
                A.at[:, slot].set(jnp.asarray(a_h)),
                B.at[:, slot].set(jnp.asarray(b_h)),
            )
        self.uploads += 1

    # ------------------------------------------------------------- #
    # invariants / observability
    # ------------------------------------------------------------- #

    def snapshot(self) -> Dict[str, int]:
        """Counters for leak checks — after every in-flight request
        has finished, ``refs`` must be exactly 1 (the base slot's
        permanent self-ref)."""
        s = self._alloc.snapshot()
        s.update(
            resident=len(self._slot_of) - 1,  # base excluded
            registered=self.num_registered,
            uploads=self.uploads,
            evictions=self.evictions,
            revivals=self.revivals,
        )
        return s

    def assert_consistent(self) -> None:
        """Allocator partition invariants plus the residency-map
        bijection; run by tests after every teardown path."""
        self._alloc.assert_consistent()
        assert self._slot_of.get(BASE_ADAPTER_ID) == 0, "base slot moved"
        assert self._alloc.refcount(0) >= 1, "base slot ref dropped"
        for aid, slot in self._slot_of.items():
            assert self._aid_at.get(slot) == aid, (
                f"slot map corrupt: adapter {aid} -> slot {slot} -> "
                f"adapter {self._aid_at.get(slot)}"
            )
        for slot, aid in self._aid_at.items():
            assert self._slot_of.get(aid) == slot, (
                f"slot map corrupt: slot {slot} -> adapter {aid} -> "
                f"slot {self._slot_of.get(aid)}"
            )
