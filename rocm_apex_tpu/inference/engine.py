"""Continuous-batching generation engine over the KV-cached GPT.

The serving loop the ROADMAP's "heavy traffic" story needs: a fixed
grid of batch slots (the preallocated `KVCache`), a host-side request
queue, and per-step admit/evict — a finished sequence frees its slot
at the end of a step and a queued request claims it at the start of
the next, so the compiled decode program never changes shape while the
set of in-flight requests churns (the continuous-batching design of
modern LLM servers, compiled-program-friendly).

Two compiled programs serve everything:

* ``prefill``: one request's padded prompt through the model against a
  single-slot cache view, scattered back into the full cache, first
  token sampled from the last REAL prompt position. Traced once (the
  prompt pad width is fixed at construction).
* ``decode_step``: ONE token for EVERY slot — active or not — in a
  single jit program with the cache buffers donated, so the per-token
  cost is one program dispatch and in-place cache writes, no per-token
  Python dispatch into XLA and no cache copies. Traced once; the
  engine exposes ``decode_trace_count`` so tests pin that invariant.

Inactive slots ride along as dead rows (their sampled tokens are
discarded and their lengths pinned) — uniform shapes beat ragged
dispatch, the same padded-slot trade the training stack's pipeline
microbatching makes.

Determinism: one engine-owned PRNG key, split once per compiled call;
a fixed seed replays the exact token stream for the same arrival
order regardless of wall-clock timing.
"""

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu import profiler
from rocm_apex_tpu.inference.kv_cache import KVCache
from rocm_apex_tpu.inference.sampling import sample
from rocm_apex_tpu.ops._pallas import on_tpu

__all__ = [
    "SamplingParams",
    "Request",
    "GenerationResult",
    "InferenceEngine",
]


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Static sampling config — fixed per engine (it is baked into the
    compiled decode program). ``temperature=0`` is greedy."""

    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None


@dataclasses.dataclass
class Request:
    request_id: int
    prompt: List[int]
    max_new_tokens: int


@dataclasses.dataclass
class GenerationResult:
    request_id: int
    prompt: List[int]
    tokens: List[int]  # generated ids (includes the eos when hit)
    finish_reason: str  # "eos" | "length" | "capacity"


@dataclasses.dataclass
class _Slot:
    """Host-side bookkeeping for one leased cache slot."""

    req: Request
    generated: List[int]
    pos: int  # tokens materialized in the cache for this slot


class InferenceEngine:
    """Continuous-batching serving loop for a `GPTModel`.

    ``model``/``params`` are the trained flax module and its variables
    (the same pytree `GPTModel.init` returns — serving reuses the
    training checkpoint directly). The cache dtype defaults to the
    model's compute dtype (bf16 under the O4/O5 recipe).

    Single-chip (tp=1) in this PR; the cache layout already stores
    LOCAL head shards, so multi-chip sharded serving is a cache-
    compatible follow-up.
    """

    def __init__(
        self,
        model,
        params,
        *,
        num_slots: int = 8,
        max_prompt_len: Optional[int] = None,
        capacity: Optional[int] = None,
        eos_id: Optional[int] = None,
        sampling: Optional[SamplingParams] = None,
        seed: int = 0,
        cache_dtype: Any = None,
    ):
        cfg = model.cfg
        if (cfg.tensor_parallel_size or 1) > 1:
            raise NotImplementedError(
                "multi-chip serving (tp > 1) is a future PR; build the "
                "engine with tensor_parallel_size=1"
            )
        self.model = model
        self.params = params
        self.capacity = int(capacity or cfg.max_position_embeddings)
        if self.capacity > cfg.max_position_embeddings:
            raise ValueError(
                f"capacity {self.capacity} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}"
            )
        self.max_prompt_len = int(max_prompt_len or self.capacity)
        if not 0 < self.max_prompt_len <= self.capacity:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} must be in "
                f"(0, capacity={self.capacity}]"
            )
        self.eos_id = eos_id
        self.sampling = sampling or SamplingParams()
        self.cache = KVCache.for_model(
            cfg, num_slots, self.capacity, dtype=cache_dtype
        )
        self._rng = jax.random.PRNGKey(seed)
        self._queue: collections.deque = collections.deque()
        self._slots: List[Optional[_Slot]] = [None] * num_slots
        self._next_id = 0
        self._prefill_traces = 0
        self._decode_traces = 0
        # serving telemetry (read via `stats()`, fed to a
        # monitor.MetricsLogger): monotonic counters + wall-time sums.
        # Latencies include the result fetch — on the tunnel platform
        # that fetch IS the device sync (the Timers rule), so these are
        # true end-to-end numbers, not dispatch times.
        self._admitted = 0
        self._evicted = 0
        self._prompt_tokens = 0
        self._generated_tokens = 0
        self._prefill_seconds = 0.0
        self._decode_seconds = 0.0
        self._decode_steps = 0

        sp = self.sampling

        def _sample(rng, logits):
            return sample(
                rng,
                logits,
                temperature=sp.temperature,
                top_k=sp.top_k,
                top_p=sp.top_p,
            )

        def _prefill(params, cache, tokens, slot, length, rng):
            # trace-time side effect: counts COMPILES, not calls
            self._prefill_traces += 1
            sub = cache.slot_view(slot)
            sub = sub.replace(lengths=jnp.zeros((1,), jnp.int32))
            logits, sub = model.apply(params, tokens, cache=sub)
            # the model advanced by the PADDED width; the live prefix
            # is the real prompt — decode overwrites the pad positions
            # one by one and never attends past `lengths`
            sub = sub.replace(
                lengths=jnp.reshape(length, (1,)).astype(jnp.int32)
            )
            cache = cache.write_back(slot, sub)
            last = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, 0, keepdims=False
            )
            first_tok = _sample(rng, last[None, :])[0]
            return first_tok, cache

        def _decode(params, cache, tokens, active, rng):
            self._decode_traces += 1
            logits, new_cache = model.apply(
                params, tokens[:, None], cache=cache
            )
            # pin inactive slots' lengths (their dead-row writes land
            # in junk the next prefill overwrites, but unbounded drift
            # would saturate the clamp)
            new_cache = new_cache.replace(
                lengths=jnp.where(
                    active, new_cache.lengths, cache.lengths
                )
            )
            tok = _sample(rng, logits[:, -1, :])
            return jnp.where(active, tok, 0), new_cache

        # cache buffers are DONATED: the step updates them in place on
        # TPU. CPU (the test platform) cannot donate and would warn on
        # every call, so donation is gated on the backend.
        donate = (1,) if on_tpu() else ()
        self._prefill_jit = jax.jit(_prefill, donate_argnums=donate)
        self._decode_jit = jax.jit(_decode, donate_argnums=donate)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @property
    def num_slots(self) -> int:
        return len(self._slots)

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    @property
    def prefill_trace_count(self) -> int:
        return self._prefill_traces

    @property
    def decode_trace_count(self) -> int:
        return self._decode_traces

    def has_work(self) -> bool:
        return bool(self._queue) or self.num_active > 0

    def stats(self) -> Dict[str, float]:
        """Serving telemetry as one flat name→scalar dict — the
        `monitor.MetricsLogger.log_step` input format (route the
        monotonic counters through its ``last_value`` set).

        Gauges: ``queue_depth``, ``slots_active``, ``slot_occupancy``.
        Counters: ``admitted``, ``evicted``, ``prompt_tokens``,
        ``generated_tokens``, ``decode_steps``. Derived: mean
        prefill/decode latency (ms, sync-inclusive — see __init__) and
        tokens/sec over each phase's accumulated wall time
        (prefill = prompt tokens absorbed, decode = tokens emitted)."""
        prefill_ms = (
            1e3 * self._prefill_seconds / self._admitted
            if self._admitted else 0.0
        )
        decode_ms = (
            1e3 * self._decode_seconds / self._decode_steps
            if self._decode_steps else 0.0
        )
        decode_generated = self._generated_tokens - self._admitted
        return {
            "queue_depth": float(self.num_queued),
            "slots_active": float(self.num_active),
            "slot_occupancy": self.num_active / self.num_slots,
            "admitted": float(self._admitted),
            "evicted": float(self._evicted),
            "prompt_tokens": float(self._prompt_tokens),
            "generated_tokens": float(self._generated_tokens),
            "decode_steps": float(self._decode_steps),
            "prefill_ms_avg": prefill_ms,
            "decode_ms_avg": decode_ms,
            "prefill_tokens_per_sec": (
                self._prompt_tokens / self._prefill_seconds
                if self._prefill_seconds > 0 else 0.0
            ),
            "decode_tokens_per_sec": (
                decode_generated / self._decode_seconds
                if self._decode_seconds > 0 else 0.0
            ),
        }

    def add_request(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        request_id: Optional[int] = None,
    ) -> int:
        """Queue a prompt; returns the request id. The request is
        admitted into a cache slot (prefilled) by a later `step` when
        a slot is free."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds max_prompt_len "
                f"{self.max_prompt_len} (chunked prefill is a future PR)"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request_id is None:
            request_id = self._next_id
        self._next_id = max(self._next_id, request_id) + 1
        self._queue.append(Request(request_id, prompt, max_new_tokens))
        return request_id

    def step(self) -> List[GenerationResult]:
        """One engine tick: admit queued requests into free slots
        (one compiled prefill each), then ONE compiled decode step for
        the whole slot grid. Returns the requests that finished this
        tick (their slots are already free for the next)."""
        finished: List[GenerationResult] = []

        # ---- admit ----------------------------------------------------
        for slot in range(self.num_slots):
            if self._slots[slot] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            toks = np.zeros((1, self.max_prompt_len), np.int32)
            toks[0, : len(req.prompt)] = req.prompt
            self._rng, rng = jax.random.split(self._rng)
            t0 = time.perf_counter()
            with profiler.annotate(
                "inference/prefill", slot=slot, prompt_len=len(req.prompt)
            ):
                tok, self.cache = self._prefill_jit(
                    self.params, self.cache, jnp.asarray(toks),
                    slot, len(req.prompt), rng,
                )
            first_tok = int(tok)  # value fetch = device sync
            self._prefill_seconds += time.perf_counter() - t0
            self._admitted += 1
            self._prompt_tokens += len(req.prompt)
            self._generated_tokens += 1
            state = _Slot(
                req=req, generated=[first_tok], pos=len(req.prompt)
            )
            done = self._finish_reason(state)
            if done is not None:
                finished.append(self._evict(slot, state, done))
            else:
                self._slots[slot] = state

        # ---- decode ---------------------------------------------------
        active = np.array(
            [s is not None for s in self._slots], dtype=bool
        )
        if active.any():
            tokens = np.array(
                [s.generated[-1] if s is not None else 0
                 for s in self._slots],
                np.int32,
            )
            self._rng, rng = jax.random.split(self._rng)
            t0 = time.perf_counter()
            with profiler.annotate(
                "inference/decode", batch=int(active.sum())
            ):
                tok, self.cache = self._decode_jit(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(active), rng,
                )
            toks = np.asarray(tok)  # value fetch = device sync
            self._decode_seconds += time.perf_counter() - t0
            self._decode_steps += 1
            self._generated_tokens += int(active.sum())
            for slot, state in enumerate(self._slots):
                if state is None:
                    continue
                state.pos += 1  # the input token was written this step
                state.generated.append(int(toks[slot]))
                done = self._finish_reason(state)
                if done is not None:
                    finished.append(self._evict(slot, state, done))
        return finished

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
    ) -> List[GenerationResult]:
        """Convenience batch API: queue every prompt, run the serving
        loop dry, return results in prompt order."""
        ids = [self.add_request(p, max_new_tokens) for p in prompts]
        done = {}
        while self.has_work():
            for r in self.step():
                done[r.request_id] = r
        return [done[i] for i in ids]

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _finish_reason(self, state: _Slot) -> Optional[str]:
        if (
            self.eos_id is not None
            and state.generated[-1] == self.eos_id
        ):
            return "eos"
        if len(state.generated) >= state.req.max_new_tokens:
            return "length"
        if state.pos >= self.capacity:
            # the next decode would need cache position `pos`; the
            # slot is full — forced eviction, never a clamped write
            return "capacity"
        return None

    def _evict(
        self, slot: int, state: _Slot, reason: str
    ) -> GenerationResult:
        self._slots[slot] = None
        self._evicted += 1
        return GenerationResult(
            request_id=state.req.request_id,
            prompt=list(state.req.prompt),
            tokens=list(state.generated),
            finish_reason=reason,
        )
