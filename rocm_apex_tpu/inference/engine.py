"""Continuous-batching generation engine over the KV-cached GPT.

The serving loop the ROADMAP's "heavy traffic" story needs: a fixed
grid of batch slots (the preallocated `KVCache`), a host-side request
queue, and per-step admit/evict — a finished sequence frees its slot
at the end of a step and a queued request claims it at the start of
the next, so the compiled programs never change shape while the set of
in-flight requests churns (the continuous-batching design of modern
LLM servers, compiled-program-friendly).

Prefill is CHUNKED by default (the Sarathi-Serve / Orca design point,
arXiv:2403.02310): each tick the scheduler packs up to
``prefill_token_budget`` pending prompt tokens — pieces of one or more
queued or partially-prefilled requests, tracked by a per-slot prefill
cursor — into one fixed-shape ``(budget,)`` buffer with per-token slot
ids and positions, and runs ONE compiled **mixed step** that

* attends the packed chunk against each slot's existing cache prefix
  plus intra-chunk causality (models/gpt.py chunk path: the packed
  varlen segments kernel merged with the chunk-width cache read),
* scatters the chunk's K/V into the cache at per-slot offsets
  (`KVCache.write_at` semantics), and
* advances the WHOLE decode grid in the same program,

so decodes never wait on a prefill (no head-of-line blocking), prompts
of ANY length stream through in budget-sized pieces (there is no
admit-time prompt-length ceiling — only the physical cache capacity),
and no padded ``(1, max_prompt_len, …)`` activation ever materializes.
Ticks with no pending prompt tokens take a decode-only fast path (the
same compiled decode program every tick). Fixed shapes mean exactly
ONE mixed-step trace for a whole serving run regardless of the prompt
mix — ``mixed_trace_count`` pins that invariant in tests.

``prefill_token_budget=None`` restores the legacy whole-prompt path
(one padded compiled prefill per request) as the A/B baseline the
serving bench measures against.

Inactive slots ride along as dead rows (their sampled tokens are
discarded and their lengths pinned) — uniform shapes beat ragged
dispatch, the same padded-slot trade the training stack's pipeline
microbatching makes.

Determinism: one engine-owned PRNG key, split once per compiled call;
a fixed seed replays the exact token stream for the same arrival
order regardless of wall-clock timing.
"""

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rocm_apex_tpu import profiler
from rocm_apex_tpu.inference.faults import NO_FAULTS, FaultInjected
from rocm_apex_tpu.inference.kv_cache import KVCache
from rocm_apex_tpu.inference.paging import (
    PageAllocator,
    PagedKVCache,
    PrefixStore,
)
from rocm_apex_tpu.inference.sampling import sample
from rocm_apex_tpu.monitor.trace import NULL_TRACER, mint_trace_id
from rocm_apex_tpu.ops._pallas import on_tpu

__all__ = [
    "SamplingParams",
    "Request",
    "GenerationResult",
    "InferenceEngine",
    "FINISH_REASONS",
    "shard_tp1_params",
]


def shard_tp1_params(model, params_tp1, mesh, sample_tokens=None):
    """Slice a tp=1 params pytree into the fake-replicated tp layout.

    The tensor-parallel layers draw INDEPENDENT per-rank values at
    init (rank-folded keys), so a tp>1 model initialized from the same
    seed does NOT compute the tp=1 function. Serving wants exactly
    that function: this helper takes the tp=1 checkpoint and, for each
    leaf, finds the one axis the tp model shards (by comparing against
    the tp model's abstract init shapes), slices the tp=1 weight into
    per-rank shards, and lays them out in the repo's fake-replicated
    idiom — global shape == local shape, each mesh device holding its
    own rank's slice (`check_rep=False` downstream). Replicated leaves
    (LayerNorms, position embeddings, biases of row-parallel layers)
    pass through unchanged on every rank.

    ``model`` is the tp>1 module (its cfg names the tensor axis and
    world size); ``mesh`` the initialized `parallel_state` mesh. The
    returned pytree is committed to the mesh devices, ready for
    `InferenceEngine(model, params)` or a training step.
    """
    from jax.experimental.shard_map import shard_map

    P = jax.sharding.PartitionSpec
    axis = model.cfg.tensor_axis
    tp = mesh.shape[axis]
    if sample_tokens is None:
        sample_tokens = jnp.zeros((1, 8), jnp.int32)

    local_shapes = jax.eval_shape(
        shard_map(
            lambda t: model.init(jax.random.PRNGKey(0), t),
            mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_rep=False,
        ),
        sample_tokens,
    )

    def _stack(full, local):
        full_np = np.asarray(full)
        gshape, lshape = tuple(full_np.shape), tuple(local.shape)
        if gshape == lshape:
            return np.stack([full_np] * tp)
        diff = [
            i for i, (g, l) in enumerate(zip(gshape, lshape)) if g != l
        ]
        if len(gshape) != len(lshape) or len(diff) != 1 or any(
            gshape[i] != lshape[i] * tp for i in diff
        ):
            raise ValueError(
                f"cannot map tp=1 leaf {gshape} onto tp={tp} local "
                f"shape {lshape}"
            )
        ax = diff[0]
        return np.stack(
            np.split(full_np, tp, axis=ax)
        )

    stacked = jax.tree_util.tree_map(_stack, params_tp1, local_shapes)

    def _pick(tree):
        r = jax.lax.axis_index(axis)
        return jax.tree_util.tree_map(
            lambda s: jax.lax.dynamic_index_in_dim(
                s, r, 0, keepdims=False
            ),
            tree,
        )

    return jax.jit(
        shard_map(
            _pick, mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_rep=False,
        )
    )(stacked)

#: every finish_reason a `GenerationResult` can carry — the lifecycle
#: contract documented in docs/inference.md "Failure semantics"
FINISH_REASONS = (
    "eos", "length", "capacity",  # normal completion paths
    "deadline", "cancelled", "error", "queue_full",  # robustness paths
)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Static sampling config — fixed per engine (it is baked into the
    compiled programs). ``temperature=0`` is greedy."""

    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None


@dataclasses.dataclass
class Request:
    request_id: int
    prompt: List[int]
    max_new_tokens: int
    # enqueue wall time (perf_counter domain) — the anchor for the
    # queue-wait and TTFT percentiles in `stats()`
    enqueued_at: float = 0.0
    # lifecycle bounds (absolute perf_counter times; None = unbounded):
    # `deadline` is end-to-end (queue wait + serving), checked at tick
    # boundaries; `queue_deadline` is the admission TTL — a request
    # still queued past it is expired without ever taking a slot.
    deadline: Optional[float] = None
    queue_deadline: Optional[float] = None
    # multi-LoRA serving (engines built with adapter_pool=): the
    # registered adapter this request decodes under (0 = base model)
    # and the tenant it bills to (None on a base engine)
    adapter_id: int = 0
    tenant: Optional[str] = None
    # fleet-causal trace context: minted ONCE at admission (router or
    # first engine to see the request) and carried verbatim across
    # every migration/failover/handoff hop, so merged timelines group
    # a request's whole fleet lifeline under one id ("" = untraced).
    trace_id: str = ""


@dataclasses.dataclass
class GenerationResult:
    request_id: int
    prompt: List[int]
    tokens: List[int]  # generated ids (includes the eos when hit)
    finish_reason: str  # one of FINISH_REASONS


@dataclasses.dataclass
class _Slot:
    """Host-side bookkeeping for one leased cache slot."""

    req: Request
    generated: List[int]
    pos: int  # tokens materialized in the cache for this slot
    cursor: int = 0  # prefix tokens committed to the cache so far
    # tokens this slot must prefill before decoding. Normally the
    # prompt; for a PREEMPTED request re-admitted after its pages were
    # reclaimed it is prompt + generated[:-1] — the recompute-on-resume
    # semantics (the last generated token stays unwritten, exactly the
    # live-slot invariant pos == prompt + generated[:-1]).
    prefix: List[int] = dataclasses.field(default_factory=list)
    resumed: bool = False  # re-admitted after preemption mid-decode
    # per-request timeline anchors (perf_counter domain — the SAME
    # clock as `enqueued_at` and `stats()`): slot lease, first sampled
    # token, and the count of mixed ticks that carried this request's
    # prompt tokens. Host floats only — no device traffic.
    leased_at: float = 0.0
    first_token_at: float = 0.0
    chunks: int = 0
    # paged-cache bookkeeping (engine-paged mode only): page indices
    # this slot BORROWS from the prefix store (immutable until a
    # copy-on-write fork), the chain key of the last full prompt page
    # walked/registered, and how many full prompt pages that is.
    borrowed: Set[int] = dataclasses.field(default_factory=set)
    chain_key: Any = None
    reg_pages: int = 0
    # adapter-pool buffer slot this lease holds ONE admission ref on
    # (0 = base, no ref; -1 = already released — the teardown guard)
    adapter_slot: int = 0

    @property
    def prefilling(self) -> bool:
        return self.cursor < len(self.prefix)


class InferenceEngine:
    """Continuous-batching serving loop for a `GPTModel`.

    ``model``/``params`` are the trained flax module and its variables
    (the same pytree `GPTModel.init` returns — serving reuses the
    training checkpoint directly). The cache dtype defaults to the
    model's compute dtype (bf16 under the O4/O5 recipe).

    ``prefill_token_budget`` (default 64) is the chunked-prefill
    scheduler knob: prompt tokens absorbed per tick, across requests.
    Larger budgets raise prefill throughput (fewer, wider chunks);
    smaller budgets cut time-to-first-token jitter for the decodes
    sharing the tick — see docs/inference.md for the trade.
    ``prefill_chunk`` optionally caps the tokens taken from ONE
    request per tick (a fairness knob inside the budget).
    ``prefill_token_budget=None`` selects the legacy whole-prompt
    prefill (one padded compiled call per request, pad width
    ``max_prompt_len``) — the A/B baseline; only this path has a
    prompt-length ceiling.

    ``tracer`` (a `monitor.Tracer`) opts into per-request serving
    timelines: each request gets its own track with
    enqueue → queue_wait → prefill_chunk spans (chunk token counts as
    args) → decode → finish, built from the SAME ``perf_counter``
    readings that feed ``stats()`` — export with
    ``tracer.export_chrome_trace(path)`` and the span boundaries
    reproduce the reported TTFT/queue-wait numbers. Default ``None``
    is the shared disabled tracer: call sites pay one attribute check,
    the compiled programs and the one-fetch-per-tick host↔device
    pattern are untouched. Per-request COMPLETION records (TTFT, TPOT,
    tokens, chunks, queue wait) accrue on ``completions``
    unconditionally — pure host bookkeeping.

    ``paged=True`` swaps the contiguous per-slot cache for the
    block-table `PagedKVCache` (chunked scheduler required): device
    memory in use scales with LIVE tokens, writes scatter through the
    page table and reads gather through it
    (`flash_attention_decode_paged`). ``page_size`` tunes the
    fragmentation/indirection trade; ``num_pages`` caps the pool
    (default: worst-case slots × pages_per_slot — size it DOWN to
    realize the memory win; exhaustion backpressures token scheduling,
    it never crashes). ``kv_dtype=jnp.int8`` stores int8 pools with
    per-(page, head) fp32 scales (~half the cache bytes and decode
    DMA; greedy outputs stay parity-grade, see tests).
    ``prefix_sharing=True`` additionally ref-counts fully-written
    prompt pages in a `PrefixStore`: a later request with the same
    prompt prefix maps those pages instead of re-prefilling them
    (TTFT collapses for shared-system-prompt traffic) and pages fork
    copy-on-write only when the borrower would write into one.

    Multi-chip serving (``cfg.tensor_parallel_size > 1``; requires
    ``paged=True`` + chunked mode and an initialized
    `parallel_state` mesh): every step program runs under one
    `shard_map` over the tensor axis. The packed prefill chunk rides
    the sequence-parallel + collective-matmul layout (each chip holds
    ``budget/tp`` rows between the embedding scatter and the LM-head
    gather; TP-edge collectives fuse into ppermute rings), the decode
    grid stays plain tensor-parallel, and the paged pools keep GLOBAL
    heads laid out head-sharded (`NamedSharding`) so per-chip KV bytes
    drop by 1/tp (`per_chip_kv_bytes`) while host fetches — page
    shipping, debugging — see full-head arrays. Greedy outputs are
    token-identical to a tp=1 engine and ``mixed_trace_count`` stays 1.

    Robustness layer (docs/inference.md "Failure semantics"): per-
    request deadlines/queue TTLs (``add_request(timeout=, queue_ttl=)``,
    checked at tick boundaries), `cancel`, `drain`, a bounded
    admission queue (``max_queue`` — overflow sheds the NEWEST request
    with a ``queue_full`` result, never silently), a stall watchdog
    (``watchdog_timeout`` wall-seconds without token progress raises
    with the stuck slots named; ``watchdog_dump_path`` persists the
    engine state + tracer timeline first), device-step retry with
    capped exponential backoff (``max_step_retries``/
    ``step_retry_backoff``; exhaustion preempts-and-requeues the
    in-flight batch before surfacing), and per-slot quarantine of
    nonfinite logits (finish reason ``error``; ``flight_recorder``
    dumps the anomaly bundle). ``faults`` accepts a seeded
    `inference.faults.FaultPlan` — the chaos harness that injects
    failures at the page-allocation / device-step / logits /
    host-fetch sites deterministically; the default is the shared
    ``NO_FAULTS`` null plan. Every transition is a host-side slot-mask
    edit: ``mixed_trace_count`` stays 1 under any plan.
    """

    def __init__(
        self,
        model,
        params,
        *,
        num_slots: int = 8,
        max_prompt_len: Optional[int] = None,
        capacity: Optional[int] = None,
        eos_id: Optional[int] = None,
        sampling: Optional[SamplingParams] = None,
        seed: int = 0,
        cache_dtype: Any = None,
        prefill_token_budget: Optional[int] = 64,
        prefill_chunk: Optional[int] = None,
        tracer=None,
        paged: bool = False,
        page_size: int = 16,
        kv_dtype: Any = None,
        num_pages: Optional[int] = None,
        prefix_sharing: bool = False,
        spec_k: int = 0,
        drafter=None,
        spec_window: int = 64,
        faults=None,
        max_queue: Optional[int] = None,
        max_step_retries: int = 2,
        step_retry_backoff: float = 0.0,
        watchdog_timeout: Optional[float] = None,
        watchdog_dump_path: Optional[str] = None,
        flight_recorder=None,
        donate_buffers: Optional[bool] = None,
        registry=None,
        stats_retention: int = 4096,
        step_source: Optional["InferenceEngine"] = None,
        adapter_pool=None,
        tier_preemption: bool = False,
        retrace_policy: Optional[str] = None,
        timeseries=None,
    ):
        cfg = model.cfg
        tp = int(cfg.tensor_parallel_size or 1)
        self.tp = tp
        self._mesh = None
        if tp > 1:
            # Multi-chip serving: the fused mixed step runs under
            # shard_map over the tensor axis. The packed chunk rides
            # the PR-3 sequence-parallel layout (ring collectives from
            # ops/collective_matmul.py); the decode grid stays plain
            # tensor-parallel (its width-1 seq axis cannot shard); the
            # paged pools are laid out head-sharded so per-chip KV
            # bytes drop by 1/tp (see _cache_pspec).
            from rocm_apex_tpu.transformer import parallel_state

            if not parallel_state.model_parallel_is_initialized():
                raise ValueError(
                    "tp>1 serving needs parallel_state."
                    "initialize_model_parallel(tp, 1) before engine "
                    "construction (the shard_map mesh comes from it)"
                )
            if parallel_state.get_tensor_model_parallel_world_size() != tp:
                raise ValueError(
                    f"model cfg.tensor_parallel_size={tp} but the "
                    f"initialized mesh has tensor size "
                    f"{parallel_state.get_tensor_model_parallel_world_size()}"
                )
            self._mesh = parallel_state.get_mesh()
            if not paged:
                raise ValueError(
                    "tp>1 serving shards the PagedKVCache pools over "
                    "heads; set paged=True"
                )
            if prefill_token_budget is None:
                raise ValueError(
                    "tp>1 serving rides the chunked mixed step; set "
                    "prefill_token_budget"
                )
            if prefill_token_budget % tp != 0:
                raise ValueError(
                    f"prefill_token_budget={prefill_token_budget} must "
                    f"divide by tp={tp} (the chunk stream is "
                    f"sequence-scattered over the tensor axis)"
                )
            if cfg.num_attention_heads % tp != 0:
                raise ValueError(
                    f"num_attention_heads={cfg.num_attention_heads} "
                    f"must divide by tp={tp}"
                )
        self.model = model
        self.params = params
        self.capacity = int(capacity or cfg.max_position_embeddings)
        if self.capacity > cfg.max_position_embeddings:
            raise ValueError(
                f"capacity {self.capacity} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}"
            )
        self.max_prompt_len = int(max_prompt_len or self.capacity)
        if not 0 < self.max_prompt_len <= self.capacity:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} must be in "
                f"(0, capacity={self.capacity}]"
            )
        if prefill_token_budget is not None and prefill_token_budget < 1:
            raise ValueError(
                f"prefill_token_budget must be >= 1 (or None for the "
                f"whole-prompt path), got {prefill_token_budget}"
            )
        self.prefill_token_budget = (
            int(prefill_token_budget)
            if prefill_token_budget is not None else None
        )
        self.prefill_chunk = (
            int(prefill_chunk) if prefill_chunk is not None else None
        )
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self.eos_id = eos_id
        self.sampling = sampling or SamplingParams()
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if self.spec_k > 0:
            if self.prefill_token_budget is None:
                raise ValueError(
                    "speculative decoding rides the chunked mixed step; "
                    "set prefill_token_budget (chunked mode) to use "
                    "spec_k"
                )
            if self.spec_k + 1 > self.prefill_token_budget:
                raise ValueError(
                    f"spec_k={self.spec_k} needs spec_k + 1 <= "
                    f"prefill_token_budget="
                    f"{self.prefill_token_budget} chunk rows (the "
                    f"verified span is the last token plus k drafts)"
                )
            if drafter is None:
                from rocm_apex_tpu.inference.drafting import NGramDrafter

                drafter = NGramDrafter(self.spec_k, window=spec_window)
        self._drafter = drafter if self.spec_k > 0 else None
        self._spec_window = int(
            getattr(self._drafter, "window", spec_window)
        )
        # ---- multi-LoRA serving (ISSUE 18) ---------------------------
        # adapter_pool: an `inference.adapters.AdapterPool` whose
        # packed device buffers the lora step closures below gather
        # per-token deltas from (ops/lora.py). The pool is engine-owned
        # state like the KV cache: its buffers are donated through the
        # jits and re-bound every tick. Admission acquires one ref per
        # in-flight request (tier-ordered, acquire-or-skip — see
        # `_pick_queued`); every teardown path releases exactly once.
        self.adapter_pool = adapter_pool
        self.tier_preemption = bool(tier_preemption)
        self._adapter_stalls = 0
        self._tier_preemptions = 0
        self._tier_sheds = 0
        # host-side per-tenant completion accounting (the chaos
        # isolation identity: sums across tenants == the global
        # counters) — keyed by TRUE tenant name, unlike the labeled
        # metric families which overflow into "other" at the cap
        self._tenant_counts: Dict[str, Dict[str, int]] = {}
        if adapter_pool is not None:
            if tp > 1:
                raise ValueError(
                    "adapter_pool serving is tp=1 only for now (the "
                    "segmented gather would need head-sharded adapter "
                    "buffers)"
                )
            if self.spec_k > 0:
                raise ValueError(
                    "adapter_pool does not compose with speculative "
                    "decoding yet (the drafter is base-model-only; a "
                    "per-adapter draft would be wrong for every "
                    "non-base slot)"
                )
            if self.prefill_token_budget is None:
                raise ValueError(
                    "adapter_pool rides the chunked mixed step; set "
                    "prefill_token_budget"
                )
            if (
                adapter_pool.num_layers != cfg.num_layers
                or adapter_pool.hidden != cfg.hidden_size
                or adapter_pool.out_dims["qkv"] != 3 * cfg.hidden_size
            ):
                raise ValueError(
                    f"adapter pool geometry (layers="
                    f"{adapter_pool.num_layers}, hidden="
                    f"{adapter_pool.hidden}, qkv_out="
                    f"{adapter_pool.out_dims['qkv']}) does not match "
                    f"the model (layers={cfg.num_layers}, hidden="
                    f"{cfg.hidden_size})"
                )
        self.paged = bool(paged)
        self.prefix_sharing = bool(prefix_sharing)
        self._allocator = None
        self._store = None
        self._cow_forks = 0
        self._prefix_hits = 0
        self._prefix_hit_tokens = 0
        self._page_stalls = 0
        self._preemptions = 0
        # preempted-request carryover: request_id -> (generated tokens,
        # first_token_at, chunk count) restored on re-admission
        self._preempted: Dict[int, Any] = {}
        # page-shipping migration: payloads handed to resume_request(pages=...)
        # wait here until the request leases a slot; fallbacks replay tokens
        self._shipped: Dict[int, Any] = {}
        self._page_ships = 0
        self._page_ship_fallbacks = 0
        # speculative-decoding accounting: every drafted token ends up
        # either accepted (emitted) or rolled back
        self._tokens_drafted = 0
        self._tokens_accepted = 0
        self._rollbacks = 0
        if not self.paged:
            if prefix_sharing:
                raise ValueError("prefix_sharing requires paged=True")
            if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
                raise ValueError("kv_dtype=int8 requires paged=True")
            self.cache = KVCache.for_model(
                cfg, num_slots, self.capacity, dtype=cache_dtype
            )
        else:
            if self.prefill_token_budget is None:
                raise ValueError(
                    "the paged cache serves the chunked-prefill "
                    "scheduler only (the legacy whole-prompt path "
                    "needs contiguous slot rows); set "
                    "prefill_token_budget"
                )
            quantized = (
                kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8
            )
            self.cache = PagedKVCache.for_model(
                cfg, num_slots, self.capacity,
                page_size=page_size, num_pages=num_pages,
                dtype=(
                    kv_dtype if (kv_dtype is not None and not quantized)
                    else cache_dtype
                ),
                quantized=quantized,
                # tp>1: GLOBAL head count in the pools; the NamedSharding
                # below splits dim 1 (heads) over the tensor axis, so
                # each chip physically holds 1/tp of the KV bytes while
                # host fetches (page shipping, debugging) still see
                # full-head arrays — shipped pages are tp-agnostic.
                full_heads=(tp > 1),
            )
            if tp > 1:
                self.cache = jax.device_put(
                    self.cache, self._cache_sharding()
                )
            self._allocator = PageAllocator(self.cache.num_pages)
            if prefix_sharing:
                self._store = PrefixStore(page_size)
                self._allocator.on_evict = self._store.unregister_page
            # host mirror of the page table (the host is the source of
            # truth; pushed to device once per tick when dirty)
            self._table = np.full(
                (num_slots, self.cache.pages_per_slot),
                self.cache.num_pages, np.int32,
            )
            self._table_dirty = False
            self._fork_jit = jax.jit(
                lambda cache, src, dst: cache.fork_page(src, dst)
            )
        self._rng = jax.random.PRNGKey(seed)
        self._queue: collections.deque = collections.deque()
        self._slots: List[Optional[_Slot]] = [None] * num_slots
        self._next_id = 0
        # Trace counters live in ONE mutable cell so replicas built
        # with `step_source=` (see below) share it: the fleet traced
        # each program once, and every replica's `*_trace_count`
        # reports that shared truth — a retrace anywhere still trips
        # the `== 1` invariant the tests pin.
        self._traces = {"prefill": 0, "decode": 0, "mixed": 0,
                        "commit": 0}
        # serving telemetry (read via `stats()`, fed to a
        # monitor.MetricsLogger): monotonic counters + wall-time sums.
        # Latencies include the result fetch — on the tunnel platform
        # that fetch IS the device sync (the Timers rule), so these are
        # true end-to-end numbers, not dispatch times. Per-request
        # queue waits (enqueue -> slot lease) and TTFTs (enqueue ->
        # first token) feed the p50/p95 fields that surface the
        # head-of-line blocking the chunked scheduler removes.
        self._admitted = 0
        self._evicted = 0
        self._prompt_tokens = 0
        self._generated_tokens = 0
        self._prefill_seconds = 0.0
        self._decode_seconds = 0.0
        self._decode_steps = 0
        self._mixed_steps = 0
        # Raw per-request samples keep EXACT percentiles while they
        # fit; `stats_retention` caps them (oldest drop) so a
        # long-lived engine has O(1) stats memory. The registry
        # histograms below never drop — once the rings wrap, stats()
        # switches to their bounded-error quantiles (see stats()).
        if stats_retention < 1:
            raise ValueError(
                f"stats_retention must be >= 1, got {stats_retention}"
            )
        self.stats_retention = int(stats_retention)
        self._queue_waits: collections.deque = collections.deque(
            maxlen=self.stats_retention
        )
        self._ttfts: collections.deque = collections.deque(
            maxlen=self.stats_retention
        )
        # per-request completion records (host-side; see `completions`)
        self._completions: collections.deque = collections.deque(
            maxlen=self.stats_retention
        )
        # Mergeable constant-memory telemetry (monitor/telemetry.py):
        # a private enabled registry by default so every engine can be
        # scraped / merged; pass monitor.NULL_REGISTRY to opt out
        # (stats() then serves the capped rings only). All observation
        # is host-side — the compiled programs gain ZERO equations
        # (pinned by tools/graphlint.py fingerprints).
        if registry is None:
            from rocm_apex_tpu.monitor.telemetry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        self._h_queue_wait = registry.histogram(
            "serve_queue_wait_ms",
            "Request queue wait (enqueue -> slot lease), ms.",
        )
        # multi-tenant engines label TTFT and the token counters by
        # tenant (the per-tenant SLO feed); unlabeled reads on these
        # families aggregate across series, so stats() and the base
        # bench consume both shapes identically. The cardinality cap
        # is honored by an explicit "other" overflow tenant (see
        # `_tenant_series`) — the serving hot path NEVER raises
        # CardinalityError.
        self._per_tenant = adapter_pool is not None
        if self._per_tenant:
            self._h_ttft = registry.histogram(
                "serve_ttft_ms",
                "Time to first token (enqueue -> first token), ms.",
                labelnames=("tenant",),
            )
            self._c_tokens = registry.counter(
                "serve_tokens_total",
                "Tokens of finished requests, by phase "
                "(prompt=ingested, generated=emitted) and tenant.",
                labelnames=("phase", "tenant"),
            )
            # pre-create the overflow series so the fallback can never
            # itself overflow, whatever max_label_sets is
            self._c_tokens.labels(phase="prompt", tenant="other")
            self._c_tokens.labels(phase="generated", tenant="other")
            self._h_ttft.labels(tenant="other")
            self._tenant_label_ok: Set[str] = {"other"}
            self._tenant_overflowed: Set[str] = set()
        else:
            self._h_ttft = registry.histogram(
                "serve_ttft_ms",
                "Time to first token (enqueue -> first token), ms.",
            )
            self._c_tokens = registry.counter(
                "serve_tokens_total",
                "Tokens of finished requests, by phase "
                "(prompt=ingested, generated=emitted).",
                labelnames=("phase",),
            )
        self._h_tpot = registry.histogram(
            "serve_tpot_ms",
            "Mean inter-token time after the first token, ms.",
        )
        self._h_e2e = registry.histogram(
            "serve_e2e_ms",
            "Request end-to-end latency (enqueue -> finish), ms.",
        )
        self._c_completions = registry.counter(
            "serve_completions_total",
            "Finished requests by terminal finish_reason.",
            labelnames=("finish_reason",),
        )
        self._g_queue_depth = registry.gauge(
            "serve_queue_depth", "Requests waiting for a slot."
        )
        self._g_slots_active = registry.gauge(
            "serve_slots_active", "Slots holding a live request."
        )
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # ---- runtime retrace sentinel + sensor plane (ISSUE 19) ------
        # retrace_policy="count"|"raise" arms a RetraceSentinel at the
        # next reset_stats() (the bench contract's warmed-up-now
        # marker): a jax compile landing after that boundary is the
        # latency cliff the one-compiled-trace invariant forbids —
        # "count" observes it into xla_compiles_post_warmup_total,
        # "raise" fails the NEXT step() (never mid-compile). The
        # timeseries ring, when attached, samples the registry once
        # per `interval` from the step loop.
        self.retrace_sentinel = None
        if retrace_policy is not None:
            from rocm_apex_tpu.monitor.trace import RetraceSentinel

            self.retrace_sentinel = RetraceSentinel(
                registry, policy=retrace_policy, tracer=self.tracer
            )
        self.timeseries = timeseries
        # ---- robustness layer (ISSUE 12) -----------------------------
        # faults: the chaos harness (NO_FAULTS = the shared null plan —
        # call sites pay one `enabled` attribute check, the NULL_TRACER
        # idiom). All injection and all lifecycle transitions below are
        # host-side slot-mask edits: the compiled programs never change
        # shape and `mixed_trace_count` stays 1 under any plan.
        self.faults = faults if faults is not None else NO_FAULTS
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        if max_step_retries < 0:
            raise ValueError(
                f"max_step_retries must be >= 0, got {max_step_retries}"
            )
        self.max_step_retries = int(max_step_retries)
        self.step_retry_backoff = float(step_retry_backoff)
        if watchdog_timeout is not None and watchdog_timeout <= 0:
            raise ValueError(
                f"watchdog_timeout must be > 0 seconds, got "
                f"{watchdog_timeout}"
            )
        self.watchdog_timeout = watchdog_timeout
        self.watchdog_dump_path = watchdog_dump_path
        self.flight_recorder = flight_recorder
        self._cancelled = 0
        self._deadline_exceeded = 0
        self._quarantined = 0
        self._step_retries = 0
        self._shed = 0
        self._watchdog_fires = 0
        self._evacuated = 0
        self._draining = False
        self._tick = 0  # step() count — the fault plans' tick domain
        # queue_full results awaiting delivery through the next step()
        self._shed_results: List[GenerationResult] = []
        # stall watchdog anchors: last wall time token progress was
        # observed, and the counter snapshot that defines "progress"
        self._last_progress = time.perf_counter()
        self._progress_mark = (0, 0, 0)

        if step_source is not None:
            # Replica fast-path: adopt an existing engine's compiled
            # step programs instead of re-tracing identical ones. The
            # traced graphs close over the model object, the sampling
            # config, the cache geometry, and the donation flag — so
            # adoption is refused unless all of them match. Used by
            # ReplicaRouter: an N-replica fleet warms up once, not N
            # times, and the shared trace-counter cell keeps every
            # replica's `mixed_trace_count == 1` invariant honest.
            if donate_buffers is None:
                donate_buffers = on_tpu()
            self.donate_buffers = bool(donate_buffers)
            self._adopt_steps(step_source)
            return

        sp = self.sampling

        # Model variants for the tp>1 split: the CHUNK apply rides the
        # sequence-parallel + collective-matmul layout (the packed
        # stream scatters to (1, budget/tp, h) rows per chip and the
        # TP-edge collectives fuse into ppermute rings), while the
        # DECODE apply keeps plain tensor parallelism (a width-1 seq
        # axis cannot be sequence-sharded). sequence_parallel changes
        # ZERO parameter shapes, so both variants consume the same
        # params pytree; at tp=1 both are the caller's model.
        decode_model = model
        chunk_model = model
        if tp > 1:
            chunk_model = type(model)(
                cfg=dataclasses.replace(
                    cfg, sequence_parallel=True, collective_matmul=True
                )
            )
            if cfg.sequence_parallel:
                decode_model = type(model)(
                    cfg=dataclasses.replace(
                        cfg, sequence_parallel=False,
                        collective_matmul=False,
                    )
                )

        if tp > 1:
            from rocm_apex_tpu.transformer.tensor_parallel import mappings

            tensor_axis = cfg.tensor_axis

            def _full_logits(logits):
                # the tied head returns VOCAB-PARALLEL logits
                # (..., vocab/tp); sampling needs the full vocab row.
                # The gather is replicated-in, replicated-out, so the
                # sample below is bit-identical on every rank.
                return mappings.gather_from_tensor_model_parallel_region(
                    logits, tensor_axis
                )
        else:
            def _full_logits(logits):
                return logits

        def _sample(rng, logits):
            return sample(
                rng,
                logits,
                temperature=sp.temperature,
                top_k=sp.top_k,
                top_p=sp.top_p,
            )

        def _prefill(params, cache, tokens, slot, length, rng):
            # trace-time side effect: counts COMPILES, not calls
            self._traces["prefill"] += 1
            sub = cache.slot_view(slot)
            sub = sub.replace(lengths=jnp.zeros((1,), jnp.int32))
            logits, sub = decode_model.apply(params, tokens, cache=sub)
            # the model advanced by the PADDED width; the live prefix
            # is the real prompt — decode overwrites the pad positions
            # one by one and never attends past `lengths`
            sub = sub.replace(
                lengths=jnp.reshape(length, (1,)).astype(jnp.int32)
            )
            cache = cache.write_back(slot, sub)
            last = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, 0, keepdims=False
            )
            first_tok = _sample(rng, _full_logits(last)[None, :])[0]
            return first_tok, cache

        is_paged = self.paged
        dev_capacity = self.cache.capacity

        def _decode_body(params, cache, tokens, active, poison, rng,
                         adapters=None):
            # `poison` is a per-slot fp32 addend on the logits — zeros
            # on the fault-free path (x + 0.0 leaves the greedy argmax
            # and the sampling distribution untouched), NaN/Inf when
            # the chaos harness poisons one slot. The per-slot
            # nonfinite flag is computed IN-GRAPH and rides the same
            # batched fetch as the sampled tokens, so fault isolation
            # costs no extra device sync and no extra trace — it also
            # catches a genuine model blow-up for free.
            lengths0 = cache.lengths
            if is_paged:
                # dead rows write at the device capacity sentinel: the
                # paged scatter DROPS the write (a contiguous cache
                # tolerates dead-row junk because the next prefill
                # overwrites it, but a paged junk write could land in
                # a live — even SHARED — page, and under int8 would
                # inflate that page's running scale)
                cache = cache.replace(
                    lengths=jnp.where(
                        active, lengths0,
                        jnp.full_like(lengths0, dev_capacity),
                    )
                )
            logits, new_cache = decode_model.apply(
                params, tokens[:, None], cache=cache, adapters=adapters
            )
            # pin inactive slots' lengths (their dead-row writes drop
            # (paged) or land in junk the next prefill overwrites
            # (contiguous), but unbounded drift would saturate the
            # clamp)
            new_cache = new_cache.replace(
                lengths=jnp.where(
                    active, new_cache.lengths, lengths0
                )
            )
            last = _full_logits(logits[:, -1, :]) + poison[:, None]
            bad = jnp.any(~jnp.isfinite(last), axis=-1)
            tok = _sample(rng, last)
            return jnp.where(active, tok, 0), bad, new_cache

        def _decode(params, cache, tokens, active, poison, rng):
            self._traces["decode"] += 1
            return _decode_body(params, cache, tokens, active, poison, rng)

        def _mixed(
            params, cache, chunk_tokens, chunk_slots, chunk_pos,
            lengths_before, lengths_after, completion_idx,
            dec_tokens, dec_active, chunk_poison, dec_poison, rng,
        ):
            """ONE compiled program per tick: packed prefill chunk +
            the whole decode grid. The host is the source of truth for
            per-slot lengths (a freed slot's stale device length must
            never bound a successor's reads), so the cursor vectors
            ride in as arguments. ``completion_idx[slot]`` is the chunk
            index of the slot's LAST prompt token when its prefill
            completes this tick (else -1): its sampled first token is
            fed STRAIGHT into the decode grid, so a completing request
            gets its second token in the same tick — exactly the
            whole-prompt path's admit-tick cadence, with no padded
            prefill."""
            self._traces["mixed"] += 1
            rng_c, rng_d = jax.random.split(rng)
            cache = cache.replace(lengths=lengths_before)
            logits_c, cache = chunk_model.apply(
                params,
                chunk_tokens[None, :],
                cache=cache,
                chunk=(chunk_slots, chunk_pos),
            )
            logits_c = _full_logits(logits_c)
            # sample EVERY chunk position (fixed shape); the host keeps
            # only the positions that completed a prompt this tick.
            # `chunk_poison` follows the decode-grid poison contract:
            # zeros normally, NaN/Inf on a quarantine-test row — the
            # per-row nonfinite flags share the tick's one fetch.
            logits_p = logits_c[0] + chunk_poison[:, None]
            chunk_bad = jnp.any(~jnp.isfinite(logits_p), axis=-1)
            chunk_tok = _sample(rng_c, logits_p)
            # commit the chunk: cursors advance by what was packed
            cache = cache.replace(lengths=lengths_after)
            budget = chunk_tokens.shape[0]
            has_comp = completion_idx >= 0
            first_tok = chunk_tok[
                jnp.clip(completion_idx, 0, budget - 1)
            ]
            dec_tokens = jnp.where(has_comp, first_tok, dec_tokens)
            dec_active = dec_active | has_comp
            dec_tok, dec_bad, cache = _decode_body(
                params, cache, dec_tokens, dec_active, dec_poison, rng_d
            )
            return chunk_tok, dec_tok, chunk_bad, dec_bad, cache

        def _mixed_spec(
            params, cache, chunk_tokens, chunk_slots, chunk_pos,
            commit_slots, lengths_before, lengths_after, completion_idx,
            dec_tokens, dec_active, chunk_poison, dec_poison, rng,
        ):
            """Speculative variant of `_mixed`: the chunk may carry,
            per decoding slot, that slot's last generated token plus up
            to k drafted continuations. Those rows score against the
            slot's committed prefix in the SAME fused trace (they are
            just budget tokens — no per-k shapes), but their K/V must
            NOT commit in-trace: a rejected draft can never be unwound
            from a shared page or an int8 scale that only grows, and
            the contiguous decode grid's dead-row write would clobber
            an eagerly-committed row. So every speculative row carries
            the pad sentinel in ``commit_slots`` (the scatter drops
            it), the model hands back the packed per-layer chunk K/V,
            and the host commits exactly the accepted prefix afterwards
            (`_commit`). One compiled program per engine run:
            ``mixed_trace_count`` stays 1 at any k."""
            self._traces["mixed"] += 1
            rng_c, rng_d = jax.random.split(rng)
            cache = cache.replace(lengths=lengths_before)
            logits_c, cache, chunk_kv = chunk_model.apply(
                params,
                chunk_tokens[None, :],
                cache=cache,
                chunk=(chunk_slots, chunk_pos, commit_slots),
            )
            logits_c = _full_logits(logits_c)
            # sample EVERY chunk position: for a draft row the sample
            # IS the verifier's token — greedy accepts on equality,
            # and under temperature the sample-vs-draft equality test
            # is exact rejection sampling for a point-mass drafter
            logits_p = logits_c[0] + chunk_poison[:, None]
            chunk_bad = jnp.any(~jnp.isfinite(logits_p), axis=-1)
            chunk_tok = _sample(rng_c, logits_p)
            cache = cache.replace(lengths=lengths_after)
            budget = chunk_tokens.shape[0]
            has_comp = completion_idx >= 0
            first_tok = chunk_tok[
                jnp.clip(completion_idx, 0, budget - 1)
            ]
            dec_tokens = jnp.where(has_comp, first_tok, dec_tokens)
            dec_active = dec_active | has_comp
            dec_tok, dec_bad, cache = _decode_body(
                params, cache, dec_tokens, dec_active, dec_poison, rng_d
            )
            return chunk_tok, dec_tok, chunk_bad, dec_bad, cache, chunk_kv

        n_layers = len(self.cache.k)

        def _commit(cache, chunk_kv, slots, positions):
            """Post-verification commit: write the accepted rows'
            packed chunk K/V into the cache (`write_at` drops the pad
            sentinel rows). Fixed (budget,) shapes — ONE compiled
            commit program per engine run."""
            self._traces["commit"] += 1
            ck, cv = chunk_kv
            for i in range(n_layers):
                cache = cache.write_at(i, slots, positions, ck[i], cv[i])
            return cache

        # cache buffers are DONATED: the step updates them in place on
        # TPU. On CPU (the test platform) the default is NO donation —
        # the fault-retry path (`_call_device`) re-runs a step from the
        # caller's still-live buffers, which donation would have
        # deleted. `donate_buffers` overrides the gate both ways (the
        # graph-contract linter lowers a donating engine to verify the
        # aliasing contract without being on TPU).
        if donate_buffers is None:
            donate_buffers = on_tpu()
        self.donate_buffers = bool(donate_buffers)
        donate = (1,) if self.donate_buffers else ()
        self._prefill_fn = _prefill
        self._decode_fn = _decode_body
        self._mixed_fn = _mixed
        self._mixed_spec_fn = _mixed_spec
        self._commit_fn = _commit
        if tp > 1:
            # One shard_map per step program, jitted around the whole
            # region: replicated host inputs (token buffers, masks,
            # cursors, rng) ride in with P(); the cache rides its
            # head-sharded spec; params are the repo's fake-replicated
            # idiom (global shape == local shape, per-rank contents),
            # so P() hands each rank its own shard. check_rep=False:
            # the sampled tokens are replicated by construction (the
            # vocab gather), not by anything the rep checker can see.
            from jax.experimental.shard_map import shard_map

            P = jax.sharding.PartitionSpec
            rep = P()
            cspec = self._cache_pspec()
            kv_spec = tuple(
                P(None, cfg.tensor_axis, None) for _ in range(n_layers)
            )
            mesh = self._mesh

            def _shmap(f, n_rep_in, out_specs):
                return shard_map(
                    f, mesh=mesh,
                    in_specs=(rep, cspec) + (rep,) * n_rep_in,
                    out_specs=out_specs,
                    check_rep=False,
                )

            _decode = _shmap(_decode, 4, (rep, rep, cspec))
            _mixed = _shmap(_mixed, 11, (rep, rep, rep, rep, cspec))
            _mixed_spec = _shmap(
                _mixed_spec, 12,
                (rep, rep, rep, rep, cspec, (kv_spec, kv_spec)),
            )
            _commit = shard_map(
                _commit, mesh=mesh,
                in_specs=(cspec, (kv_spec, kv_spec), rep, rep),
                out_specs=cspec,
                check_rep=False,
            )
        self._prefill_jit = jax.jit(_prefill, donate_argnums=donate)
        self._decode_jit = jax.jit(_decode, donate_argnums=donate)
        self._mixed_jit = jax.jit(_mixed, donate_argnums=donate)
        self._mixed_spec_jit = jax.jit(_mixed_spec, donate_argnums=donate)
        self._commit_jit = jax.jit(
            _commit, donate_argnums=(0,) if self.donate_buffers else ()
        )

        # ---- multi-LoRA step programs (adapter_pool engines only).
        # Separate closures with the adapter-buffer pytree as argument
        # 2 — the BASE programs above are byte-identical with or
        # without a pool (their graphlint fingerprints never move).
        # The buffers are donated alongside the cache and returned
        # pass-through, so the output aliases the input allocation and
        # the host re-binds `pool.buffers` each tick exactly like
        # `self.cache`. Adapter IDS are data: any tenant mix, any
        # park/reclaim churn, and any adapter registration all ride
        # ONE compiled program (`mixed_trace_count` stays 1).
        self._decode_lora_fn = None
        self._mixed_lora_fn = None
        self._decode_lora_jit = None
        self._mixed_lora_jit = None
        if self.adapter_pool is not None:
            def _decode_lora(
                params, cache, adapters, tokens, active, dec_adp,
                poison, rng,
            ):
                self._traces["decode"] += 1
                full = dict(
                    adapters, ids=dec_adp,
                    active=jnp.any(dec_adp != 0),
                )
                tok, bad, cache = _decode_body(
                    params, cache, tokens, active, poison, rng,
                    adapters=full,
                )
                return tok, bad, cache, adapters

            def _mixed_lora(
                params, cache, adapters, chunk_tokens, chunk_slots,
                chunk_pos, chunk_adp, lengths_before, lengths_after,
                completion_idx, dec_tokens, dec_active, dec_adp,
                chunk_poison, dec_poison, rng,
            ):
                """`_mixed` with per-token adapter ids riding next to
                the slot ids/positions: ``chunk_adp`` (budget,) maps
                each packed prompt token to its pool buffer slot,
                ``dec_adp`` (S,) each decode row. ``active`` flags
                (any id != 0, computed in-trace) arm the `apply_lora`
                skip branch — a pure-base tick runs zero adapter
                FLOPs in this same program."""
                self._traces["mixed"] += 1
                rng_c, rng_d = jax.random.split(rng)
                cache = cache.replace(lengths=lengths_before)
                chunk_full = dict(
                    adapters, ids=chunk_adp,
                    active=jnp.any(chunk_adp != 0),
                )
                logits_c, cache = chunk_model.apply(
                    params,
                    chunk_tokens[None, :],
                    cache=cache,
                    chunk=(chunk_slots, chunk_pos),
                    adapters=chunk_full,
                )
                logits_c = _full_logits(logits_c)
                logits_p = logits_c[0] + chunk_poison[:, None]
                chunk_bad = jnp.any(~jnp.isfinite(logits_p), axis=-1)
                chunk_tok = _sample(rng_c, logits_p)
                cache = cache.replace(lengths=lengths_after)
                budget = chunk_tokens.shape[0]
                has_comp = completion_idx >= 0
                first_tok = chunk_tok[
                    jnp.clip(completion_idx, 0, budget - 1)
                ]
                dec_tokens = jnp.where(has_comp, first_tok, dec_tokens)
                dec_active = dec_active | has_comp
                dec_full = dict(
                    adapters, ids=dec_adp,
                    active=jnp.any(dec_adp != 0),
                )
                dec_tok, dec_bad, cache = _decode_body(
                    params, cache, dec_tokens, dec_active, dec_poison,
                    rng_d, adapters=dec_full,
                )
                return (
                    chunk_tok, dec_tok, chunk_bad, dec_bad, cache,
                    adapters,
                )

            donate_l = (1, 2) if self.donate_buffers else ()
            self._decode_lora_fn = _decode_lora
            self._mixed_lora_fn = _mixed_lora
            self._decode_lora_jit = jax.jit(
                _decode_lora, donate_argnums=donate_l
            )
            self._mixed_lora_jit = jax.jit(
                _mixed_lora, donate_argnums=donate_l
            )

    def _adopt_steps(self, src: "InferenceEngine") -> None:
        """Alias `src`'s compiled step programs (and the trace-counter
        cell they increment) into this engine. The traced graphs bake
        in everything checked here; a mismatch would silently retrace
        per call or, worse, run the wrong geometry — so refuse loudly.
        """
        def _shapes(tree):
            return jax.tree_util.tree_map(
                lambda a: (
                    tuple(getattr(a, "shape", ())),
                    str(getattr(a, "dtype", type(a).__name__)),
                ),
                tree,
            )

        mismatches = []
        if src.model is not self.model:
            mismatches.append("model (must be the SAME object)")
        if src.sampling != self.sampling:
            mismatches.append("sampling")
        if src.prefill_token_budget != self.prefill_token_budget:
            mismatches.append("prefill_token_budget")
        if src.spec_k != self.spec_k:
            mismatches.append("spec_k")
        if src.paged != self.paged:
            mismatches.append("paged")
        if src.donate_buffers != self.donate_buffers:
            mismatches.append("donate_buffers")
        if type(src.cache) is not type(self.cache):
            mismatches.append("cache layout")
        elif _shapes(src.cache) != _shapes(self.cache):
            mismatches.append(
                "cache geometry (num_slots/capacity/page_size/dtype)"
            )
        if (src.adapter_pool is None) != (self.adapter_pool is None):
            mismatches.append("adapter_pool presence")
        elif self.adapter_pool is not None and _shapes(
            src.adapter_pool.buffers
        ) != _shapes(self.adapter_pool.buffers):
            mismatches.append(
                "adapter pool geometry (max_resident/max_rank)"
            )
        if mismatches:
            raise ValueError(
                "step_source engine is incompatible; differs in: "
                + ", ".join(mismatches)
            )
        self._traces = src._traces
        self._prefill_fn = src._prefill_fn
        self._decode_fn = src._decode_fn
        self._mixed_fn = src._mixed_fn
        self._mixed_spec_fn = src._mixed_spec_fn
        self._commit_fn = src._commit_fn
        self._prefill_jit = src._prefill_jit
        self._decode_jit = src._decode_jit
        self._mixed_jit = src._mixed_jit
        self._mixed_spec_jit = src._mixed_spec_jit
        self._commit_jit = src._commit_jit
        self._decode_lora_fn = src._decode_lora_fn
        self._mixed_lora_fn = src._mixed_lora_fn
        self._decode_lora_jit = src._decode_lora_jit
        self._mixed_lora_jit = src._mixed_lora_jit
        if self.paged:
            self._fork_jit = src._fork_jit

    # ------------------------------------------------------------------
    # tp>1 cache layout
    # ------------------------------------------------------------------

    def _cache_pspec(self):
        """PartitionSpec pytree matching the `PagedKVCache` structure:
        pools head-sharded over the tensor axis (dim 1 of
        ``(num_pages, heads, page_size, head_dim)``), int8 scales
        likewise (dim 1 of ``(num_pages, heads)``), table and lengths
        replicated. Used both as the shard_map cache spec and (through
        `_cache_sharding`) as the initial device layout."""
        P = jax.sharding.PartitionSpec
        axis = self.model.cfg.tensor_axis
        n = len(self.cache.k)
        pool = P(None, axis, None, None)
        sc = P(None, axis)
        return PagedKVCache(
            k=tuple(pool for _ in range(n)),
            v=tuple(pool for _ in range(n)),
            k_scale=(
                None if self.cache.k_scale is None
                else tuple(sc for _ in range(n))
            ),
            v_scale=(
                None if self.cache.v_scale is None
                else tuple(sc for _ in range(n))
            ),
            page_table=P(),
            lengths=P(),
            page_size=self.cache.page_size,
        )

    def _cache_sharding(self):
        """`NamedSharding` pytree for `jax.device_put` of the cache."""
        mesh = self._mesh
        spec = self._cache_pspec()
        ns = lambda s: jax.sharding.NamedSharding(mesh, s)
        n = len(self.cache.k)
        return PagedKVCache(
            k=tuple(ns(s) for s in spec.k),
            v=tuple(ns(s) for s in spec.v),
            k_scale=(
                None if spec.k_scale is None
                else tuple(ns(s) for s in spec.k_scale)
            ),
            v_scale=(
                None if spec.v_scale is None
                else tuple(ns(s) for s in spec.v_scale)
            ),
            page_table=ns(spec.page_table),
            lengths=ns(spec.lengths),
            page_size=spec.page_size,
        )

    def per_chip_kv_bytes(self) -> int:
        """Physical KV pool + scale bytes held by the most-loaded chip
        — the 1/tp audit number (a tp=1 engine reports the full pool).
        Walks `addressable_shards`, so it measures the layout the
        arrays actually have, not the intended spec."""
        per_dev: Dict[Any, int] = {}
        arrays = list(self.cache.k) + list(self.cache.v)
        for scales in (self.cache.k_scale, self.cache.v_scale):
            if scales is not None:
                arrays += list(scales)
        for a in arrays:
            for sh in a.addressable_shards:
                nbytes = sh.data.size * sh.data.dtype.itemsize
                per_dev[sh.device] = per_dev.get(sh.device, 0) + nbytes
        return max(per_dev.values()) if per_dev else 0

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @property
    def num_slots(self) -> int:
        return len(self._slots)

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    @property
    def chunked(self) -> bool:
        return self.prefill_token_budget is not None

    @property
    def prefill_trace_count(self) -> int:
        return self._traces["prefill"]

    @property
    def decode_trace_count(self) -> int:
        return self._traces["decode"]

    @property
    def mixed_trace_count(self) -> int:
        return self._traces["mixed"]

    def has_work(self) -> bool:
        return (
            bool(self._queue) or self.num_active > 0
            or bool(self._shed_results)
        )

    @property
    def draining(self) -> bool:
        """True once `drain()` was called: admission is closed."""
        return self._draining

    @property
    def tick_count(self) -> int:
        """Engine ticks so far — the `FaultPlan` tick domain."""
        return self._tick

    @property
    def completions(self) -> List[Dict[str, float]]:
        """Per-request completion records, one dict per finished
        request in finish order: ``request_id``, ``finish_reason``,
        ``prompt_tokens``, ``new_tokens``, ``chunks`` (mixed ticks
        that carried this prompt; 1 on the whole-prompt path),
        ``queue_wait_ms`` (enqueue → slot lease), ``ttft_ms``
        (enqueue → first token — the SAME values whose percentiles
        ``stats()`` reports), ``tpot_ms`` (mean inter-token time after
        the first), ``e2e_ms``. Jsonl-ready: route through
        `monitor.JsonlWriter.emit` (``bench.py serve --trace`` and
        ``examples/generate_gpt.py --trace`` do). Cleared by
        `reset_stats`; retention is capped at ``stats_retention``
        records (oldest drop) — the registry counters/histograms keep
        the full-traffic accounting in constant memory."""
        return list(self._completions)

    # -- telemetry recording (host-side only; one registry `enabled`
    # -- check per sample, the NULL_TRACER discipline) ----------------

    def _record_queue_wait(self, seconds: float) -> None:
        self._queue_waits.append(seconds)
        if self.registry.enabled:
            self._h_queue_wait.observe(1e3 * seconds)

    def _tenant_series(self, tenant: Optional[str]) -> str:
        """Metric label for a tenant, honoring ``max_label_sets``: the
        first sighting tries to create the tenant's series; once the
        registry cap trips, that tenant maps to the pre-created
        ``other`` overflow label forever. The serving hot path never
        raises `CardinalityError` — a tenant beyond the cap still has
        every token and TTFT accounted, just under ``other``."""
        if tenant is None:
            tenant = "base"
        if tenant in self._tenant_label_ok:
            return tenant
        if tenant in self._tenant_overflowed:
            return "other"
        from rocm_apex_tpu.monitor.telemetry import CardinalityError

        try:
            # the token family first: two series per tenant, so it
            # trips the cap before the single-series TTFT family
            self._c_tokens.labels(phase="prompt", tenant=tenant)
            self._c_tokens.labels(phase="generated", tenant=tenant)
            self._h_ttft.labels(tenant=tenant)
        except CardinalityError:
            self._tenant_overflowed.add(tenant)
            return "other"
        self._tenant_label_ok.add(tenant)
        return tenant

    def _record_ttft(
        self, seconds: float, tenant: Optional[str] = None
    ) -> None:
        self._ttfts.append(seconds)
        if self.registry.enabled:
            if self._per_tenant:
                self._h_ttft.observe(
                    1e3 * seconds, tenant=self._tenant_series(tenant)
                )
            else:
                self._h_ttft.observe(1e3 * seconds)

    def _record_completion(self, rec: Dict[str, float]) -> None:
        self._completions.append(rec)
        tenant = rec.get("tenant")
        if self.adapter_pool is not None:
            # host-side per-tenant accounting keyed by the TRUE tenant
            # name (never collapsed to "other"): the chaos isolation
            # identity sums these against the global counters
            tc = self._tenant_counts.setdefault(
                tenant or "base",
                {"completed": 0, "prompt_tokens": 0,
                 "generated_tokens": 0},
            )
            tc["completed"] += 1
            tc["prompt_tokens"] += int(rec["prompt_tokens"])
            tc["generated_tokens"] += int(rec["new_tokens"])
        if self.registry.enabled:
            self._c_completions.inc(
                finish_reason=rec["finish_reason"]
            )
            if self._per_tenant:
                label = self._tenant_series(tenant)
                self._c_tokens.inc(
                    rec["prompt_tokens"], phase="prompt", tenant=label
                )
                self._c_tokens.inc(
                    rec["new_tokens"], phase="generated", tenant=label
                )
            else:
                self._c_tokens.inc(
                    rec["prompt_tokens"], phase="prompt"
                )
                self._c_tokens.inc(
                    rec["new_tokens"], phase="generated"
                )
            self._h_e2e.observe(rec["e2e_ms"])
            if rec["new_tokens"] > 1:
                self._h_tpot.observe(rec["tpot_ms"])

    def tenant_stats(self) -> Dict[str, Dict[str, int]]:
        """Host-side per-tenant completion accounting (true tenant
        names — unlike the labeled metric families, never collapsed
        into ``other``): tenant -> {completed, prompt_tokens,
        generated_tokens}. Empty on engines without an adapter pool.
        The per-tenant sums partition the global counters: summing
        ``completed`` across tenants equals the completion-record
        count, and likewise for both token phases."""
        return {t: dict(c) for t, c in self._tenant_counts.items()}

    def stats(self) -> Dict[str, float]:
        """Serving telemetry as one flat name→scalar dict — the
        `monitor.MetricsLogger.log_step` input format (route the
        monotonic counters through its ``last_value`` set).

        Gauges: ``queue_depth``, ``slots_active``, ``slot_occupancy``.
        Counters: ``admitted``, ``evicted``, ``prompt_tokens``,
        ``generated_tokens``, ``decode_steps``, ``mixed_steps``.
        Derived: mean latency per prefill-carrying tick
        (``prefill_ms_avg`` — a whole-prompt admit in legacy mode, a
        mixed chunk+decode tick in chunked mode), mean decode-only
        tick latency, and tokens/sec over each phase's accumulated
        wall time. Per-request distributions: ``queue_wait_ms_p50/95``
        (enqueue → slot lease) and ``ttft_ms_p50/95`` (enqueue →
        first token) — the tails that surface head-of-line blocking,
        which the averages above hide.

        Stats memory is O(1): raw per-request samples are retained up
        to ``stats_retention`` (default 4096, oldest drop) and the
        percentiles are EXACT over them; once traffic exceeds the cap,
        percentiles switch to the engine registry's constant-memory
        log-bucket histograms (``serve_queue_wait_ms`` /
        ``serve_ttft_ms``), whose quantile estimates carry the
        documented relative error bound
        ``monitor.telemetry.Histogram.error_bound`` (~26% hard bound
        at 20 buckets/decade; typically <2% interpolated — see
        docs/observability.md "Telemetry & SLOs"). With a disabled
        registry (``monitor.NULL_REGISTRY``) the capped rings are the
        only source and percentiles describe the newest
        ``stats_retention`` requests.

        Paged-cache occupancy (zeros on the contiguous engine):
        ``pages_total``/``pages_used``/``page_occupancy`` (pages
        holding a live mapping — THE memory-win witness: it scales
        with live tokens, not slots × capacity), ``shared_page_ratio``
        (mapped table entries pointing at ref>1 pages),
        ``cow_forks``, ``prefix_hits``/``prefix_hit_tokens`` (admits
        that skipped re-prefilling a stored prefix, and the tokens
        skipped), ``page_stalls`` (tokens deferred by pool
        backpressure), ``preemptions`` (slots whose pages were
        reclaimed under pool deadlock — the request recomputes via
        chunked prefill on re-admission), ``page_ships`` /
        ``page_ship_fallbacks`` (migrations that landed their KV
        payload directly vs fell back to token replay).

        Speculative decoding (zeros at ``spec_k == 0``):
        ``tokens_drafted``/``tokens_accepted`` (drafter proposals
        scheduled into the chunk vs. proposals the verify step
        emitted), ``acceptance_rate`` (their ratio), ``rollbacks``
        (spans with at least one rejected draft). Every drafted token
        is one or the other: ``drafted - accepted`` is exactly the
        rolled-back row count."""
        prefill_ticks = (
            self._mixed_steps if self.chunked else self._admitted
        )
        prefill_ms = (
            1e3 * self._prefill_seconds / prefill_ticks
            if prefill_ticks else 0.0
        )
        decode_ms = (
            1e3 * self._decode_seconds / self._decode_steps
            if self._decode_steps else 0.0
        )
        decode_generated = self._generated_tokens - self._admitted

        def _pct_ms(ring, hist, q):
            # exact percentile while the capped ring still holds every
            # sample; bounded-error histogram quantile once it wrapped
            if self.registry.enabled and hist.count() > len(ring):
                return float(hist.percentile(q))
            if not ring:
                return 0.0
            return 1e3 * float(np.percentile(np.asarray(ring), q))

        # page-occupancy counters (zeros when not paged, so one
        # MetricsLogger schema serves both engines)
        pages_total = float(self.cache.num_pages) if self.paged else 0.0
        pages_used = (
            float(self._allocator.pages_used) if self.paged else 0.0
        )
        shared_ratio = 0.0
        if self.paged:
            sentinel = self.cache.num_pages
            mapped = self._table[self._table != sentinel]
            if mapped.size:
                shared = sum(
                    1 for p in mapped
                    if self._allocator.refcount(int(p)) > 1
                )
                shared_ratio = shared / mapped.size
        paged_stats = {
            "pages_total": pages_total,
            "pages_used": pages_used,
            "page_occupancy": (
                pages_used / pages_total if pages_total else 0.0
            ),
            "shared_page_ratio": shared_ratio,
            "cow_forks": float(self._cow_forks),
            "prefix_hits": float(self._prefix_hits),
            "prefix_hit_tokens": float(self._prefix_hit_tokens),
            "page_stalls": float(self._page_stalls),
            "preemptions": float(self._preemptions),
            "page_ships": float(self._page_ships),
            "page_ship_fallbacks": float(self._page_ship_fallbacks),
        }
        # multi-LoRA pool economics (zeros without an adapter pool):
        # uploads/evictions/revivals witness the park-reclaim cycle,
        # adapter_stalls counts admission skips under residency
        # backpressure, tier_* the SLO-driven admission actions
        if self.adapter_pool is not None:
            snap = self.adapter_pool.snapshot()
            adapter_stats = {
                "adapters_registered": float(snap["registered"]),
                "adapters_resident": float(snap["resident"]),
                "adapter_uploads": float(snap["uploads"]),
                "adapter_evictions": float(snap["evictions"]),
                "adapter_revivals": float(snap["revivals"]),
            }
        else:
            adapter_stats = {
                "adapters_registered": 0.0,
                "adapters_resident": 0.0,
                "adapter_uploads": 0.0,
                "adapter_evictions": 0.0,
                "adapter_revivals": 0.0,
            }
        adapter_stats.update(
            adapter_stalls=float(self._adapter_stalls),
            tier_preemptions=float(self._tier_preemptions),
            tier_sheds=float(self._tier_sheds),
        )
        return {
            **paged_stats,
            **adapter_stats,
            # robustness counters (docs/inference.md "Failure
            # semantics"): every lifecycle transition is accounted —
            # completed + shed + quarantined + cancelled + expired
            # equals submitted, never a silent drop
            "cancelled": float(self._cancelled),
            "deadline_exceeded": float(self._deadline_exceeded),
            "quarantined": float(self._quarantined),
            "step_retries": float(self._step_retries),
            "shed": float(self._shed),
            "watchdog_fires": float(self._watchdog_fires),
            "evacuated": float(self._evacuated),
            "tokens_drafted": float(self._tokens_drafted),
            "tokens_accepted": float(self._tokens_accepted),
            "acceptance_rate": (
                self._tokens_accepted / self._tokens_drafted
                if self._tokens_drafted else 0.0
            ),
            "rollbacks": float(self._rollbacks),
            "queue_depth": float(self.num_queued),
            "slots_active": float(self.num_active),
            "slot_occupancy": self.num_active / self.num_slots,
            "admitted": float(self._admitted),
            "evicted": float(self._evicted),
            "prompt_tokens": float(self._prompt_tokens),
            "generated_tokens": float(self._generated_tokens),
            "decode_steps": float(self._decode_steps),
            "mixed_steps": float(self._mixed_steps),
            "prefill_ms_avg": prefill_ms,
            "decode_ms_avg": decode_ms,
            "prefill_tokens_per_sec": (
                self._prompt_tokens / self._prefill_seconds
                if self._prefill_seconds > 0 else 0.0
            ),
            "decode_tokens_per_sec": (
                decode_generated / self._decode_seconds
                if self._decode_seconds > 0 else 0.0
            ),
            "queue_wait_ms_p50": _pct_ms(
                self._queue_waits, self._h_queue_wait, 50
            ),
            "queue_wait_ms_p95": _pct_ms(
                self._queue_waits, self._h_queue_wait, 95
            ),
            "ttft_ms_p50": _pct_ms(self._ttfts, self._h_ttft, 50),
            "ttft_ms_p95": _pct_ms(self._ttfts, self._h_ttft, 95),
        }

    def reset_stats(self) -> None:
        """Zero the telemetry counters and per-request distributions.
        Compiled programs, trace counters, and cache state are
        untouched — benchmarks warm the compiles up on the same engine,
        then measure a clean window."""
        self._admitted = 0
        self._evicted = 0
        self._prompt_tokens = 0
        self._generated_tokens = 0
        self._prefill_seconds = 0.0
        self._decode_seconds = 0.0
        self._decode_steps = 0
        self._mixed_steps = 0
        self._queue_waits.clear()
        self._ttfts.clear()
        self._completions.clear()
        # zero the ENGINE's registry series in place (a shared
        # registry's other families are untouched)
        if self.registry.enabled:
            for metric in (
                self._h_queue_wait, self._h_ttft, self._h_tpot,
                self._h_e2e, self._c_completions, self._c_tokens,
                self._g_queue_depth, self._g_slots_active,
            ):
                metric.clear()
            if self._per_tenant:
                # clear() dropped every tenant series, including the
                # pre-created overflow — rebuild the overflow series
                # and forget the sighting sets so re-creation replays
                # the same cap-honoring first-sighting protocol
                self._c_tokens.labels(phase="prompt", tenant="other")
                self._c_tokens.labels(phase="generated", tenant="other")
                self._h_ttft.labels(tenant="other")
                self._tenant_label_ok = {"other"}
                self._tenant_overflowed = set()
        self._cow_forks = 0
        self._prefix_hits = 0
        self._prefix_hit_tokens = 0
        self._page_stalls = 0
        self._preemptions = 0
        self._page_ships = 0
        self._page_ship_fallbacks = 0
        self._tokens_drafted = 0
        self._tokens_accepted = 0
        self._rollbacks = 0
        self._cancelled = 0
        self._deadline_exceeded = 0
        self._quarantined = 0
        self._step_retries = 0
        self._shed = 0
        self._watchdog_fires = 0
        self._evacuated = 0
        self._adapter_stalls = 0
        self._tier_preemptions = 0
        self._tier_sheds = 0
        self._tenant_counts.clear()
        # the watchdog's progress snapshot tracks counters just zeroed
        self._progress_mark = (0, 0, 0)
        self._last_progress = time.perf_counter()
        if self.retrace_sentinel is not None:
            # reset_stats() IS the bench contract's warmed-up-now
            # marker (warm generate(), reset, measure a clean window)
            # — arm the sentinel here: compiles from now on are the
            # retraces the one-compiled-trace invariant forbids
            self.retrace_sentinel.arm()

    def cache_bytes(self) -> int:
        """Device bytes held by the KV cache (pools/buffers + scales +
        tables + lengths — every leaf of the cache pytree). The paged
        A/B's memory line: contiguous = slots × capacity rows up
        front; paged = the page pool you sized (int8 ~halves it)."""
        return sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(self.cache)
        )

    def add_request(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        request_id: Optional[int] = None,
        *,
        timeout: Optional[float] = None,
        queue_ttl: Optional[float] = None,
        adapter_id: int = 0,
        tenant: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        """Queue a prompt; returns the request id. The request is
        admitted into a cache slot by a later `step` when a slot is
        free; its prompt then streams through the prefill budget. The
        only length bound is the physical cache: a prompt must fit in
        ``capacity`` rows. (The legacy whole-prompt path additionally
        needs the prompt to fit its ``max_prompt_len`` pad width.)

        ``timeout`` (seconds) is the request's END-TO-END deadline —
        queue wait included — and ``queue_ttl`` bounds the queue wait
        alone; both are checked at tick boundaries and expire the
        request with ``finish_reason='deadline'`` (in-flight work is
        torn down through the ordinary eviction path, so pages and
        slots are released correctly).

        With ``max_queue`` set, a request arriving at a full queue is
        SHED, never silently dropped: it still gets an id, a
        ``queue_full`` result is delivered by the next `step()` (so
        `generate` callers see it), and the ``shed`` counter ticks.
        After `drain()` admission is closed and this raises.

        ``adapter_id`` selects a LoRA adapter registered in the
        engine's `AdapterPool` (0 = base model, always valid); the
        request's ``tenant`` defaults to the adapter's registered
        tenant and labels its telemetry. On a full queue with an
        adapter pool, shedding is TIER-AWARE: an arrival outranking
        the lowest-tier queued request sheds that victim (newest
        within the tier) instead of itself — paying tenants keep
        their queue positions under overload (``tier_sheds``)."""
        if self._draining:
            raise RuntimeError(
                "engine is draining: admission is closed "
                "(drain() was called)"
            )
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if len(prompt) > self.capacity:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the cache "
                f"capacity {self.capacity} (rows per slot)"
            )
        if not self.chunked and len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the whole-prompt "
                f"pad width max_prompt_len={self.max_prompt_len}; the "
                f"default chunked engine (prefill_token_budget) "
                f"streams prompts of any length"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if timeout is not None and timeout <= 0:
            raise ValueError(f"timeout must be > 0 s, got {timeout}")
        if queue_ttl is not None and queue_ttl <= 0:
            raise ValueError(f"queue_ttl must be > 0 s, got {queue_ttl}")
        adapter_id = int(adapter_id)
        if adapter_id != 0:
            if self.adapter_pool is None:
                raise ValueError(
                    f"adapter_id={adapter_id} but the engine has no "
                    f"adapter_pool"
                )
            if not self.adapter_pool.known(adapter_id):
                raise KeyError(f"unknown adapter_id {adapter_id}")
        if tenant is None and self.adapter_pool is not None:
            tenant = self.adapter_pool.tenant_of(adapter_id)
        if request_id is None:
            request_id = self._next_id
        self._next_id = max(self._next_id, request_id) + 1
        # fleet-causal context: mint at first admission, carry a
        # caller-supplied id verbatim (the router mints once per
        # admitted request and every hop re-presents the same id)
        if trace_id is None:
            trace_id = mint_trace_id()
        now = time.perf_counter()
        if (
            self.max_queue is not None
            and len(self._queue) >= self.max_queue
        ):
            # bounded admission: shed-NEWEST (the queued requests keep
            # their positions — fairness under overload), accounted in
            # the completion records and delivered as a queue_full
            # result through the next step(). With an adapter pool the
            # shed is TIER-AWARE: when the arrival outranks the
            # lowest-tier queued request, THAT victim (newest within
            # its tier) is shed instead and the arrival takes its
            # place at the tail.
            victim_req, victim_idx = None, None
            if self.adapter_pool is not None:
                inc_tier = self.adapter_pool.tier_of(adapter_id)
                min_tier, min_idx = inc_tier, None
                for i, q in enumerate(self._queue):
                    t = self.adapter_pool.tier_of(q.adapter_id)
                    if t <= min_tier and t < inc_tier:
                        min_tier, min_idx = t, i
                if min_idx is not None:
                    victim_idx = min_idx
                    victim_req = self._queue[min_idx]
            if victim_req is not None:
                del self._queue[victim_idx]
                self._tier_sheds += 1
                shed_id = victim_req.request_id
                shed_prompt = victim_req.prompt
                shed_tenant = victim_req.tenant
                shed_trace = victim_req.trace_id
            else:
                shed_id, shed_prompt, shed_tenant, shed_trace = (
                    request_id, prompt, tenant, trace_id
                )
            self._shed += 1
            self._record_completion({
                "request_id": shed_id,
                "finish_reason": "queue_full",
                "prompt_tokens": len(shed_prompt),
                "new_tokens": 0,
                "chunks": 0,
                "queue_wait_ms": 0.0,
                "ttft_ms": 0.0,
                "tpot_ms": 0.0,
                "e2e_ms": 0.0,
                "tenant": shed_tenant,
            })
            self._shed_results.append(GenerationResult(
                request_id=shed_id, prompt=list(shed_prompt),
                tokens=[], finish_reason="queue_full",
            ))
            if self.tracer.enabled:
                self.tracer.instant(
                    "shed", ts=now, track=f"req{shed_id}",
                    queue_depth=len(self._queue),
                    request_id=shed_id, trace_id=shed_trace,
                )
            if victim_req is None:
                return request_id
        req = Request(
            request_id, prompt, max_new_tokens,
            enqueued_at=now,
            deadline=(now + timeout) if timeout is not None else None,
            queue_deadline=(
                (now + queue_ttl) if queue_ttl is not None else None
            ),
            adapter_id=adapter_id,
            tenant=tenant,
            trace_id=trace_id,
        )
        self._queue.append(req)
        if self.tracer.enabled:
            self.tracer.instant(
                "enqueue", ts=req.enqueued_at,
                track=f"req{request_id}",
                prompt_tokens=len(prompt), max_new_tokens=max_new_tokens,
                request_id=request_id, trace_id=trace_id,
            )
        return request_id

    def step(self) -> List[GenerationResult]:
        """One engine tick. Chunked mode: admit queued requests into
        free slots (bookkeeping only), pack up to the token budget of
        pending prompt tokens, and run ONE compiled mixed
        chunk+decode step (decode-only fast path when nothing is
        prefilling). Legacy mode: one compiled whole-prompt prefill
        per admit, then the decode step. Returns the requests that
        finished this tick (their slots are already free for the
        next) — including any shed (``queue_full``) and expired
        (``deadline``) requests, so every submitted request yields
        exactly one result."""
        now = time.perf_counter()
        self._check_watchdog(now)
        out: List[GenerationResult] = []
        if self._shed_results:
            out.extend(self._shed_results)
            self._shed_results = []
        out.extend(self._expire_deadlines(now))
        if self.chunked:
            out.extend(self._step_chunked())
        else:
            out.extend(self._step_whole())
        self._tick += 1
        self._note_progress()
        if self.registry.enabled:
            # live occupancy gauges for the async /metrics scrape
            # (host-side sets; the compiled programs are untouched)
            self._g_queue_depth.set(self.num_queued)
            self._g_slots_active.set(self.num_active)
        if self.timeseries is not None:
            self.timeseries.tick()
        if self.retrace_sentinel is not None:
            # tick-boundary enforcement — under policy="raise" a
            # post-warmup compile fails HERE, never inside the jax
            # callback mid-compile
            self.retrace_sentinel.check()
        return out

    def cancel(self, request_id: int) -> Optional[GenerationResult]:
        """Cancel one request, wherever it is in its lifecycle, and
        return its partial result (``finish_reason='cancelled'``, the
        tokens generated so far) — or None if the id is unknown or
        already finished. In-flight work tears down through the
        ordinary eviction path, so the slot frees and its pages
        release with the PR-7 allocator invariants intact (CoW
        refcounts drop, store-registered prefix pages park). A
        preempted request's carried tokens are returned too. Host
        bookkeeping only — the compiled programs never see a cancel
        (the next tick simply runs without the slot)."""
        now = time.perf_counter()
        for req in self._queue:
            if req.request_id == request_id:
                self._queue.remove(req)
                self._cancelled += 1
                return self._finalize_queued(req, "cancelled", now)
        for slot, st in enumerate(self._slots):
            if st is not None and st.req.request_id == request_id:
                self._cancelled += 1
                if self.tracer.enabled:
                    self.tracer.instant(
                        "cancel", ts=now, track=f"req{request_id}",
                        slot=slot, generated=len(st.generated),
                        request_id=request_id,
                        trace_id=st.req.trace_id,
                    )
                return self._evict(slot, st, "cancelled")
        return None

    def drain(self, shed_queue: bool = False) -> List[GenerationResult]:
        """Graceful shutdown: close admission (`add_request` raises
        from here on), run the engine until all accepted work
        finishes, and return those results. ``shed_queue=True``
        additionally cancels the still-QUEUED requests up front
        (finish_reason ``cancelled``) so only the in-flight slots run
        to completion — the SIGTERM fast path. Stats counters and
        tracer events are all emitted by the time this returns; the
        caller flushes them (``stats()`` / ``export_chrome_trace``).
        Bounded by the stall watchdog like any other stepping.

        Idempotent: a second drain on an already-draining (or already
        drained) engine just runs any remaining work dry and returns
        those results — no error, no duplicate drain markers — so a
        supervisor and a signal handler can both call it. The return
        path is `reopen()`."""
        already = self._draining
        self._draining = True
        now = time.perf_counter()
        if self.tracer.enabled and not already:
            self.tracer.instant(
                "drain_begin", ts=now, track="engine",
                queued=self.num_queued, active=self.num_active,
            )
        out: List[GenerationResult] = []
        if shed_queue:
            while self._queue:
                req = self._queue.popleft()
                self._cancelled += 1
                out.append(self._finalize_queued(req, "cancelled", now))
        while self.has_work():
            out.extend(self.step())
        if self.tracer.enabled and not already:
            self.tracer.instant(
                "drain_end", track="engine", finished=len(out),
            )
        return out

    def reopen(self) -> None:
        """Rejoin after `drain()` or a quarantine: reset the lifecycle
        latches (drain flag, watchdog-fire count, progress anchors) so
        admission reopens on the SAME engine — compiled programs,
        cache, and prefix store survive, nothing retraces. The state
        must be provably clean or this raises `RuntimeError`: no
        leased slot, empty queue, no preempted carryover, no
        undelivered shed results, and (paged) an all-sentinel block
        table with the allocator's free-list/refcount invariants
        intact. Callers that want the clean state first use
        `evacuate()` / `drain()`; parked prefix pages are FINE — they
        are the reusable prefix cache, not a leak."""
        dirty = []
        if any(st is not None for st in self._slots):
            dirty.append(f"{self.num_active} leased slot(s)")
        if self._queue:
            dirty.append(f"{len(self._queue)} queued request(s)")
        if self._preempted:
            dirty.append(
                f"{len(self._preempted)} preempted carryover(s)"
            )
        if self._shed_results:
            dirty.append(
                f"{len(self._shed_results)} undelivered shed result(s)"
            )
        if self.paged:
            sentinel = self.cache.num_pages
            mapped = int((self._table != sentinel).sum())
            if mapped:
                dirty.append(f"{mapped} mapped page-table entries")
        if dirty:
            raise RuntimeError(
                "reopen() on a dirty engine: " + ", ".join(dirty)
                + " — drain() or evacuate() first"
            )
        if self.paged:
            # the allocator's own invariants (free-list / refcounts /
            # parked set) must hold before we accept traffic again
            self._allocator.assert_consistent()
        self._draining = False
        self._watchdog_fires = 0
        self._progress_mark = (
            self._prompt_tokens, self._generated_tokens, self._evicted,
        )
        self._last_progress = time.perf_counter()
        if self.tracer.enabled:
            self.tracer.instant("reopen", track="engine")

    def outstanding(self) -> List[Dict[str, Any]]:
        """Snapshot of every request this engine currently OWNS —
        in-flight slots (slot order), then the queue (queue order) —
        as migration records: ``request_id``, ``prompt``,
        ``max_new_tokens``, ``generated`` (tokens emitted so far),
        ``enqueued_at``/``deadline``/``queue_deadline`` (absolute
        perf_counter times), ``first_token_at``, ``chunks``. A
        prompt + its ``generated`` tokens IS the request's migration
        format (the vLLM recompute transition): feed a record to
        another engine's `resume_request` and greedy decode continues
        token-identically. Pure read — engine state is untouched."""
        recs: List[Dict[str, Any]] = []

        def _rec(req: Request, generated, first_at, chunks):
            recs.append({
                "request_id": req.request_id,
                "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "generated": list(generated),
                "enqueued_at": req.enqueued_at,
                "deadline": req.deadline,
                "queue_deadline": req.queue_deadline,
                "first_token_at": first_at,
                "chunks": chunks,
                "adapter_id": req.adapter_id,
                "tenant": req.tenant,
                "trace_id": req.trace_id,
            })

        for st in self._slots:
            if st is not None:
                _rec(st.req, st.generated, st.first_token_at, st.chunks)
        for req in self._queue:
            carried = self._preempted.get(req.request_id)
            if carried is not None:
                _rec(req, carried[0], carried[1], carried[2])
            else:
                _rec(req, [], 0.0, 0)
        return recs

    def evacuate(self, ship_pages: bool = False) -> List[Dict[str, Any]]:
        """Hand EVERY owned request off for migration: snapshot
        `outstanding()`, then release all slots and pages and empty
        the queue, leaving the engine provably clean for `reopen()`.
        The records are returned to the caller (the router), which
        re-owns their delivery — no completion is recorded here, so a
        migrated request still finishes exactly once, on whichever
        engine ultimately runs it. Store-registered prefix pages park
        (they remain a valid cross-request cache); private pages
        free. Host bookkeeping only — except with ``ship_pages=True``
        on a paged cache, where each slot-held record additionally
        carries its materialized KV page blocks (``rec["pages"]``, the
        `_export_slot_pages` payload): feed the whole record to another
        engine's `resume_request(pages=...)` and the destination skips
        the recompute prefill, token-identically."""
        recs = self.outstanding()
        by_id = {rec["request_id"]: rec for rec in recs}
        for slot in range(self.num_slots - 1, -1, -1):
            st = self._slots[slot]
            if st is None:
                continue
            if self.paged:
                if ship_pages:
                    payload = self._export_slot_pages(st, slot)
                    if payload is not None:
                        by_id[st.req.request_id]["pages"] = payload
                self._release_slot_pages(st, slot)
            self._release_adapter(st)
            self._slots[slot] = None
            if self.tracer.enabled:
                self.tracer.instant(
                    "evacuate", track=f"req{st.req.request_id}",
                    slot=slot, generated=len(st.generated),
                    request_id=st.req.request_id,
                    trace_id=st.req.trace_id,
                )
        if self.paged:
            self._push_table()
        self._queue.clear()
        self._preempted.clear()
        self._shipped.clear()
        self._evacuated += len(recs)
        return recs

    def evacuate_request(
        self, request_id: int, ship_pages: bool = False,
    ) -> Optional[Dict[str, Any]]:
        """Hand off ONE owned request (the disaggregation handoff
        primitive: a prefill-class replica evacuates a request the
        moment its prompt is materialized and the router re-lands it on
        a decode-class replica). Same contract as `evacuate()` scoped
        to a single request: the returned record — with its KV pages
        attached when ``ship_pages`` and the request holds a slot — is
        the caller's to deliver; this engine forgets the request
        entirely. Returns None when the request is not owned here."""
        for slot in range(self.num_slots):
            st = self._slots[slot]
            if st is None or st.req.request_id != request_id:
                continue
            rec: Dict[str, Any] = {
                "request_id": st.req.request_id,
                "prompt": list(st.req.prompt),
                "max_new_tokens": st.req.max_new_tokens,
                "generated": list(st.generated),
                "enqueued_at": st.req.enqueued_at,
                "deadline": st.req.deadline,
                "queue_deadline": st.req.queue_deadline,
                "first_token_at": st.first_token_at,
                "chunks": st.chunks,
                "adapter_id": st.req.adapter_id,
                "tenant": st.req.tenant,
                "trace_id": st.req.trace_id,
            }
            if self.paged:
                if ship_pages:
                    payload = self._export_slot_pages(st, slot)
                    if payload is not None:
                        rec["pages"] = payload
                self._release_slot_pages(st, slot)
                self._push_table()
            self._release_adapter(st)
            self._slots[slot] = None
            self._evacuated += 1
            if self.tracer.enabled:
                self.tracer.instant(
                    "evacuate", track=f"req{request_id}",
                    slot=slot, generated=len(st.generated),
                    request_id=request_id,
                    trace_id=st.req.trace_id,
                )
            return rec
        for i, req in enumerate(self._queue):
            if req.request_id != request_id:
                continue
            carried = self._preempted.pop(request_id, None)
            generated, first_at, chunks = carried or ([], 0.0, 0)
            del self._queue[i]
            self._shipped.pop(request_id, None)
            self._evacuated += 1
            return {
                "request_id": req.request_id,
                "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "generated": list(generated),
                "enqueued_at": req.enqueued_at,
                "deadline": req.deadline,
                "queue_deadline": req.queue_deadline,
                "first_token_at": first_at,
                "chunks": chunks,
                "adapter_id": req.adapter_id,
                "tenant": req.tenant,
                "trace_id": req.trace_id,
            }
        return None

    def resume_request(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        request_id: int,
        *,
        generated: Sequence[int] = (),
        enqueued_at: Optional[float] = None,
        deadline: Optional[float] = None,
        queue_deadline: Optional[float] = None,
        first_token_at: float = 0.0,
        chunks: int = 0,
        pages: Optional[Dict[str, Any]] = None,
        adapter_id: int = 0,
        tenant: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        """Admit a request MIGRATED from another engine, carrying the
        tokens it already emitted (an `outstanding()`/`evacuate()`
        record). Re-admission recomputes prompt + generated[:-1]
        through the ordinary chunked prefill — the PR-8 preemption
        carryover — so greedy decode continues bitwise-identically
        and no carried token is ever re-emitted. Deadlines are
        ABSOLUTE (same perf_counter domain): a migrated request keeps
        its original SLA clock. Unlike `add_request`, a full queue
        never sheds a resumed request — it was already admitted once;
        shedding it here would double-account it.

        ``pages`` (a record's ``rec["pages"]`` from
        ``evacuate(ship_pages=True)``) upgrades the resume to
        page-shipping: when the request leases a slot, the payload's
        KV blocks land directly in this engine's pool and the prefill
        cursor starts past them — only the final prefix token recomputes.
        The payload is best-effort: if it cannot be imported (geometry
        mismatch, pool pressure, or an injected ``page_ship`` fault)
        admission silently falls back to the token-replay path above,
        with identical greedy output."""
        if self._draining:
            raise RuntimeError(
                "engine is draining: admission is closed "
                "(drain() was called)"
            )
        prompt = [int(t) for t in prompt]
        generated = [int(t) for t in generated]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if len(prompt) > self.capacity:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the cache "
                f"capacity {self.capacity} (rows per slot)"
            )
        if generated and not self.chunked:
            raise ValueError(
                "resume with carried tokens needs the chunked engine "
                "(prefill_token_budget): the recompute prefix "
                "prompt + generated[:-1] streams through the budget"
            )
        if len(generated) >= max_new_tokens:
            raise ValueError(
                f"carried {len(generated)} tokens >= max_new_tokens="
                f"{max_new_tokens}: the request already finished"
            )
        adapter_id = int(adapter_id)
        if adapter_id != 0:
            if self.adapter_pool is None:
                raise ValueError(
                    f"adapter_id={adapter_id} but the engine has no "
                    f"adapter_pool"
                )
            if not self.adapter_pool.known(adapter_id):
                raise KeyError(f"unknown adapter_id {adapter_id}")
        if tenant is None and self.adapter_pool is not None:
            tenant = self.adapter_pool.tenant_of(adapter_id)
        now = time.perf_counter()
        self._next_id = max(self._next_id, request_id) + 1
        # carry the hop's trace context verbatim; mint only if this
        # request was never traced (a bare resume outside the router)
        if not trace_id:
            trace_id = mint_trace_id()
        req = Request(
            request_id, prompt, max_new_tokens,
            enqueued_at=enqueued_at if enqueued_at is not None else now,
            deadline=deadline,
            queue_deadline=queue_deadline,
            adapter_id=adapter_id,
            tenant=tenant,
            trace_id=trace_id,
        )
        if generated:
            self._preempted[request_id] = (
                list(generated), first_token_at or now, int(chunks),
            )
        if pages is not None and self.paged:
            self._shipped[request_id] = pages
        self._queue.append(req)
        if self.tracer.enabled:
            self.tracer.instant(
                "resume", ts=now, track=f"req{request_id}",
                carried=len(generated),
                request_id=request_id, trace_id=trace_id,
            )
        return request_id

    def prefix_match_tokens(self, prompt: Sequence[int]) -> int:
        """How many of ``prompt``'s tokens this engine's `PrefixStore`
        already holds materialized (0 without prefix sharing). Pure
        read — the router's prefix-affinity signal: route a prompt to
        the replica that can skip the most prefill."""
        if self._store is None:
            return 0
        return self._store.match([int(t) for t in prompt])[1]

    @property
    def pages_used(self) -> int:
        """Pages holding a live mapping (0 on the contiguous cache) —
        the memory-pressure term of least-loaded placement."""
        return int(self._allocator.pages_used) if self.paged else 0

    @property
    def progress_marker(self) -> Tuple[int, int, int]:
        """(prompt_tokens, generated_tokens, evicted) — the same
        signals the stall watchdog watches, for an EXTERNAL
        zero-progress detector (the router's stall probe)."""
        return (
            self._prompt_tokens, self._generated_tokens, self._evicted,
        )

    #: consecutive zero-progress ticks `generate()` tolerates before
    #: diagnosing a stall (a backstop when no wall-clock watchdog is
    #: configured; page-stall backpressure either recovers within a
    #: tick or two or raises the pool-deadlock diagnosis long before)
    _GENERATE_STALL_TICKS = 1000

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
    ) -> List[GenerationResult]:
        """Convenience batch API: queue every prompt, run the serving
        loop dry, return results in prompt order. The loop is BOUNDED:
        the engine's wall-clock watchdog (``watchdog_timeout``) fires
        through `step()`, and even without one a run of
        ``_GENERATE_STALL_TICKS`` consecutive ticks with no token
        progress and nothing finished raises a diagnostic RuntimeError
        naming the stuck slot(s) instead of spinning forever."""
        ids = [self.add_request(p, max_new_tokens) for p in prompts]
        done = {}
        stale = 0
        mark = (
            self._prompt_tokens, self._generated_tokens, self._evicted,
        )
        while self.has_work():
            results = self.step()
            for r in results:
                done[r.request_id] = r
            work = (
                self._prompt_tokens, self._generated_tokens,
                self._evicted,
            )
            if results or work != mark:
                stale, mark = 0, work
                continue
            stale += 1
            if stale >= self._GENERATE_STALL_TICKS:
                raise RuntimeError(
                    f"generate() stalled: {stale} consecutive ticks "
                    f"without token progress; {self._stall_diagnosis()}"
                    f" (set watchdog_timeout for a wall-clock bound)"
                )
        return [done[i] for i in ids]

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    # -- paged-cache host bookkeeping ----------------------------------

    def _page_registered(self, page: int) -> bool:
        return self._store is not None and self._store.is_registered(page)

    def _map_page(self, slot: int, idx: int, page: int) -> None:
        self._table[slot, idx] = page
        self._table_dirty = True

    def _push_table(self) -> None:
        """Sync the host page-table mirror to the device pytree (once
        per tick, only when the mapping changed)."""
        if self._table_dirty:
            table = jnp.asarray(self._table)
            if self._mesh is not None:
                # keep the replacement on the mesh layout (replicated)
                # so the step pytree never mixes device assignments
                table = jax.device_put(
                    table,
                    jax.sharding.NamedSharding(
                        self._mesh, jax.sharding.PartitionSpec()
                    ),
                )
            self.cache = self.cache.replace(page_table=table)
            self._table_dirty = False

    def _export_slot_pages(self, st: _Slot, slot: int):
        """Snapshot the slot's mapped KV pages as a migration payload —
        the pool IS the transfer format. One batched host fetch pulls
        the per-layer page blocks (and int8 scale rows) for the pages
        covering ``st.pos`` materialized rows; the payload plus the
        `outstanding()` record is everything a destination engine needs
        to resume without re-prefilling. Pools are head-FULL even at
        tp>1 (the cache shards a full-head pool over the mesh), so a
        payload exported at any tp imports at any other tp. Returns
        None when the slot holds no rows — the caller ships nothing and
        the request replays."""
        ps = self.cache.page_size
        rows = int(st.pos)
        if rows <= 0:
            return None
        sentinel = self.cache.num_pages
        n = -(-rows // ps)  # ceil: partial last page ships whole
        pages = [int(p) for p in self._table[slot, :n]]
        if any(p == sentinel for p in pages):
            return None
        idx = jnp.asarray(pages, jnp.int32)
        payload: Dict[str, Any] = {
            "rows": rows,
            "page_size": int(ps),
            "quantized": bool(self.cache.quantized),
            "dtype": str(self.cache.k[0].dtype),
            "k": [pool[idx] for pool in self.cache.k],
            "v": [pool[idx] for pool in self.cache.v],
        }
        if self.cache.quantized:
            payload["k_scale"] = [s[idx] for s in self.cache.k_scale]
            payload["v_scale"] = [s[idx] for s in self.cache.v_scale]
        return jax.device_get(payload)

    def _import_shipped_pages(self, st: _Slot, slot: int, payload) -> bool:
        """Land a shipped KV payload directly in this engine's pool:
        allocate destination pages, scatter the page blocks in, map the
        slot's table rows, and start the cursor past the shipped rows.
        The LAST prefix token is never trusted from the wire — it
        replays through the ordinary chunk path so the fused step
        re-derives the slot's device lengths and decode feed exactly as
        a replay-resume would (greedy output is identical either way;
        the rewritten row holds the same values it shipped with).

        Returns False — and counts a fallback — whenever the payload
        cannot be used verbatim: the ``page_ship`` fault site fires
        (transfer dropped mid-flight), the geometry disagrees
        (page_size/dtype/quantization/pool shape), or the local
        allocator is out of pages. The caller then simply admits the
        request on the token-replay path; nothing was mapped, so
        neither allocator can leak."""
        track = f"req{st.req.request_id}"
        if self.faults.enabled and self.faults.fire(
            "page_ship", tick=self._tick, slot=slot,
        ) is not None:
            # injected transfer loss: the payload never arrived —
            # fall back to replay, exactly like a real dropped ship
            self._page_ship_fallbacks += 1
            if self.tracer.enabled:
                self.tracer.instant(
                    "page_ship_dropped", track=track, slot=slot,
                )
            return False
        cache = self.cache
        ps = cache.page_size
        rows = int(payload.get("rows", 0))
        target = min(rows, len(st.prefix) - 1)
        if target <= 0:
            return False
        k_bufs = payload.get("k", ())
        v_bufs = payload.get("v", ())
        compatible = (
            int(payload.get("page_size", -1)) == ps
            and bool(payload.get("quantized")) == cache.quantized
            and payload.get("dtype") == str(cache.k[0].dtype)
            and len(k_bufs) == cache.num_layers
            and len(v_bufs) == cache.num_layers
            and all(
                tuple(b.shape[1:]) == tuple(cache.k[0].shape[1:])
                for b in list(k_bufs) + list(v_bufs)
            )
        )
        n = len(k_bufs[0]) if compatible else 0
        if not compatible or n < -(-rows // ps) or n > cache.pages_per_slot:
            self._page_ship_fallbacks += 1
            return False
        got = self._allocator.alloc(n)
        if got is None:
            # pool pressure at admission: replaying is strictly better
            # than holding the slot hostage waiting for pages
            self._page_ship_fallbacks += 1
            return False
        dst = jnp.asarray(got, jnp.int32)
        k = tuple(
            pool.at[dst].set(jnp.asarray(buf))
            for pool, buf in zip(cache.k, k_bufs)
        )
        v = tuple(
            pool.at[dst].set(jnp.asarray(buf))
            for pool, buf in zip(cache.v, v_bufs)
        )
        k_scale, v_scale = cache.k_scale, cache.v_scale
        if cache.quantized:
            k_scale = tuple(
                s.at[dst].set(jnp.asarray(buf))
                for s, buf in zip(cache.k_scale, payload["k_scale"])
            )
            v_scale = tuple(
                s.at[dst].set(jnp.asarray(buf))
                for s, buf in zip(cache.v_scale, payload["v_scale"])
            )
        self.cache = cache.replace(
            k=k, v=v, k_scale=k_scale, v_scale=v_scale,
        )
        if self._mesh is not None:
            # eager scatters may drop the head sharding; restore the
            # canonical layout so the donated step inputs stay put
            self.cache = jax.device_put(
                self.cache, self._cache_sharding()
            )
        for i, page in enumerate(got):
            self._map_page(slot, i, page)
        st.cursor = target
        st.pos = target
        self._page_ships += 1
        if self.tracer.enabled:
            self.tracer.instant(
                "page_ship_import", track=track, slot=slot,
                pages=n, rows=target,
            )
        return True

    def _ensure_writable(self, st: _Slot, slot: int, idx: int) -> bool:
        """Page index ``idx`` of ``slot`` is mapped and privately
        owned after this call — allocating a fresh page for an
        unmapped entry, or copy-on-write-forking a BORROWED
        (prefix-shared) page the slot is about to write into. Returns
        False when the pool cannot supply a page: the caller
        backpressures (the token simply is not scheduled this tick;
        nothing crashes, nothing clamps)."""
        if self.faults.enabled and self.faults.fire(
            "page_alloc", tick=self._tick, slot=slot, page_idx=idx,
        ) is not None:
            # injected allocator failure: indistinguishable from a
            # genuinely exhausted pool — the caller backpressures
            return False
        sentinel = self.cache.num_pages
        page = int(self._table[slot, idx])
        track = f"req{st.req.request_id}"
        if page == sentinel:
            got = self._allocator.alloc(1)
            if got is None:
                return False
            self._map_page(slot, idx, got[0])
            if self.tracer.enabled:
                self.tracer.instant(
                    "page_alloc", track=track,
                    page=got[0], page_idx=idx, slot=slot,
                )
            return True
        if idx in st.borrowed:
            got = self._allocator.alloc(1)
            if got is None:
                return False
            dst = got[0]
            # device copy first (one compiled program for every fork),
            # then remap: the sharers keep reading the source page —
            # their bytes are never touched
            self.cache = self._fork_jit(
                self.cache, jnp.int32(page), jnp.int32(dst)
            )
            self._allocator.decref(page, park=self._page_registered(page))
            st.borrowed.discard(idx)
            self._map_page(slot, idx, dst)
            self._cow_forks += 1
            if self.tracer.enabled:
                self.tracer.instant(
                    "cow_fork", track=track,
                    src=page, dst=dst, page_idx=idx, slot=slot,
                )
        return True

    def _secure_prefill_pages(self, st: _Slot, slot: int, n: int) -> int:
        """Make pages for prompt positions ``[cursor, cursor + n)``
        writable; returns how many of the n tokens actually have a
        page (possibly 0 — free-list exhaustion backpressure)."""
        ps = self.cache.page_size
        secured_end = st.cursor
        first = st.cursor // ps
        last = (st.cursor + n - 1) // ps
        for idx in range(first, last + 1):
            if not self._ensure_writable(st, slot, idx):
                self._page_stalls += 1
                break
            secured_end = min(st.cursor + n, (idx + 1) * ps)
        return secured_end - st.cursor

    def _register_full_pages(self, st: _Slot, slot: int) -> None:
        """Advance the slot's prefix chain over every page that is now
        FULL of prompt tokens: freshly-owned pages register in the
        store (immutable from here on — appends only land past them);
        borrowed pages just advance the chain key they were matched
        from."""
        ps = self.cache.page_size
        prompt = st.req.prompt
        while ((st.reg_pages + 1) * ps <= st.cursor
               and (st.reg_pages + 1) * ps <= len(prompt)):
            idx = st.reg_pages
            tokens = prompt[idx * ps:(idx + 1) * ps]
            if idx in st.borrowed:
                st.chain_key = self._store.chain_key(
                    st.chain_key, tokens
                )
            else:
                st.chain_key = self._store.register(
                    st.chain_key, tokens, int(self._table[slot, idx])
                )
            st.reg_pages += 1

    def _release_slot_pages(self, st: _Slot, slot: int) -> None:
        """Eviction: drop this slot's page references. Store-registered
        pages PARK (reclaimable prefix cache — a later request with
        the same prefix revives them for free); private pages free."""
        sentinel = self.cache.num_pages
        for idx in range(self._table.shape[1]):
            page = int(self._table[slot, idx])
            if page == sentinel:
                continue
            self._allocator.decref(
                page, park=self._page_registered(page)
            )
            self._table[slot, idx] = sentinel
        self._table_dirty = True
        st.borrowed.clear()

    def _release_adapter(self, st: _Slot) -> None:
        """Drop an in-flight request's adapter residency ref, exactly
        once per lease (``adapter_slot = -1`` marks the lease closed,
        so overlapping teardown paths under failure recovery cannot
        double-release). The pool slot PARKS at refcount zero — the
        tenant's next request revives the bytes for free."""
        if self.adapter_pool is None or st.adapter_slot < 0:
            return
        self.adapter_pool.release(st.req.adapter_id)
        st.adapter_slot = -1

    def _preempt_for_pages(self) -> None:
        """Break a pool deadlock by preempting slots — youngest lease
        first (least recompute lost, and it frees the most recently
        allocated pages) — until at least one page is available.
        Only slots that actually hold table mappings are candidates
        (preempting a pageless slot frees nothing). A preempted
        request keeps its generated tokens and timeline anchors in
        ``_preempted`` and rejoins the HEAD of the queue; re-admission
        recomputes prompt + generated through the ordinary chunked
        prefill (determinism: greedy output is unchanged). If every
        mapped slot is drained and the pool is still empty (pages
        pinned elsewhere), the original deadlock diagnosis raises."""
        sentinel = self.cache.num_pages
        while self._allocator.available < 1:
            victim, vslot = None, -1
            for slot, st in enumerate(self._slots):
                if st is None:
                    continue
                if not any(
                    int(p) != sentinel for p in self._table[slot]
                ):
                    continue
                if victim is None or st.leased_at >= victim.leased_at:
                    victim, vslot = st, slot
            # preemption is only productive if ANOTHER in-flight slot
            # remains to consume the freed pages: the victim rejoins
            # the queue HEAD, so preempting the sole request would
            # re-admit it straight into the same wall — a livelock,
            # not a recovery (the num_pages=1 unservable-pool case)
            if sum(s is not None for s in self._slots) <= 1:
                victim = None
            if victim is None:
                raise RuntimeError(
                    "paged KV pool deadlock: every in-flight request "
                    "is stalled waiting for pages, no decode can run "
                    "to free any, and no slot holds reclaimable pages "
                    f"(pages={self.cache.num_pages}, used="
                    f"{self._allocator.pages_used}); size num_pages "
                    "for the expected live tokens, or admit less "
                    "concurrency"
                )
            self._release_slot_pages(victim, vslot)
            self._release_adapter(victim)
            self._slots[vslot] = None
            self._preempted[victim.req.request_id] = (
                list(victim.generated), victim.first_token_at,
                victim.chunks,
            )
            self._queue.appendleft(victim.req)
            self._preemptions += 1
            if self.tracer.enabled:
                self.tracer.instant(
                    "preempt", track=f"req{victim.req.request_id}",
                    slot=vslot, generated=len(victim.generated),
                    request_id=victim.req.request_id,
                    trace_id=victim.req.trace_id,
                )

    def _guard_capacity(self, active) -> None:
        """The host-side replacement for the cache's silent
        clamp-at-capacity: a live slot about to DECODE at a position
        >= capacity is an engine invariant violation (the scheduler
        must have evicted it with finish_reason='capacity' already) —
        raise with the slot id instead of wedging the length and
        silently re-sampling from a stale last row."""
        for slot, st in enumerate(self._slots):
            if st is None or not active[slot]:
                continue
            if st.pos >= self.capacity:
                raise RuntimeError(
                    f"slot {slot} (request {st.req.request_id}) would "
                    f"write cache position {st.pos} >= capacity "
                    f"{self.capacity}: the engine must evict a "
                    f"sequence before its length hits capacity "
                    f"(finish_reason='capacity'), never clamp a live "
                    f"write"
                )

    def _pick_queued(self) -> Optional[Tuple[Request, int]]:
        """Pick the next admissible queued request. Without an adapter
        pool: plain FIFO. With one, admission is TIER-ORDERED (highest
        tier first, FIFO within a tier) and ACQUIRE-OR-SKIP: the
        candidate's adapter must take a residency ref NOW — if every
        pool slot is pinned by in-flight work the candidate is skipped
        (``adapter_stalls``; token-level backpressure, retried next
        tick once a finishing request drops a ref — never a deadlock)
        and a lower-tier request whose adapter IS available admits
        instead. Returns ``(request, adapter buffer slot)`` with the
        ref already held; the caller owns releasing it."""
        if not self._queue:
            return None
        if self.adapter_pool is None:
            return self._queue.popleft(), 0
        order = sorted(
            range(len(self._queue)),
            key=lambda i: (
                -self.adapter_pool.tier_of(
                    self._queue[i].adapter_id
                ),
                i,
            ),
        )
        for i in order:
            req = self._queue[i]
            aslot = self.adapter_pool.acquire(req.adapter_id)
            if aslot is None:
                self._adapter_stalls += 1
                continue
            del self._queue[i]
            return req, aslot
        return None

    def _admit_free_slots(self, now: float) -> None:
        """Lease free slots to queued requests (host bookkeeping; the
        prefill work itself is scheduled by the caller). With prefix
        sharing, a prompt that extends an already-materialized page
        chain maps those pages by REFERENCE and starts its prefill
        cursor past them — the shared tokens are never re-prefilled.

        With ``tier_preemption`` and a fully-occupied engine, a queued
        request outranking the lowest-tier in-flight one preempts that
        victim (youngest lease within the tier; at most one per tick)
        through the PR-8 requeue path — tokens kept, cache recomputed
        on re-admission, greedy output unchanged."""
        if (
            self.tier_preemption
            and self.adapter_pool is not None
            and self._queue
            and all(s is not None for s in self._slots)
        ):
            top = max(
                self.adapter_pool.tier_of(q.adapter_id)
                for q in self._queue
            )
            victim, vslot, vtier = None, -1, 0
            for slot, st in enumerate(self._slots):
                t = self.adapter_pool.tier_of(st.req.adapter_id)
                if (
                    victim is None or t < vtier
                    or (t == vtier and st.leased_at >= victim.leased_at)
                ):
                    victim, vslot, vtier = st, slot, t
            if top > vtier:
                if self.paged:
                    self._release_slot_pages(victim, vslot)
                self._release_adapter(victim)
                self._slots[vslot] = None
                self._preempted[victim.req.request_id] = (
                    list(victim.generated), victim.first_token_at,
                    victim.chunks,
                )
                self._queue.appendleft(victim.req)
                self._tier_preemptions += 1
                if self.tracer.enabled:
                    self.tracer.instant(
                        "tier_preempt",
                        track=f"req{victim.req.request_id}",
                        slot=vslot, tier=vtier, over=top,
                        request_id=victim.req.request_id,
                        trace_id=victim.req.trace_id,
                    )
        for slot in range(self.num_slots):
            if self._slots[slot] is not None or not self._queue:
                continue
            picked = self._pick_queued()
            if picked is None:
                # nothing admissible this tick (adapter residency
                # backpressure) — no point probing the other slots
                break
            req, aslot = picked
            self._admitted += 1
            self._record_queue_wait(now - req.enqueued_at)
            st = _Slot(
                req=req, generated=[], pos=0, cursor=0,
                prefix=list(req.prompt), leased_at=now,
                adapter_slot=aslot,
            )
            carried = self._preempted.pop(req.request_id, None)
            if carried is not None:
                # preempted request: restore its tokens and recompute
                # the cache via ordinary chunked prefill of
                # prompt + generated[:-1] (the last generated token
                # stays unwritten — the live-slot invariant — so the
                # slot rejoins the decode grid exactly where it left
                # off; greedy output is identical to an unpreempted
                # run). TTFT/chunk anchors carry over: the first token
                # was already delivered before preemption.
                generated, first_at, chunks = carried
                st.generated = list(generated)
                st.first_token_at = first_at
                st.chunks = chunks
                if generated:
                    st.prefix = list(req.prompt) + list(generated[:-1])
                    st.resumed = True
            self._slots[slot] = st
            shipped = self._shipped.pop(req.request_id, None)
            if shipped is not None and self._import_shipped_pages(
                st, slot, shipped
            ):
                # page-shipping landed: the cursor already covers the
                # shipped rows, which is at least what a local prefix
                # match could offer — skip the store consult entirely
                if self.tracer.enabled:
                    self.tracer.add_span(
                        "queue_wait", req.enqueued_at, now,
                        track=f"req{req.request_id}", slot=slot,
                        request_id=req.request_id,
                        trace_id=req.trace_id,
                    )
                continue
            if self._store is not None:
                pages, matched, partial, key = self._store.match(
                    req.prompt
                )
                if matched > 0:
                    for idx, page in enumerate(pages):
                        self._allocator.ref(page)
                        self._map_page(slot, idx, page)
                        st.borrowed.add(idx)
                    st.cursor = matched
                    st.pos = matched
                    st.chain_key = key
                    st.reg_pages = len(pages) - (1 if partial else 0)
                    self._prefix_hits += 1
                    self._prefix_hit_tokens += matched
                    if self.tracer.enabled:
                        self.tracer.instant(
                            "prefix_hit", track=f"req{req.request_id}",
                            tokens=matched, pages=len(pages),
                            partial_tokens=partial, slot=slot,
                            request_id=req.request_id,
                            trace_id=req.trace_id,
                        )
            if self.tracer.enabled:
                self.tracer.add_span(
                    "queue_wait", req.enqueued_at, now,
                    track=f"req{req.request_id}", slot=slot,
                    request_id=req.request_id, trace_id=req.trace_id,
                )

    # -- robustness internals ------------------------------------------

    def _maybe_fail_fetch(self) -> None:
        """The ``host_fetch`` fault site: between the device call and
        the value fetch — the retry wrapper sees it like any other
        transient failure."""
        if self.faults.enabled and self.faults.fire(
            "host_fetch", tick=self._tick,
        ) is not None:
            raise FaultInjected(
                f"injected host_fetch fault (tick {self._tick})"
            )

    def _call_device(self, thunk):
        """Run one compiled step (+ its fetch) with the ``device_step``
        fault site and capped exponential-backoff retry. ``thunk``
        performs the jitted call and the fetch and RETURNS the new
        cache instead of assigning it — `self.cache` only moves
        forward on success, so a retry re-runs against the pre-step
        cache and the rng split already made (bitwise-deterministic
        recovery on CPU, where buffers are not donated; on TPU a
        genuine mid-step failure consumes the donated cache and the
        retry surfaces that — the requeue path below still runs).
        On exhaustion every in-flight slot preempts-and-requeues via
        the PR-8 path, then the failure propagates."""
        attempt = 0
        while True:
            try:
                if self.faults.enabled and self.faults.fire(
                    "device_step", tick=self._tick,
                ) is not None:
                    raise FaultInjected(
                        f"injected device_step fault (tick {self._tick})"
                    )
                return thunk()
            except Exception:
                if attempt >= self.max_step_retries:
                    self._requeue_in_flight()
                    raise
                attempt += 1
                self._step_retries += 1
                if self.tracer.enabled:
                    self.tracer.instant(
                        "step_retry", track="engine", attempt=attempt,
                    )
                if self.step_retry_backoff > 0:
                    time.sleep(min(
                        self.step_retry_backoff * (2 ** (attempt - 1)),
                        1.0,
                    ))

    def _requeue_in_flight(self) -> None:
        """Device-step retries exhausted: hand every in-flight request
        back to the queue through the PR-8 preempt path before the
        failure surfaces, so a caller that catches it finds a
        consistent engine (slots free, pages released, requests
        queued) and the next successful tick recomputes everything.
        Reverse slot order + appendleft keeps the original slot order
        at the queue head. Pages registered in the prefix store by
        COMPLETED ticks are valid and park as usual; the failed
        tick's writes never registered (registration is deferred past
        the device call) so no junk page can be matched later."""
        for slot in range(self.num_slots - 1, -1, -1):
            st = self._slots[slot]
            if st is None:
                continue
            if self.paged:
                self._release_slot_pages(st, slot)
            self._release_adapter(st)
            self._slots[slot] = None
            if st.generated:
                self._preempted[st.req.request_id] = (
                    list(st.generated), st.first_token_at, st.chunks,
                )
            self._queue.appendleft(st.req)
            self._preemptions += 1
            if self.tracer.enabled:
                self.tracer.instant(
                    "requeue", track=f"req{st.req.request_id}",
                    slot=slot, generated=len(st.generated),
                    request_id=st.req.request_id,
                    trace_id=st.req.trace_id,
                )
        if self.paged:
            self._push_table()

    def _expire_deadlines(self, now: float) -> List[GenerationResult]:
        """Tick-boundary deadline sweep: queued requests past their
        queue TTL or end-to-end deadline expire without a slot;
        in-flight requests past their deadline tear down through the
        ordinary eviction (slot + pages released). Both finish with
        reason ``deadline``."""
        out: List[GenerationResult] = []
        if self._queue:
            keep: collections.deque = collections.deque()
            for req in self._queue:
                expired = (
                    (req.queue_deadline is not None
                     and now > req.queue_deadline)
                    or (req.deadline is not None and now > req.deadline)
                )
                if expired:
                    self._deadline_exceeded += 1
                    out.append(
                        self._finalize_queued(req, "deadline", now)
                    )
                else:
                    keep.append(req)
            self._queue = keep
        for slot, st in enumerate(self._slots):
            if st is None or st.req.deadline is None:
                continue
            if now > st.req.deadline:
                self._deadline_exceeded += 1
                out.append(self._evict(slot, st, "deadline"))
        return out

    def _finalize_queued(
        self, req: Request, reason: str, now: float
    ) -> GenerationResult:
        """Finish a request that never (re)took a slot — expired in
        queue, cancelled in queue, or shed by drain. A PREEMPTED
        request waiting to resume returns the tokens it already
        generated (they were delivered work; dropping them would
        un-deliver it)."""
        carried = self._preempted.pop(req.request_id, None)
        tokens = list(carried[0]) if carried is not None else []
        self._record_completion({
            "request_id": req.request_id,
            "finish_reason": reason,
            "prompt_tokens": len(req.prompt),
            "new_tokens": len(tokens),
            "chunks": carried[2] if carried is not None else 0,
            "queue_wait_ms": 1e3 * (now - req.enqueued_at),
            "ttft_ms": 0.0,
            "tpot_ms": 0.0,
            "e2e_ms": 1e3 * (now - req.enqueued_at),
            "tenant": req.tenant,
        })
        if self.tracer.enabled:
            self.tracer.instant(
                "finish", ts=now, track=f"req{req.request_id}",
                reason=reason, request_id=req.request_id,
                trace_id=req.trace_id,
            )
        return GenerationResult(
            request_id=req.request_id, prompt=list(req.prompt),
            tokens=tokens, finish_reason=reason,
        )

    def _quarantine(
        self, slot: int, st: _Slot, why: str
    ) -> GenerationResult:
        """Fault isolation: nonfinite logits on ONE slot evict that
        slot only (``finish_reason='error'``) — the tick's other slots
        already got their tokens from the same fetch, bitwise
        identical to a fault-free run (the poison/flag path adds
        ``+0.0`` to their logits and nothing else). The flight
        recorder, when wired, dumps a ``nonfinite/slot<i>`` bundle for
        the postmortem."""
        self._quarantined += 1
        rid = st.req.request_id
        if self.tracer.enabled:
            self.tracer.instant(
                "quarantine", track=f"req{rid}", slot=slot, why=why,
                request_id=rid, trace_id=st.req.trace_id,
            )
        if self.flight_recorder is not None:
            self.flight_recorder.record(
                self._tick, {f"nonfinite/slot{slot}": 1.0},
                request_id=rid, pos=st.pos,
                generated=len(st.generated),
            )
        return self._evict(slot, st, "error")

    def _note_progress(self) -> None:
        """Token progress = prompt tokens absorbed, tokens generated,
        or slots evicted — the signals the stall watchdog watches."""
        work = (
            self._prompt_tokens, self._generated_tokens, self._evicted,
        )
        if work != self._progress_mark:
            self._progress_mark = work
            self._last_progress = time.perf_counter()

    def _check_watchdog(self, now: float) -> None:
        if self.watchdog_timeout is None or not self.has_work():
            return
        stalled = now - self._last_progress
        if stalled <= self.watchdog_timeout:
            return
        self._watchdog_fires += 1
        diag = self._stall_diagnosis()
        if self.tracer.enabled:
            self.tracer.instant(
                "watchdog", track="engine", stalled_seconds=stalled,
            )
        if self.watchdog_dump_path is not None:
            # the postmortem bundle: engine state as json, plus the
            # tracer timeline next to it when tracing is on
            import json

            with open(self.watchdog_dump_path, "w") as f:
                json.dump({
                    "event": "watchdog",
                    "stalled_seconds": stalled,
                    "tick": self._tick,
                    "diagnosis": diag,
                    "stats": self.stats(),
                }, f, indent=2)
            if self.tracer.enabled:
                self.tracer.export_chrome_trace(
                    self.watchdog_dump_path + ".trace.json"
                )
        raise RuntimeError(
            f"serving watchdog: no token progress for {stalled:.2f}s "
            f"(watchdog_timeout={self.watchdog_timeout}s); {diag}"
        )

    def _stall_diagnosis(self) -> str:
        """Name the stuck slot(s) — the diagnostic the watchdog and
        the bounded `generate()` raise with."""
        parts = []
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            phase = "prefilling" if st.prefilling else "decoding"
            parts.append(
                f"slot {slot}: request {st.req.request_id} {phase} "
                f"pos={st.pos} cursor={st.cursor}/{len(st.prefix)} "
                f"generated={len(st.generated)}"
            )
        if not parts:
            parts.append("no slots leased")
        return (
            f"queue_depth={self.num_queued}, "
            f"draining={self._draining}; " + "; ".join(parts)
        )

    def _step_chunked(self) -> List[GenerationResult]:
        finished: List[GenerationResult] = []
        now = time.perf_counter()
        self._admit_free_slots(now)

        budget = self.prefill_token_budget
        S = self.num_slots
        chunk_tokens = np.zeros((budget,), np.int32)
        # slot id == num_slots marks padding: the scatter drops it and
        # the segment mask keeps pads talking only to each other
        chunk_slots = np.full((budget,), S, np.int32)
        chunk_pos = np.zeros((budget,), np.int32)
        # speculative mode only: who COMMITS in-trace. Prefill rows
        # commit like always; speculative rows keep the pad sentinel
        # (the host commits their accepted prefix post-verification)
        commit_slots = np.full((budget,), S, np.int32)
        lengths_before = np.zeros((S,), np.int32)
        lengths_after = np.zeros((S,), np.int32)
        # logits poison: zeros on the fault-free path (the compiled
        # programs add it unconditionally — x + 0.0 — so the fault-free
        # tokens are bitwise identical and the trace never changes);
        # a `logits` fault poisons ONE slot's rows with NaN/Inf
        chunk_poison = np.zeros((budget,), np.float32)
        dec_poison = np.zeros((S,), np.float32)
        # per-row adapter BUFFER slots (multi-LoRA): pad rows stay 0 =
        # base = zero factors, so padding is exact with or without
        # adapters in the batch
        pool = self.adapter_pool
        chunk_adp = dec_adp = None
        if pool is not None:
            chunk_adp = np.zeros((budget,), np.int32)
            dec_adp = np.zeros((S,), np.int32)
        poison_slot = -1
        poison_val = 0.0
        if self.faults.enabled:
            flt = self.faults.fire("logits", tick=self._tick)
            if flt is not None:
                pay = (
                    flt.payload if isinstance(flt.payload, dict)
                    else {"slot": flt.payload}
                )
                s = pay.get("slot")
                poison_slot = int(s) if s is not None else 0
                poison_val = float(pay.get("value", float("nan")))
                if 0 <= poison_slot < S:
                    dec_poison[poison_slot] = poison_val
        # (slot, chunk index of last prompt token, fed-to-decode flag)
        completions = []
        packed = []  # (slot, tokens, start_pos) — tracer span payload
        # paged prefix registration deferred past the device call (a
        # failed step must not leave never-written pages registered)
        reg_pending = []
        # speculative bookkeeping: (slot, first chunk row, drafted
        # count, draft tokens, pre-draft position)
        spec_entries = []
        used = 0
        prefill_used = 0

        drafts_np = counts_np = None
        t_d0 = t_d1 = 0.0
        if self.spec_k > 0:
            # one batched drafter call per tick, covering every
            # decoding slot (jitted inside the drafter; numpy in/out)
            W = self._spec_window
            hist = np.full((S, W), -1, np.int32)
            hist_len = np.zeros((S,), np.int32)
            any_decoding = False
            for slot, s in enumerate(self._slots):
                if s is None or not s.generated or s.prefilling:
                    continue
                any_decoding = True
                h = (s.req.prompt + s.generated)[-W:]
                hist[slot, W - len(h):] = h
                hist_len[slot] = len(h)
            if any_decoding:
                t_d0 = time.perf_counter()
                drafts_np, counts_np = self._drafter(hist, hist_len)
                t_d1 = time.perf_counter()

        # slot order keeps the packed segment ids non-decreasing (the
        # varlen kernel's contract); a slot contributes either prefill
        # rows or a speculative span, never both
        for slot in range(S):
            st = self._slots[slot]
            if st is not None:
                lengths_before[slot] = st.pos
                lengths_after[slot] = st.pos
            if st is None or used >= budget:
                continue
            if st.prefilling:
                n = min(budget - used, len(st.prefix) - st.cursor)
                if self.prefill_chunk is not None:
                    n = min(n, self.prefill_chunk)
                if self.paged:
                    # pool backpressure: only tokens whose pages exist
                    # (or could be allocated / CoW-forked) are
                    # scheduled; a starved slot just waits for
                    # evictions to free pages
                    n = self._secure_prefill_pages(st, slot, n)
                    if n <= 0:
                        continue
                chunk_tokens[used:used + n] = st.prefix[
                    st.cursor:st.cursor + n
                ]
                chunk_slots[used:used + n] = slot
                commit_slots[used:used + n] = slot
                chunk_pos[used:used + n] = np.arange(
                    st.cursor, st.cursor + n
                )
                if chunk_adp is not None:
                    chunk_adp[used:used + n] = st.adapter_slot
                packed.append((slot, n, st.cursor))
                st.cursor += n
                st.pos = st.cursor
                st.chunks += 1
                lengths_after[slot] = st.cursor
                self._prompt_tokens += n
                if self.paged and self._store is not None:
                    reg_pending.append((st, slot))
                if not st.prefilling and not st.resumed:
                    # the completing prompt's first sampled token is
                    # fed straight into the fused decode — UNLESS that
                    # decode write has nowhere to land: a prompt that
                    # exactly fills capacity (the old silent
                    # clamp-at-capacity; the host evicts it right
                    # after the first token instead) or a paged slot
                    # whose next page the pool cannot supply yet (it
                    # decodes on a later tick). A RESUMED (preempted)
                    # request completing its recomputed prefix emits
                    # nothing here — its tokens already exist; it
                    # rejoins the decode grid below this same tick.
                    fed = st.cursor < self.capacity
                    if fed and self.paged:
                        fed = self._ensure_writable(
                            st, slot, st.cursor // self.cache.page_size
                        )
                        if not fed:
                            self._page_stalls += 1
                    completions.append((slot, used + n - 1, fed))
                used += n
                prefill_used += n
                continue
            # ---- speculative span: [last generated token, k drafts].
            # The last token needs its decode row scored anyway; the
            # drafts ride the same packed chunk, so acceptance costs
            # no extra trace. Clamps: drafter confidence, spec_k, the
            # remaining budget (one row is the last token itself),
            # capacity (every accepted token + bonus needs a cache
            # row), and max_new (finishing mid-span is handled, but
            # drafting past the request's end is wasted budget).
            if drafts_np is None or not st.generated:
                continue
            n = min(
                int(counts_np[slot]), self.spec_k, budget - used - 1,
                self.capacity - st.pos - 1,
                st.req.max_new_tokens - len(st.generated) - 1,
            )
            if n < 1:
                continue
            if self.paged and not self._ensure_writable(
                st, slot, st.pos // self.cache.page_size
            ):
                # pool exhausted even for the last token's row: fall
                # through to the decode grid, which hits the same wall
                # and stalls the slot for the tick
                continue
            drafts = [int(t) for t in drafts_np[slot, :n]]
            chunk_tokens[used] = st.generated[-1]
            chunk_tokens[used + 1:used + 1 + n] = drafts
            chunk_slots[used:used + n + 1] = slot
            chunk_pos[used:used + n + 1] = np.arange(
                st.pos, st.pos + n + 1
            )
            spec_entries.append((slot, used, n, drafts, st.pos))
            self._tokens_drafted += n
            used += n + 1

        if poison_slot >= 0:
            # poison the faulted slot's chunk rows too (a prompt
            # completion or speculative span must quarantine the same
            # way a decode row does)
            chunk_poison[chunk_slots == poison_slot] = poison_val

        # decode grid: slots whose prompt completed in an EARLIER tick
        # (a slot finishing prefill this tick gets its first token from
        # the chunk logits below and starts decoding next tick; a slot
        # with a speculative span this tick advances via the accept
        # walk instead)
        active = np.array(
            [s is not None and bool(s.generated) and not s.prefilling
             for s in self._slots],
            dtype=bool,
        )
        for slot, _, _, _, _ in spec_entries:
            active[slot] = False
        self._guard_capacity(active)
        if self.paged:
            for slot, st in enumerate(self._slots):
                if not active[slot]:
                    continue
                if not self._ensure_writable(
                    st, slot, st.pos // self.cache.page_size
                ):
                    # stall THIS slot's decode for the tick; everyone
                    # else advances (fixed shapes: the row just rides
                    # along dead)
                    active[slot] = False
                    self._page_stalls += 1
        dec_tokens = np.array(
            [s.generated[-1] if s is not None and s.generated else 0
             for s in self._slots],
            np.int32,
        )

        completion_idx = np.full((S,), -1, np.int32)
        for slot, idx, fed in completions:
            completion_idx[slot] = idx if fed else -1
        if dec_adp is not None:
            # only rows the fused decode actually emits carry their
            # adapter slot; dead rows stay 0 so a pure-base tick's
            # `active` skip condition sees all-zero ids exactly
            for slot, st in enumerate(self._slots):
                if st is None:
                    continue
                if active[slot] or completion_idx[slot] >= 0:
                    dec_adp[slot] = st.adapter_slot
        if self.paged:
            if (
                used == 0 and not active.any() and completions == []
                and self.has_work()
            ):
                # pool deadlock: every in-flight request is stalled
                # waiting for pages and no decode can run to free any.
                # Preempt-and-requeue (the vLLM recompute policy)
                # instead of stalling forever or raising: the youngest
                # page-holding slot gives its pages back and its
                # request rejoins the queue head; on re-admission its
                # prompt + generated tokens are recomputed through the
                # ordinary chunked prefill.
                self._preempt_for_pages()
            self._push_table()

        chunk_out = None
        dec_out = None
        chunk_bad = None
        dec_bad = None
        chunk_kv = None
        spec_t0 = spec_t1 = 0.0
        if self.spec_k > 0 and (used > 0 or active.any()):
            # speculative engines ALWAYS run the (single) spec mixed
            # program, even on draft-free ticks: the decode-only fast
            # path reads device-resident lengths, which the host-side
            # accept walk outruns — here the host cursors ride in as
            # arguments every tick, and one program means
            # mixed_trace_count == 1 at any k
            self._rng, rng = jax.random.split(self._rng)
            t0 = time.perf_counter()

            def _spec_thunk():
                chunk_tok, dec_tok, cbad, dbad, cache, kv = (
                    self._mixed_spec_jit(
                        self.params, self.cache,
                        jnp.asarray(chunk_tokens),
                        jnp.asarray(chunk_slots),
                        jnp.asarray(chunk_pos),
                        jnp.asarray(commit_slots),
                        jnp.asarray(lengths_before),
                        jnp.asarray(lengths_after),
                        jnp.asarray(completion_idx),
                        jnp.asarray(dec_tokens),
                        jnp.asarray(active),
                        jnp.asarray(chunk_poison),
                        jnp.asarray(dec_poison), rng,
                    )
                )
                self._maybe_fail_fetch()
                # ONE batched value fetch per tick (= the device
                # sync); chunk_kv stays on device for the commit
                # program. The nonfinite flags ride the same fetch.
                fetched = jax.device_get(
                    (chunk_tok, dec_tok, cbad, dbad)
                )
                return fetched, cache, kv

            with profiler.annotate(
                "inference/mixed_step",
                chunk_tokens=used, decodes=int(active.sum()),
                drafted=sum(e[2] for e in spec_entries),
            ):
                fetched, self.cache, chunk_kv = self._call_device(
                    _spec_thunk
                )
            chunk_out, dec_out, chunk_bad, dec_bad = fetched
            t1 = time.perf_counter()
            spec_t0, spec_t1 = t0, t1
            if prefill_used > 0:
                self._prefill_seconds += t1 - t0
                self._mixed_steps += 1
            else:
                self._decode_seconds += t1 - t0
            if active.any() or completions or spec_entries:
                self._decode_steps += 1
            if self.tracer.enabled:
                self.tracer.add_span(
                    "mixed_step", t0, t1, track="engine",
                    chunk_tokens=used, decodes=int(active.sum()),
                    drafted=sum(e[2] for e in spec_entries),
                )
                for slot, n, start_pos in packed:
                    st = self._slots[slot]
                    self.tracer.add_span(
                        "prefill_chunk", t0, t1,
                        track=f"req{st.req.request_id}",
                        tokens=n, start_pos=start_pos, slot=slot,
                    )
        elif used > 0:
            self._rng, rng = jax.random.split(self._rng)
            t0 = time.perf_counter()

            def _mixed_thunk():
                if pool is None:
                    chunk_tok, dec_tok, cbad, dbad, cache = (
                        self._mixed_jit(
                            self.params, self.cache,
                            jnp.asarray(chunk_tokens),
                            jnp.asarray(chunk_slots),
                            jnp.asarray(chunk_pos),
                            jnp.asarray(lengths_before),
                            jnp.asarray(lengths_after),
                            jnp.asarray(completion_idx),
                            jnp.asarray(dec_tokens),
                            jnp.asarray(active),
                            jnp.asarray(chunk_poison),
                            jnp.asarray(dec_poison), rng,
                        )
                    )
                    adapters = None
                else:
                    # the SAME fused chunk+decode program for any
                    # adapter mix — ids are data, so adapter add /
                    # park / reclaim churn never retraces
                    (chunk_tok, dec_tok, cbad, dbad, cache,
                     adapters) = self._mixed_lora_jit(
                        self.params, self.cache, pool.buffers,
                        jnp.asarray(chunk_tokens),
                        jnp.asarray(chunk_slots),
                        jnp.asarray(chunk_pos),
                        jnp.asarray(chunk_adp),
                        jnp.asarray(lengths_before),
                        jnp.asarray(lengths_after),
                        jnp.asarray(completion_idx),
                        jnp.asarray(dec_tokens),
                        jnp.asarray(active),
                        jnp.asarray(dec_adp),
                        jnp.asarray(chunk_poison),
                        jnp.asarray(dec_poison), rng,
                    )
                self._maybe_fail_fetch()
                # ONE batched value fetch per tick (= the device sync)
                # — never a per-request scalar pull; the nonfinite
                # flags ride the same fetch
                return jax.device_get(
                    (chunk_tok, dec_tok, cbad, dbad)
                ), cache, adapters

            with profiler.annotate(
                "inference/mixed_step",
                chunk_tokens=used, decodes=int(active.sum()),
            ):
                fetched, self.cache, new_adp = self._call_device(
                    _mixed_thunk
                )
            if new_adp is not None:
                # re-bind the donated adapter buffers (like the cache,
                # they only move forward on step success)
                pool.buffers = new_adp
            chunk_out, dec_out, chunk_bad, dec_bad = fetched
            t1 = time.perf_counter()
            self._prefill_seconds += t1 - t0
            self._mixed_steps += 1
            if active.any() or completions:
                self._decode_steps += 1
            if self.tracer.enabled:
                self.tracer.add_span(
                    "mixed_step", t0, t1, track="engine",
                    chunk_tokens=used, decodes=int(active.sum()),
                )
                for slot, n, start_pos in packed:
                    st = self._slots[slot]
                    self.tracer.add_span(
                        "prefill_chunk", t0, t1,
                        track=f"req{st.req.request_id}",
                        tokens=n, start_pos=start_pos, slot=slot,
                    )
        elif active.any():
            self._rng, rng = jax.random.split(self._rng)
            t0 = time.perf_counter()

            def _decode_thunk():
                if pool is None:
                    tok, bad, cache = self._decode_jit(
                        self.params, self.cache,
                        jnp.asarray(dec_tokens),
                        jnp.asarray(active), jnp.asarray(dec_poison),
                        rng,
                    )
                    adapters = None
                else:
                    tok, bad, cache, adapters = self._decode_lora_jit(
                        self.params, self.cache, pool.buffers,
                        jnp.asarray(dec_tokens), jnp.asarray(active),
                        jnp.asarray(dec_adp), jnp.asarray(dec_poison),
                        rng,
                    )
                self._maybe_fail_fetch()
                # value fetch = device sync
                return jax.device_get((tok, bad)), cache, adapters

            with profiler.annotate(
                "inference/decode", batch=int(active.sum())
            ):
                fetched, self.cache, new_adp = self._call_device(
                    _decode_thunk
                )
            if new_adp is not None:
                pool.buffers = new_adp
            dec_out, dec_bad = fetched
            t1 = time.perf_counter()
            self._decode_seconds += t1 - t0
            self._decode_steps += 1
            if self.tracer.enabled:
                self.tracer.add_span(
                    "decode_step", t0, t1, track="engine",
                    decodes=int(active.sum()),
                )

        # the device step committed: NOW the tick's full prompt pages
        # may register in the prefix store (see reg_pending above)
        for st, slot in reg_pending:
            self._register_full_pages(st, slot)

        now2 = time.perf_counter()
        for slot, idx, fed in completions:
            st = self._slots[slot]
            if chunk_bad is not None and chunk_bad[idx]:
                # fault isolation: only THIS slot quarantines; every
                # other slot's tokens came out of the same fetch,
                # bitwise identical to a fault-free tick
                finished.append(self._quarantine(
                    slot, st, "nonfinite logits at prompt completion",
                ))
                continue
            st.generated.append(int(chunk_out[idx]))
            self._generated_tokens += 1
            st.first_token_at = now2
            self._record_ttft(now2 - st.req.enqueued_at)
            done = self._finish_reason(st)
            if done is not None:
                # any fused decode output for this slot is discarded
                # with the eviction (dead-row junk)
                finished.append(self._evict(slot, st, done))
                continue
            if not fed:
                # no fused decode ran for this slot (at-capacity edge
                # already evicted above, or a paged page stall): the
                # second token arrives on a later tick
                continue
            if dec_bad is not None and dec_bad[slot]:
                finished.append(self._quarantine(
                    slot, st, "nonfinite logits in fused decode",
                ))
                continue
            # the mixed step fed the first token straight into the
            # decode grid: the SECOND token arrives in the same tick
            # (the whole-prompt admit-tick cadence, without the pad)
            st.pos += 1
            st.generated.append(int(dec_out[slot]))
            self._generated_tokens += 1
            done = self._finish_reason(st)
            if done is not None:
                finished.append(self._evict(slot, st, done))
        if dec_out is not None:
            for slot, st in enumerate(self._slots):
                if st is None or not active[slot]:
                    continue
                if dec_bad is not None and dec_bad[slot]:
                    finished.append(self._quarantine(
                        slot, st, "nonfinite logits in decode",
                    ))
                    continue
                st.pos += 1  # the input token was written this step
                st.generated.append(int(dec_out[slot]))
                self._generated_tokens += 1
                done = self._finish_reason(st)
                if done is not None:
                    finished.append(self._evict(slot, st, done))

        # ---- speculative accept walk. Every packed span was sampled
        # under the target model (row j conditioned on the drafts before
        # it), so for the point-mass drafter the exact rejection rule
        # (arXiv 2302.01318) degenerates to: accept draft j iff the
        # model's own sample at row j equals it; the first disagreeing
        # row's sample is the corrected "bonus" token — m accepted
        # drafts always yield m+1 emitted tokens. Rejected rows simply
        # never commit: their K/V exists only in the trace's packed
        # per-layer output, so rollback is "don't write", not "undo" —
        # shared pages and int8 scales are untouchable by construction.
        if spec_entries:
            any_commit = False
            commit_np = np.full((budget,), S, np.int32)
            commit_pos_np = np.zeros((budget,), np.int32)
            for slot, r0, n, drafts, pos0 in spec_entries:
                st = self._slots[slot]
                if chunk_bad is not None and chunk_bad[
                    r0:r0 + n + 1
                ].any():
                    # the whole span's K/V stays uncommitted (rollback
                    # = "never written"), so quarantining the slot
                    # cannot leave poisoned rows in shared pages
                    finished.append(self._quarantine(
                        slot, st,
                        "nonfinite logits in speculative span",
                    ))
                    continue
                out = chunk_out[r0:r0 + n + 1]
                m = 0
                while m < n and int(out[m]) == drafts[m]:
                    m += 1
                if self.paged and m > 0:
                    # accepted tokens become cache writes: clamp the
                    # accept length to pages the pool can actually
                    # supply (CoW-forking shared ones as usual)
                    ps = self.cache.page_size
                    for j in range(1, m + 1):
                        if not self._ensure_writable(
                            st, slot, (pos0 + j) // ps
                        ):
                            self._page_stalls += 1
                            m = j - 1
                            break
                emit = drafts[:m] + [int(out[m])]
                accepted = 0
                done = None
                for i, tok in enumerate(emit):
                    st.pos += 1
                    st.generated.append(int(tok))
                    self._generated_tokens += 1
                    if i < m:
                        accepted += 1
                        self._tokens_accepted += 1
                    done = self._finish_reason(st)
                    if done is not None:
                        break
                if n - accepted > 0:
                    self._rollbacks += 1
                if self.tracer.enabled:
                    track = f"req{st.req.request_id}"
                    self.tracer.add_span(
                        "draft", t_d0, t_d1, track=track, tokens=n,
                    )
                    self.tracer.add_span(
                        "verify", spec_t0, spec_t1, track=track,
                        drafted=n, accepted=accepted, slot=slot,
                    )
                    if n - accepted > 0:
                        self.tracer.instant(
                            "rollback", track=track,
                            rejected=n - accepted,
                        )
                if done is not None:
                    # evicted slot: its uncommitted rows just die with
                    # the lease (paged pages are derefed by the evict)
                    finished.append(self._evict(slot, st, done))
                    continue
                # commit the span's written prefix: the last token's
                # row r0 (it was never in the cache — the scatter
                # dropped it in-trace) plus the m accepted draft rows.
                # The bonus token is NOT written: it is the slot's new
                # trailing unwritten token, exactly like normal decode.
                commit_np[r0:r0 + m + 1] = slot
                commit_pos_np[r0:r0 + m + 1] = np.arange(
                    pos0, pos0 + m + 1
                )
                any_commit = True
            if any_commit:
                if self.paged:
                    self._push_table()  # CoW forks from the clamp above
                self.cache = self._commit_jit(
                    self.cache, chunk_kv,
                    jnp.asarray(commit_np), jnp.asarray(commit_pos_np),
                )
        return finished

    def _step_whole(self) -> List[GenerationResult]:
        """Legacy whole-prompt prefill (the A/B baseline): one padded
        compiled prefill per admitted request — every other slot's
        decode WAITS on it (the head-of-line blocking the chunked
        scheduler removes) — then one decode step for the grid."""
        finished: List[GenerationResult] = []
        t_admit = time.perf_counter()
        pending = []  # (slot, device first-token)
        for slot in range(self.num_slots):
            if self._slots[slot] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            self._record_queue_wait(t_admit - req.enqueued_at)
            if self.tracer.enabled:
                self.tracer.add_span(
                    "queue_wait", req.enqueued_at, t_admit,
                    track=f"req{req.request_id}", slot=slot,
                )
            toks = np.zeros((1, self.max_prompt_len), np.int32)
            toks[0, : len(req.prompt)] = req.prompt
            self._rng, rng = jax.random.split(self._rng)
            with profiler.annotate(
                "inference/prefill", slot=slot, prompt_len=len(req.prompt)
            ):
                tok, self.cache = self._prefill_jit(
                    self.params, self.cache, jnp.asarray(toks),
                    slot, len(req.prompt), rng,
                )
            self._admitted += 1
            self._prompt_tokens += len(req.prompt)
            self._slots[slot] = _Slot(
                req=req, generated=[], pos=len(req.prompt),
                cursor=len(req.prompt), prefix=list(req.prompt),
                leased_at=t_admit, chunks=1,
            )
            pending.append((slot, tok))
        if pending:
            # ONE batched value fetch for every admit this tick (the
            # device sync) — the per-request int(tok) pull serialized
            # host and device once per admitted request
            first_toks = jax.device_get([t for _, t in pending])
            now = time.perf_counter()
            self._prefill_seconds += now - t_admit
            for (slot, _), tok in zip(pending, first_toks):
                st = self._slots[slot]
                st.generated.append(int(tok))
                self._generated_tokens += 1
                st.first_token_at = now
                self._record_ttft(now - st.req.enqueued_at)
                if self.tracer.enabled:
                    self.tracer.add_span(
                        "prefill", st.leased_at, now,
                        track=f"req{st.req.request_id}",
                        tokens=len(st.req.prompt), slot=slot,
                    )
                done = self._finish_reason(st)
                if done is not None:
                    finished.append(self._evict(slot, st, done))

        # ---- decode ---------------------------------------------------
        active = np.array(
            [s is not None for s in self._slots], dtype=bool
        )
        self._guard_capacity(active)
        if active.any():
            tokens = np.array(
                [s.generated[-1] if s is not None else 0
                 for s in self._slots],
                np.int32,
            )
            self._rng, rng = jax.random.split(self._rng)
            t0 = time.perf_counter()
            poison = np.zeros((self.num_slots,), np.float32)
            with profiler.annotate(
                "inference/decode", batch=int(active.sum())
            ):
                tok, bad, self.cache = self._decode_jit(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(active), jnp.asarray(poison), rng,
                )
            # value fetch = device sync
            toks, bad_h = jax.device_get((tok, bad))
            self._decode_seconds += time.perf_counter() - t0
            self._decode_steps += 1
            for slot, state in enumerate(self._slots):
                if state is None:
                    continue
                if bad_h[slot]:
                    # a genuine model blow-up on one slot quarantines
                    # it on the legacy path too (the chaos harness's
                    # injection sites thread the chunked scheduler)
                    finished.append(self._quarantine(
                        slot, state, "nonfinite logits in decode",
                    ))
                    continue
                state.pos += 1  # the input token was written this step
                state.generated.append(int(toks[slot]))
                self._generated_tokens += 1
                done = self._finish_reason(state)
                if done is not None:
                    finished.append(self._evict(slot, state, done))
        return finished

    def _finish_reason(self, state: _Slot) -> Optional[str]:
        if (
            self.eos_id is not None
            and state.generated[-1] == self.eos_id
        ):
            return "eos"
        if len(state.generated) >= state.req.max_new_tokens:
            return "length"
        if state.pos >= self.capacity:
            # the next decode would need cache position `pos`; the
            # slot is full — forced eviction, never a clamped write
            return "capacity"
        return None

    def _evict(
        self, slot: int, state: _Slot, reason: str
    ) -> GenerationResult:
        self._slots[slot] = None
        self._evicted += 1
        if self.paged:
            self._release_slot_pages(state, slot)
        self._release_adapter(state)
        finished_at = time.perf_counter()
        req = state.req
        n_new = len(state.generated)
        # a request torn down BEFORE its first token (cancel/deadline/
        # quarantine mid-prefill) has no TTFT anchor — clamp to the
        # teardown time so the record stays sane
        first_at = state.first_token_at or finished_at
        # the jsonl-ready per-request completion record: the same
        # perf_counter anchors the tracer spans and `stats()` use, so
        # the three reports can never disagree about one request
        self._record_completion({
            "request_id": req.request_id,
            "finish_reason": reason,
            "prompt_tokens": len(req.prompt),
            "new_tokens": n_new,
            "chunks": state.chunks,
            "queue_wait_ms": 1e3 * (state.leased_at - req.enqueued_at),
            "ttft_ms": 1e3 * (first_at - req.enqueued_at),
            "tpot_ms": (
                1e3 * (finished_at - first_at)
                / max(n_new - 1, 1)
            ),
            "e2e_ms": 1e3 * (finished_at - req.enqueued_at),
            "tenant": req.tenant,
        })
        if self.tracer.enabled:
            track = f"req{req.request_id}"
            self.tracer.add_span(
                "decode", first_at, finished_at,
                track=track, tokens=n_new, slot=slot,
                request_id=req.request_id, trace_id=req.trace_id,
            )
            self.tracer.instant(
                "finish", ts=finished_at, track=track, reason=reason,
                request_id=req.request_id, trace_id=req.trace_id,
            )
        return GenerationResult(
            request_id=req.request_id,
            prompt=list(req.prompt),
            tokens=list(state.generated),
            finish_reason=reason,
        )
