"""TPU-native observability (the pyprof replacement).

The reference's pyprof (reference: apex/pyprof/, deprecated in-tree)
monkey-patches torch ops to emit NVTX ranges (nvtx/nvmarker.py:1-50),
parses nvprof SQLite dumps (parse/), and maps kernels back to ops with
FLOP/byte accounting (prof/). The TPU equivalents:

* `annotate(name, **payload)` — `jax.profiler.TraceAnnotation` scopes
  carrying the op name + shape/dtype payload (the NVTX marker analogue);
* `annotate_function(fn)` — decorator form (nvmarker wraps functions);
* `trace(log_dir)` — capture context manager over `jax.profiler.trace`;
* `op_stats(log_dir)` — per-op device-time aggregation from the
  captured trace (the parse/ + prof/ analogue, reading XLA's own op
  breakdown instead of nvprof databases).
"""

import collections
import functools
import glob
import gzip
import json
import re
from typing import Any, Dict, List, Optional

import jax

__all__ = ["annotate", "annotate_function", "trace", "op_stats", "OpStat"]


def annotate(name: str, **payload):
    """Named trace scope; payload (shapes/dtypes/args) is folded into
    the annotation string like the reference's marker payload
    (reference: nvmarker.py traceMarker dict)."""
    if payload:
        name = f"{name}|{json.dumps(payload, default=str, sort_keys=True)}"
    return jax.profiler.TraceAnnotation(name)


def annotate_function(fn=None, *, name: Optional[str] = None):
    """Decorator: run `fn` inside a named scope with arg shape/dtype
    payload (the nvmarker function-wrap analogue)."""
    if fn is None:
        return functools.partial(annotate_function, name=name)

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        shapes = [
            f"{getattr(a, 'dtype', type(a).__name__)}{list(getattr(a, 'shape', []))}"
            for a in args
        ]
        with annotate(name or fn.__qualname__, args=shapes):
            return fn(*args, **kwargs)

    return wrapped


class trace:
    """`with profiler.trace('/tmp/tb'):` capture context
    (wraps jax.profiler.trace so the import point is this package)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._cm = None

    def __enter__(self):
        self._cm = jax.profiler.trace(self.log_dir)
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


class OpStat(
    collections.namedtuple(
        "OpStat",
        [
            "name", "total_ms", "count", "category",
            # pyprof-style accounting (estimates from HLO shapes):
            "flops",        # total FLOPs attributed to this op row
            "bytes",        # total HBM bytes moved (operands + outputs)
            "tflops_sec",   # achieved TFLOP/s over the row's device time
            "gb_sec",       # achieved GB/s over the row's device time
            "pct_peak",     # roofline % of peak: max(flops-, bytes-bound);
                            # 0.0 when device_kind is not in _CHIP_PEAKS
                            # (no made-up placeholder peaks)
        ],
    )
):
    __slots__ = ()


_DTYPE_BYTES = {
    "pred": 1, "s4": 0.5, "u4": 0.5, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# best-effort per-chip peaks for the roofline column (bf16 FLOPs, HBM)
_CHIP_PEAKS = {
    "v6": (918e12, 1640e9),
    "v5p": (459e12, 2765e9),
    "v5 lite": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v5": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([\d,]*)\]")


def _dtype_bytes(dt: str):
    if dt.startswith("f8"):
        return 1
    return _DTYPE_BYTES.get(dt)


def _split_result(long_name: str):
    """(result_text, rest_text) for an HLO line.

    ``%f = bf16[...]{...} fusion(...)`` → result token before the
    opcode; tuple results ``= (t1, t2) fusion(...)`` need a balanced
    paren scan because layouts contain parens (``{1,0:T(8,128)}``).
    """
    eq = long_name.find("= ")
    if eq < 0:
        return "", long_name
    body = long_name[eq + 2 :]
    if body.startswith("("):
        depth = 0
        for i, ch in enumerate(body):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return body[: i + 1], body[i + 1 :]
        return body, ""
    sp = body.find(" ")
    if sp < 0:
        return body, ""
    return body[:sp], body[sp:]


def _parse_shapes(text: str):
    """[(dtype_bytes, element_count, dims), ...] for one HLO fragment."""
    out = []
    for dt, dims_s in _SHAPE_RE.findall(text):
        size = _dtype_bytes(dt)
        if size is None:
            continue
        dims = tuple(int(d) for d in dims_s.split(",") if d)
        n = 1
        for d in dims:
            n *= d
        out.append((size, n, dims))
    return out


def _matmul_flops(out_dims, a_dims, b_dims, out_n):
    """2·|C|·k when (a, b) → out looks like a contraction.

    Transpose-agnostic dim-multiset test: for C = A·B the dims of A
    and B combined, minus C's dims, leave the contraction dim twice
    (plus batch dims once each, which C also carries). Most
    elementwise pairs fail the exactly-one-dim-left-twice test; a
    SQUARE same-shape pair ([N,N], [N,N] → [N,N]) is genuinely
    ambiguous from shapes alone and is counted as a matmul — callers
    only take this path for fusion categories XLA says carry a
    dot/conv, which is the right prior for that ambiguity.
    """
    rem = collections.Counter(a_dims) + collections.Counter(b_dims)
    rem.subtract(collections.Counter(out_dims))
    doubles = [d for d, c in rem.items() if c >= 2 and d > 1]
    if len(doubles) != 1:
        return None
    if any(c < 0 for c in rem.values()):
        return None
    return 2.0 * out_n * doubles[0]


def _event_accounting(category: str, long_name: str):
    """(flops, bytes) estimate for one device op.

    The pyprof analogue (reference: apex/pyprof/prof/blas.py, conv.py —
    per-op-class formulas from shapes). Bytes = sum of operand + result
    buffer sizes. FLOPs: fusions whose category says they carry a dot/
    conv ("convolution fusion", kOutput "custom fusion") get the
    contraction recovered by `_matmul_flops` over the two largest
    operands; everything elementwise/reduce counts one FLOP per output
    element; custom-calls (Pallas kernels) and copies claim bytes only.
    """
    res_text, ops_text = _split_result(long_name)
    results = _parse_shapes(res_text)
    operands = _parse_shapes(ops_text)
    if not results and not operands:
        return 0.0, 0.0
    nbytes = float(
        sum(s * n for s, n, _ in results)
        + sum(s * n for s, n, _ in operands)
    )
    # the LARGEST result element is the op's real output; a tuple's
    # small extras (fused probe scalars etc.) are epilogues
    out = max(results, key=lambda t: t[1]) if results else None
    out_n = out[1] if out else 0
    cat = (category or "").lower()
    if "custom-call" in cat:
        # Pallas kernels: operand shapes say nothing about internal
        # math — report the (real) HBM traffic, no FLOP claim
        return 0.0, nbytes
    if "convolution" in cat or cat == "custom fusion":
        # tuple-result elements are NOT candidate matmul operands —
        # only the true operand list qualifies
        ops = sorted(operands, key=lambda t: -t[1])
        if len(ops) >= 2 and out is not None and out_n:
            f = _matmul_flops(out[2], ops[0][2], ops[1][2], out_n)
            if f is not None:
                return f, nbytes
        return float(out_n), nbytes
    if "copy" in cat or "data formatting" in cat:
        return 0.0, nbytes
    return float(out_n), nbytes


_probed_kind = None


def _probe_device_kind() -> str:
    """Device kind for the roofline peaks, probed at most once (a live
    jax.devices() call initializes the backend — not something a pure
    trace-analysis function should do more than once, and callers can
    bypass it entirely via op_stats(device_kind=...))."""
    global _probed_kind
    if _probed_kind is None:
        try:
            _probed_kind = getattr(
                jax.devices()[0], "device_kind", ""
            ).lower()
        except Exception:  # no live backend: kind unknown, pct_peak=0.0
            _probed_kind = ""
    return _probed_kind


def op_stats(
    log_dir: str,
    top: int = 0,
    merge_numeric_suffix: bool = True,
    device_kind: Optional[str] = None,
) -> List[OpStat]:
    """Aggregate per-op device time + FLOP/byte/roofline accounting
    from the newest capture in `log_dir` (reads the trace.json.gz
    XLA-op timeline; the pyprof parse/prof analogue).
    `merge_numeric_suffix` folds fusion.12 / fusion.34 into one row;
    `device_kind` overrides the peak table row (e.g. "tpu v5e") for
    offline analysis."""
    files = sorted(
        glob.glob(f"{log_dir}/plugins/profile/*/*.trace.json.gz")
    )
    if not files:
        raise FileNotFoundError(f"no captured trace under {log_dir}")
    with gzip.open(files[-1]) as f:
        data = json.load(f)

    names: Dict[Any, str] = {}
    tids: Dict[Any, str] = {}
    for e in data.get("traceEvents", []):
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                names[e["pid"]] = e["args"].get("name", "")
            elif e.get("name") == "thread_name":
                tids[(e["pid"], e["tid"])] = e["args"].get("name", "")
    # any process with an "XLA Ops" thread is a device timeline (TPU
    # process names on the tunnel platform; CPU traces lack them)
    device_pids = {
        p for (p, t), n in tids.items() if n == "XLA Ops"
    } | {p for p, n in names.items() if "TPU" in n or "GPU" in n}

    if device_kind is None:
        device_kind = _probe_device_kind()
    peak_f = peak_b = None
    device_kind = device_kind.lower()  # _probe_device_kind lowercases too
    for key, (pf, pb) in _CHIP_PEAKS.items():
        if key in device_kind:
            peak_f, peak_b = pf, pb
            break
    # unknown chip: pct_peak stays 0.0 rather than being computed
    # against made-up peaks (achieved TFLOP/s + GB/s columns still hold)

    tot = collections.Counter()
    cnt = collections.Counter()
    flops = collections.Counter()
    nbytes = collections.Counter()
    cat = {}
    for e in data.get("traceEvents", []):
        if (
            e.get("ph") == "X"
            and e.get("dur", 0) > 0
            and e.get("pid") in device_pids
            and tids.get((e["pid"], e["tid"])) == "XLA Ops"
        ):
            base = e["name"]
            if merge_numeric_suffix:
                base = re.sub(r"[.\d]+$", "", base)
            args = e.get("args") or {}
            tot[base] += e["dur"]
            cnt[base] += 1
            cat.setdefault(base, args.get("hlo_category", ""))
            # account with THIS event's category: merged rows can mix
            # categories (fusion.1 loop fusion, fusion.2 conv fusion)
            f, b = _event_accounting(
                args.get("hlo_category", "") or base,
                args.get("long_name", ""),
            )
            flops[base] += f
            nbytes[base] += b

    def row(n):
        ms = tot[n] / 1e3
        sec = ms / 1e3
        tf = flops[n] / sec / 1e12 if sec else 0.0
        gb = nbytes[n] / sec / 1e9 if sec else 0.0
        if peak_f is None or not sec:
            pct = 0.0
        else:
            pct = max(
                flops[n] / sec / peak_f,
                nbytes[n] / sec / peak_b,
            ) * 100.0
        return OpStat(
            n, ms, cnt[n], cat.get(n, ""),
            flops[n], nbytes[n], round(tf, 3), round(gb, 2), round(pct, 2),
        )

    stats = [row(n) for n in tot]
    stats.sort(key=lambda s: -s.total_ms)
    return stats[:top] if top else stats
