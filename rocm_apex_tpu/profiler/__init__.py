"""TPU-native observability (the pyprof replacement).

The reference's pyprof (reference: apex/pyprof/, deprecated in-tree)
monkey-patches torch ops to emit NVTX ranges (nvtx/nvmarker.py:1-50),
parses nvprof SQLite dumps (parse/), and maps kernels back to ops with
FLOP/byte accounting (prof/). The TPU equivalents:

* `annotate(name, **payload)` — `jax.profiler.TraceAnnotation` scopes
  carrying the op name + shape/dtype payload (the NVTX marker analogue);
* `annotate_function(fn)` — decorator form (nvmarker wraps functions);
* `trace(log_dir)` — capture context manager over `jax.profiler.trace`;
* `op_stats(log_dir)` — per-op device-time aggregation from the
  captured trace (the parse/ + prof/ analogue, reading XLA's own op
  breakdown instead of nvprof databases).
"""

import collections
import functools
import glob
import gzip
import json
import re
from typing import Any, Dict, List, Optional

import jax

__all__ = ["annotate", "annotate_function", "trace", "op_stats", "OpStat"]


def annotate(name: str, **payload):
    """Named trace scope; payload (shapes/dtypes/args) is folded into
    the annotation string like the reference's marker payload
    (reference: nvmarker.py traceMarker dict)."""
    if payload:
        name = f"{name}|{json.dumps(payload, default=str, sort_keys=True)}"
    return jax.profiler.TraceAnnotation(name)


def annotate_function(fn=None, *, name: Optional[str] = None):
    """Decorator: run `fn` inside a named scope with arg shape/dtype
    payload (the nvmarker function-wrap analogue)."""
    if fn is None:
        return functools.partial(annotate_function, name=name)

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        shapes = [
            f"{getattr(a, 'dtype', type(a).__name__)}{list(getattr(a, 'shape', []))}"
            for a in args
        ]
        with annotate(name or fn.__qualname__, args=shapes):
            return fn(*args, **kwargs)

    return wrapped


class trace:
    """`with profiler.trace('/tmp/tb'):` capture context
    (wraps jax.profiler.trace so the import point is this package)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._cm = None

    def __enter__(self):
        self._cm = jax.profiler.trace(self.log_dir)
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


class OpStat(
    collections.namedtuple("OpStat", ["name", "total_ms", "count", "category"])
):
    __slots__ = ()


def op_stats(
    log_dir: str, top: int = 0, merge_numeric_suffix: bool = True
) -> List[OpStat]:
    """Aggregate per-op device time from the newest capture in
    `log_dir` (reads the trace.json.gz XLA-op timeline; the pyprof
    parse/prof analogue). `merge_numeric_suffix` folds fusion.12 /
    fusion.34 into one row."""
    files = sorted(
        glob.glob(f"{log_dir}/plugins/profile/*/*.trace.json.gz")
    )
    if not files:
        raise FileNotFoundError(f"no captured trace under {log_dir}")
    with gzip.open(files[-1]) as f:
        data = json.load(f)

    names: Dict[Any, str] = {}
    tids: Dict[Any, str] = {}
    for e in data.get("traceEvents", []):
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                names[e["pid"]] = e["args"].get("name", "")
            elif e.get("name") == "thread_name":
                tids[(e["pid"], e["tid"])] = e["args"].get("name", "")
    # any process with an "XLA Ops" thread is a device timeline (TPU
    # process names on the tunnel platform; CPU traces lack them)
    device_pids = {
        p for (p, t), n in tids.items() if n == "XLA Ops"
    } | {p for p, n in names.items() if "TPU" in n or "GPU" in n}

    tot = collections.Counter()
    cnt = collections.Counter()
    cat = {}
    for e in data.get("traceEvents", []):
        if (
            e.get("ph") == "X"
            and e.get("dur", 0) > 0
            and e.get("pid") in device_pids
            and tids.get((e["pid"], e["tid"])) == "XLA Ops"
        ):
            base = e["name"]
            if merge_numeric_suffix:
                base = re.sub(r"[.\d]+$", "", base)
            tot[base] += e["dur"]
            cnt[base] += 1
            cat.setdefault(
                base, (e.get("args") or {}).get("hlo_category", "")
            )

    stats = [
        OpStat(n, tot[n] / 1e3, cnt[n], cat.get(n, ""))
        for n in tot
    ]
    stats.sort(key=lambda s: -s.total_ms)
    return stats[:top] if top else stats
