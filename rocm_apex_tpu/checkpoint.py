"""Checkpoint / resume, preemption-aware.

The reference's checkpoint story has three pieces (SURVEY.md §5): amp
scaler state via `amp.state_dict()` (reference: apex/amp/frontend.py:
428-467 — implemented in rocm_apex_tpu.amp), model/optimizer state via
standard torch saves, and an ADLR autoresume hook that is referenced
but never wired (reference: pipeline_parallel/utils.py:131). Here the
model/optimizer piece is orbax (atomic, async-capable, sharding-aware —
the TPU-native torch.save) and autoresume is an actual API:

    mgr = CheckpointManager(dir, max_to_keep=3)
    state = mgr.restore_or(init_fn)          # resume if anything exists
    ...
    mgr.save(step, state)                    # atomic, retention-pruned
    if mgr.should_exit():                    # preemption signal seen
        mgr.save(step, state, force=True); sys.exit(0)
"""

import os
import signal
import threading
from typing import Any, Callable, Optional

import orbax.checkpoint as ocp

__all__ = ["CheckpointManager", "save_pytree", "restore_pytree"]


def save_pytree(path: str, tree: Any) -> None:
    """One-shot atomic pytree save (the torch.save analogue)."""
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(path), tree, force=True)


def restore_pytree(path: str, template: Optional[Any] = None) -> Any:
    """Restore a pytree; `template` restores into matching
    shapes/dtypes/shardings when given."""
    ckptr = ocp.PyTreeCheckpointer()
    if template is not None:
        return ckptr.restore(os.path.abspath(path), item=template)
    return ckptr.restore(os.path.abspath(path))


class CheckpointManager:
    """Stepped checkpoints with retention + preemption awareness.

    The autoresume capability the reference stubs out
    (get_autoresume/check_and_exit semantics of Megatron's ADLR hook):
    SIGTERM — the preemption notice on TPU VMs — flips `should_exit()`
    so the training loop can save and leave cleanly.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        install_sigterm_handler: bool = True,
    ):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._exit = threading.Event()
        if install_sigterm_handler and threading.current_thread() is threading.main_thread():
            try:
                prev = signal.getsignal(signal.SIGTERM)

                def _handler(signum, frame):
                    self._exit.set()
                    if callable(prev):
                        prev(signum, frame)

                signal.signal(signal.SIGTERM, _handler)
            except (ValueError, OSError):
                pass  # non-main context: should_exit() stays manual

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.PyTreeSave(state), force=force
        )
        if force:
            self._mgr.wait_until_finished()
        return saved

    def restore(self, step: Optional[int] = None, template: Any = None):
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        if template is not None:
            return self._mgr.restore(
                step, args=ocp.args.PyTreeRestore(template)
            )
        return self._mgr.restore(step)

    def restore_or(self, init_fn: Callable[[], Any], template: Any = None):
        """Resume from the latest checkpoint or build fresh state —
        the autoresume entry point."""
        if self.latest_step() is None:
            return init_fn()
        return self.restore(template=template)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def should_exit(self) -> bool:
        """True once a preemption notice (SIGTERM) arrived."""
        return self._exit.is_set()

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
