"""Context/sequence parallelism: ring attention + Ulysses all-to-all.

The reference has NO context parallelism (SURVEY.md §5: long-context
support there stops at seqlen-2048 softmax kernels + activation
checkpointing); this module is the capability the mesh design makes
natural — long sequences sharded over a ``context`` axis with two
interchangeable strategies:

* **ring attention** (`ring_flash_attention`): K/V shards rotate around
  the axis via `ppermute`; each hop computes a flash partial (o, lse)
  against the resident K/V block and the partials merge with the
  log-sum-exp rule. Peak memory per chip is O(s_local); the ring hides
  transfer behind compute the same way the published ring-attention
  schedules do, with XLA overlapping the collective.
* **Ulysses / all-to-all** (`ulysses_attention`): `all_to_all` swaps the
  sharded dimension from sequence to heads, each chip runs ordinary
  flash attention on full sequences for its head subset, and a second
  `all_to_all` swaps back. Cheaper collectives when heads >= axis size.

Both run inside `shard_map` with the context axis bound (sequence
sharded contiguously in axis order), are causal-correct across shards,
and differentiate through (the ppermute/all_to_all transpose is the
reverse collective; flash partial grads use the lse cotangent path).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from rocm_apex_tpu.ops.flash_attention import flash_attention_with_lse
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = ["ring_flash_attention", "ulysses_attention"]


def _merge(o1, lse1, o2, lse2):
    """Combine two disjoint-key partials: the online-softmax rule.
    Safe when both partials are empty (lse = -inf): weights become 0
    instead of exp(-inf - -inf) = nan."""
    lse = jnp.logaddexp(lse1, lse2)
    safe = jnp.where(jnp.isneginf(lse), 0.0, lse)
    w1 = jnp.exp(lse1 - safe)[..., None]
    w2 = jnp.exp(lse2 - safe)[..., None]
    return o1.astype(jnp.float32) * w1 + o2.astype(jnp.float32) * w2, lse


def ring_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = parallel_state.CONTEXT_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Flash attention over a sequence sharded on `axis_name`.

    Operands are the LOCAL shards (bh, s_local, d), sequence split
    contiguously in axis order (rank r holds tokens
    [r*s_local, (r+1)*s_local)). Returns the local output shard.
    """
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    bh, s_loc, dh = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def full_fn(kv):
        kc, vc = kv
        return flash_attention_with_lse(q, kc, vc, None, False, scale)

    def tri_fn(kv):
        kc, vc = kv
        return flash_attention_with_lse(q, kc, vc, None, True, scale)

    def skip_fn(kv):
        return (
            jnp.zeros_like(q),
            jnp.full((bh, s_loc), -jnp.inf, jnp.float32),
        )

    def body(carry, i):
        kc, vc, o, lse = carry
        src = (my - i) % n  # which rank's block currently resides here
        if causal:
            # src <  my: keys strictly in the past -> full attention
            # src == my: the diagonal block -> causal triangle
            # src >  my: the future -> contributes nothing
            case = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            o_i, lse_i = jax.lax.switch(
                case, [full_fn, tri_fn, skip_fn], (kc, vc)
            )
        else:
            o_i, lse_i = full_fn((kc, vc))
        o, lse = _merge(o, lse, o_i, lse_i)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, o, lse), None

    o0 = jnp.zeros((bh, s_loc, dh), jnp.float32)
    lse0 = jnp.full((bh, s_loc), -jnp.inf, jnp.float32)
    (_, _, o, _), _ = jax.lax.scan(
        body, (k, v, o0, lse0), jnp.arange(n)
    )
    return o.astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = parallel_state.CONTEXT_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Operands are local shards (b, s_local, h, d) with the FULL head
    count; `h` must be divisible by the axis size. Internally the
    sharding swaps seq->heads, local flash attention runs over the full
    sequence for h/n heads, and the output swaps back. Returns
    (b, s_local, h, d).
    """
    n = axis_size(axis_name)
    b, s_loc, h, dh = q.shape
    if h % n:
        raise ValueError(f"num heads {h} not divisible by axis size {n}")

    def seq_to_heads(x):
        # (b, s_loc, h, d) -> (b, n*s_loc, h/n, d)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    s_full, h_loc = qg.shape[1], qg.shape[2]

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h_loc, s_full, dh)

    o, _ = flash_attention_with_lse(
        flat(qg), flat(kg), flat(vg), None, causal, scale
    )
    o = o.reshape(b, h_loc, s_full, dh).transpose(0, 2, 1, 3)
    return heads_to_seq(o)
