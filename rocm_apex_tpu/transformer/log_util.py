"""Per-module logging helpers.

Reference: apex/transformer/log_util.py — `get_transformer_logger` with
env-controlled level, plus `set_logging_level`.
"""

import logging
import os

__all__ = ["get_transformer_logger", "set_logging_level"]

_ENV = "APEX_TPU_LOG_LEVEL"


def get_transformer_logger(name: str) -> logging.Logger:
    name = name.rsplit(".", 1)[-1]
    logger = logging.getLogger(f"rocm_apex_tpu.transformer.{name}")
    level = os.environ.get(_ENV)
    if level:
        logger.setLevel(level.upper())
    return logger


def set_logging_level(verbosity) -> None:
    logging.getLogger("rocm_apex_tpu.transformer").setLevel(verbosity)
