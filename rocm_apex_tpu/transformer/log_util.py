"""Per-module logging helpers.

Reference: apex/transformer/log_util.py — `get_transformer_logger` with
env-controlled level, plus `set_logging_level`.
"""

import logging
import os

__all__ = ["get_transformer_logger", "set_logging_level"]

_ENV = "APEX_TPU_LOG_LEVEL"
_ROOT = "rocm_apex_tpu.transformer"
# read once at import: the env var is a process-level setting, and the
# previous per-call read meant a logger could flip level mid-run when
# the environment mutated (and paid a getenv on every getLogger)
_ENV_LEVEL = os.environ.get(_ENV)


def get_transformer_logger(name: str) -> logging.Logger:
    """Logger for ``name`` (pass ``__name__``) nested under the
    ``rocm_apex_tpu.transformer`` root.

    The FULL dotted path is kept: the old ``rsplit('.', 1)[-1]``
    basename collapsed distinct modules with the same final component
    (any two ``utils`` modules shared one logger, so a level set for
    one silenced the other). Package-internal names drop the redundant
    ``rocm_apex_tpu.``/``rocm_apex_tpu.transformer.`` prefix; anything
    else nests verbatim — distinct modules always get distinct loggers,
    and `set_logging_level` on the root still reaches all of them."""
    if name == _ROOT:
        name = ""
    for prefix in (_ROOT + ".", "rocm_apex_tpu."):
        if name.startswith(prefix):
            name = name[len(prefix):]
            break
    logger = logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)
    if _ENV_LEVEL:
        logger.setLevel(_ENV_LEVEL.upper())
    return logger


def set_logging_level(verbosity) -> None:
    logging.getLogger(_ROOT).setLevel(verbosity)
