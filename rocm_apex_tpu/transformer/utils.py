"""Small tensor utilities shared by the transformer subpackage.

Reference: apex/transformer/utils.py (ensure_divisibility, divide,
split_tensor_into_1d_equal_chunks, gather_split_1d_tensor) and
apex/transformer/tensor_parallel/utils.py (split_tensor_along_last_dim,
VocabUtility).
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from rocm_apex_tpu.utils.compat import axis_size

__all__ = [
    "ensure_divisibility",
    "divide",
    "split_tensor_along_last_dim",
    "split_tensor_into_1d_equal_chunks",
    "gather_split_1d_tensor",
    "VocabUtility",
]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    """Reference: apex/transformer/utils.py:24-27."""
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Reference: apex/transformer/utils.py:30-34."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(
    tensor: jnp.ndarray, num_partitions: int
) -> Tuple[jnp.ndarray, ...]:
    """Split a tensor along its last dimension.

    Reference: apex/transformer/tensor_parallel/utils.py:20-37. JAX arrays
    are immutable so the reference's `contiguous_split_chunks` flag is
    meaningless here; splits are views until XLA materializes them.
    """
    last = tensor.shape[-1]
    divide(last, num_partitions)
    return tuple(jnp.split(tensor, num_partitions, axis=-1))


def split_tensor_into_1d_equal_chunks(tensor: jnp.ndarray, axis_name: str):
    """Flatten and take this rank's 1/N chunk (used by the pipeline P2P
    scatter-gather bandwidth optimization).

    Reference: apex/transformer/utils.py:37-48. Must run inside shard_map
    with `axis_name` bound.
    """
    flat = tensor.reshape(-1)
    n = axis_size(axis_name)
    chunk = divide(flat.shape[0], n)
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(flat, rank * chunk, chunk, axis=0)


def gather_split_1d_tensor(tensor: jnp.ndarray, axis_name: str):
    """Inverse of split_tensor_into_1d_equal_chunks.

    Reference: apex/transformer/utils.py:51-61.
    """
    return jax.lax.all_gather(tensor, axis_name, axis=0, tiled=True)


class VocabUtility:
    """Vocab range bookkeeping for vocab-parallel layers.

    Reference: apex/transformer/tensor_parallel/utils.py:40-54.
    """

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ):
        index_f = rank * per_partition_vocab_size
        index_l = index_f + per_partition_vocab_size
        return index_f, index_l

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank, world_size: int):
        per_partition_vocab_size = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size, rank, world_size
        )
