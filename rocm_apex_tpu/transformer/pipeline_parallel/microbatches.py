"""Number-of-microbatches calculators (constant + batch-size rampup).

Same semantics as the reference calculators
(reference: apex/transformer/microbatches.py:21-172): the number of
microbatches per step is ``global_batch // (micro_batch * dp)``, and the
rampup variant grows the global batch linearly from ``start_batch_size``
to ``global_batch_size`` in ``batch_size_increment`` steps spread evenly
over ``rampup_samples`` consumed samples. Pure host-side Python — the
resulting count is a *static* trip count for the jitted pipeline (a
changed count triggers a recompile, which is the XLA-correct way to
express a ramp: a handful of compilations, each with static shapes).
"""

from abc import ABC, abstractmethod
from typing import List, Optional

from rocm_apex_tpu import logger

__all__ = [
    "build_num_microbatches_calculator",
    "NumMicroBatchesCalculator",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
]


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> "NumMicroBatchesCalculator":
    """Factory (reference: microbatches.py:21-66)."""
    if rampup_batch_size is None:
        calc = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
        if rank == 0:
            logger.info(
                "setting number of micro-batches to constant %d", calc.get()
            )
        return calc
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "expected rampup_batch_size = [start, increment, rampup_samples], "
            f"got {rampup_batch_size!r}"
        )
    start, inc, samples = (int(v) for v in rampup_batch_size)
    if rank == 0:
        logger.info(
            "batch size rampup: %d -> %d in increments of %d over %d samples",
            start,
            global_batch_size,
            inc,
            samples,
        )
    return RampupBatchsizeNumMicroBatches(
        start, inc, samples, global_batch_size, micro_batch_size, data_parallel_size
    )


class NumMicroBatchesCalculator(ABC):
    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    @abstractmethod
    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        ...


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """reference: microbatches.py:84-99."""

    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        per_step = micro_batch_size * data_parallel_size
        if global_batch_size % per_step != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel "
                f"size ({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // per_step
        if self.num_micro_batches < 1:
            raise ValueError("need at least one microbatch")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear global-batch ramp (reference: microbatches.py:101-172)."""

    def __init__(
        self,
        start_batch_size: int,
        batch_size_increment: int,
        rampup_samples: int,
        global_batch_size: int,
        micro_batch_size: int,
        data_parallel_size: int,
    ):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )
        if start_batch_size <= 0 or batch_size_increment <= 0:
            raise ValueError("start_batch_size and increment must be positive")
        self.start_batch_size = start_batch_size
        self.global_batch_size = global_batch_size
        diff = global_batch_size - start_batch_size
        if diff < 0 or diff % batch_size_increment != 0:
            raise ValueError(
                f"global batch size interval ({diff}) must be a non-negative "
                f"multiple of the increment ({batch_size_increment})"
            )
        self.batch_size_increment = batch_size_increment
        self.rampup_samples = rampup_samples
        if rampup_samples < 0:
            raise ValueError("rampup_samples must be >= 0")
        num_increments = max(diff // batch_size_increment, 1)
        self.rampup_samples_per_increment = rampup_samples / num_increments
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if consumed_samples >= self.rampup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            assert self.current_global_batch_size <= self.global_batch_size
        if consistency_check and (
            self.current_global_batch_size
            % self.micro_batch_times_data_parallel_size
            != 0
        ):
            raise ValueError(
                f"current global batch size ({self.current_global_batch_size}) "
                f"is not divisible by micro-batch-size "
                f"({self.micro_batch_size}) times data parallel size "
                f"({self.data_parallel_size})"
            )
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size
        )
