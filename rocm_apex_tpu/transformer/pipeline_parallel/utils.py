"""Pipeline-parallel utilities: microbatch singleton, loss averaging,
norms, masks, memory reporting.

TPU-native rebuild of the reference utils
(reference: apex/transformer/pipeline_parallel/utils.py). Collective
helpers are mesh-axis functions usable inside shard_map; mask/position
construction is vectorized jnp (the reference loops over the batch in
python, utils.py:279-333 — that pattern would be a trace-time
catastrophe under jit).
"""

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer.pipeline_parallel.microbatches import (
    build_num_microbatches_calculator,
)
from rocm_apex_tpu.utils.compat import axis_size

__all__ = [
    "setup_microbatch_calculator",
    "get_micro_batch_size",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "average_losses_across_data_parallel_group",
    "calc_params_l2_norm",
    "get_ltor_masks_and_position_ids",
    "report_memory",
    "param_min_max_norm_table",
]

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """Install the singleton (reference: utils.py:57-88)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size, data_parallel_size
    )


def _destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def _require_calculator():
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None:
        raise RuntimeError(
            "microbatch calculator is not initialized; call "
            "setup_microbatch_calculator first"
        )
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def get_micro_batch_size() -> int:
    return _require_calculator().micro_batch_size


def get_num_microbatches() -> int:
    """reference: utils.py:91-93."""
    return _require_calculator().get()


def get_current_global_batch_size() -> int:
    return _require_calculator().get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, consistency_check: bool = True):
    _require_calculator().update(consumed_samples, consistency_check)


def average_losses_across_data_parallel_group(
    losses: Sequence[jnp.ndarray], axis_name: Optional[str] = None
) -> jnp.ndarray:
    """pmean of stacked losses over the data axis
    (reference: utils.py:218-227). Must run inside shard_map."""
    axis = axis_name or parallel_state.DATA_AXIS
    stacked = jnp.stack([jnp.reshape(l, ()) for l in losses])
    return jax.lax.pmean(stacked, axis)


def calc_params_l2_norm(
    params: Any,
    model_axis_names: Sequence[str] = (
        parallel_state.TENSOR_AXIS,
        parallel_state.PIPE_AXIS,
    ),
    *,
    exclude_replicated: Optional[Any] = None,
) -> jnp.ndarray:
    """Global param L2 norm across model-parallel shards
    (reference: utils.py:189-215 — local multi_tensor_l2norm, square,
    all-reduce over the model group, sqrt).

    ``exclude_replicated``: optional bool pytree marking leaves that are
    REPLICATED across tensor parallel ranks (the analogue of the
    reference's `param_is_not_tensor_parallel_duplicate` filter) — those
    contribute from one logical copy only, by dividing their square by
    the tensor axis size.
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if exclude_replicated is not None:
        repl = jax.tree_util.tree_leaves(exclude_replicated)
    else:
        repl = [False] * len(leaves)

    bound = []
    for ax in model_axis_names:
        try:
            axis_size(ax)
            bound.append(ax)
        except NameError:
            pass

    tp_size = 1.0
    if parallel_state.TENSOR_AXIS in bound:
        tp_size = axis_size(parallel_state.TENSOR_AXIS)

    total = jnp.zeros((), jnp.float32)
    for leaf, is_repl in zip(leaves, repl):
        sq = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        if is_repl:
            sq = sq / tp_size
        total = total + sq
    for ax in bound:
        total = jax.lax.psum(total, ax)
    return jnp.sqrt(total)


def get_ltor_masks_and_position_ids(
    data: jnp.ndarray,
    eod_token: int,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Causal masks / loss mask / position ids for left-to-right LMs.

    Semantics of reference utils.py:279-333, vectorized: attention mask
    True = MASKED (matches the reference's final `< 0.5` binarization);
    document-boundary resets use cumulative-EOD counts instead of the
    reference's per-batch python loops.
    """
    micro_batch_size, seq_length = data.shape

    causal = ~jnp.tril(jnp.ones((seq_length, seq_length), bool))

    is_eod = data == eod_token
    # eod_count[b, i] = number of EOD tokens at positions < i.
    eod_before = jnp.cumsum(is_eod, axis=1) - is_eod.astype(jnp.int32)

    if reset_attention_mask:
        # Token i may attend to j iff same document: equal eod-prefix
        # counts (documents are delimited by EOD; position i+1 onward
        # must not see ≤ i of a previous doc, reference utils.py:318-320).
        same_doc = eod_before[:, :, None] == eod_before[:, None, :]
        attention_mask = (causal[None] | ~same_doc)[:, None, :, :]
    else:
        attention_mask = jnp.broadcast_to(
            causal[None, None], (1, 1, seq_length, seq_length)
        )

    loss_mask = jnp.ones(data.shape, jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(is_eod, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(
        jnp.arange(seq_length)[None], data.shape
    )
    if reset_position_ids:
        # Position restarts after each EOD: subtract the index just past
        # the most recent EOD (reference utils.py:322-325).
        idx = jnp.arange(seq_length)[None]
        last_eod_plus1 = jnp.where(is_eod, idx + 1, 0)
        doc_start = jax.lax.associative_scan(jnp.maximum, last_eod_plus1, axis=1)
        # shift right: position i belongs to the doc started at the last
        # EOD strictly before i.
        doc_start = jnp.concatenate(
            [jnp.zeros((micro_batch_size, 1), doc_start.dtype), doc_start[:, :-1]],
            axis=1,
        )
        position_ids = position_ids - doc_start

    return attention_mask, loss_mask, position_ids


def report_memory(name: str) -> str:
    """Device memory report (reference: utils.py:229-240 uses
    torch.cuda counters; here `device.memory_stats()`)."""
    mega = 1024.0 * 1024.0
    lines = [f"{name} memory (MB)"]
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        in_use = stats.get("bytes_in_use", 0) / mega
        peak = stats.get("peak_bytes_in_use", 0) / mega
        limit = stats.get("bytes_limit", 0) / mega
        lines.append(
            f" | {d.platform}:{d.id} allocated: {in_use:.1f}"
            f" | peak: {peak:.1f} | limit: {limit:.1f}"
        )
    out = "".join(lines)
    from rocm_apex_tpu import logger

    logger.info(out)
    return out


def param_min_max_norm_table(params: Any, iteration: int = 0) -> str:
    """min/max/norm per parameter (reference: utils.py:241-277)."""
    rows = ["iteration, index, min, max, norm"]
    flat = jax.tree_util.tree_leaves_with_path(params)
    for i, (path, leaf) in enumerate(flat):
        leaf = jnp.asarray(leaf)
        rows.append(
            f"{iteration:7d}, {i:4d}, {float(leaf.min()):.6E}, "
            f"{float(leaf.max()):.6E}, "
            f"{float(jnp.linalg.norm(leaf.astype(jnp.float32))):.6E}"
        )
    return "\n".join(rows)
