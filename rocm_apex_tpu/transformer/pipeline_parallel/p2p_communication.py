"""Stage-to-stage activation transfer over the ``pipe`` mesh axis.

TPU-native rebuild of the reference's P2P layer
(reference: apex/transformer/pipeline_parallel/p2p_communication.py).
The reference batches `torch.distributed.isend/irecv` pairs between
neighbouring pipeline processes (`_run_p2pops:31-69` →
`batch_isend_irecv:67`) and optimizes bandwidth by scattering payloads
over the TP ranks before sending and all-gathering after receipt
(`:116-119,152-157`). Here every transfer is a single
`jax.lax.ppermute` over the ``pipe`` axis executed by all stages at
once — XLA lowers it to ICI neighbour exchange and overlaps it with
compute, which is precisely what the reference's hand-built
send/recv-both-directions batching simulates. The scatter-gather
optimization is kept as an opt-in (`scatter_gather_tensors_in_pipeline`)
that shards the payload's last dim over ``tensor`` around the permute.

The reference's fp32-payload policy (`:130-134`, a RCCL workaround) is
deliberately NOT replicated: ICI transfers any dtype; payloads travel in
their native dtype.

All functions must run inside shard_map with the pipe axis bound. The
forward direction is stage i → i+1; the backward direction is
stage i → i−1. Ring variants wrap around (used by the circular
interleaved schedule).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = [
    "send_forward",
    "send_backward",
    "send_forward_recv_backward",
    "send_backward_recv_forward",
    "ring_forward",
    "ring_backward",
]


def _fwd_perm(p, wrap):
    pairs = [(i, i + 1) for i in range(p - 1)]
    if wrap:
        pairs.append((p - 1, 0))
    return pairs


def _bwd_perm(p, wrap):
    pairs = [(i, i - 1) for i in range(1, p)]
    if wrap:
        pairs.append((0, p - 1))
    return pairs


def _permute_tree(tree: Any, axis_name: str, perm) -> Any:
    return jax.tree_util.tree_map(
        lambda x: jax.lax.ppermute(x, axis_name, perm), tree
    )


def _scatter(x, tensor_axis):
    tp = axis_size(tensor_axis)
    if x.shape[-1] % tp != 0:
        raise ValueError(
            f"scatter_gather transfer needs last dim {x.shape[-1]} divisible "
            f"by tensor size {tp}"
        )
    r = jax.lax.axis_index(tensor_axis)
    chunk = x.shape[-1] // tp
    return jax.lax.dynamic_slice_in_dim(x, r * chunk, chunk, axis=x.ndim - 1)


def _gather(x, tensor_axis):
    return jax.lax.all_gather(x, tensor_axis, axis=x.ndim - 1, tiled=True)


def _transfer(
    tree: Any,
    perm,
    axis_name: Optional[str],
    scatter_gather: bool,
    tensor_axis: Optional[str],
) -> Any:
    axis = axis_name or parallel_state.PIPE_AXIS
    if scatter_gather:
        taxis = tensor_axis or parallel_state.TENSOR_AXIS
        tree = jax.tree_util.tree_map(lambda x: _scatter(x, taxis), tree)
        tree = _permute_tree(tree, axis, perm)
        return jax.tree_util.tree_map(lambda x: _gather(x, taxis), tree)
    return _permute_tree(tree, axis, perm)


def send_forward(
    output_tensor: Any,
    axis_name: Optional[str] = None,
    *,
    scatter_gather_tensors_in_pipeline: bool = False,
    tensor_axis: Optional[str] = None,
) -> Any:
    """Shift activations one stage forward (i → i+1); every stage's
    return value is what it *received* from its predecessor (stage 0
    receives zeros). Combines the reference's send_forward/recv_forward
    pair (p2p_communication.py:188-260) — in SPMD both sides are one op.
    """
    p = axis_size(axis_name or parallel_state.PIPE_AXIS)
    return _transfer(
        output_tensor,
        _fwd_perm(p, wrap=False),
        axis_name,
        scatter_gather_tensors_in_pipeline,
        tensor_axis,
    )


# Aliases expressing the receiving side of the same collective, for
# call-site readability parity with the reference API.
recv_forward = send_forward


def send_backward(
    input_tensor_grad: Any,
    axis_name: Optional[str] = None,
    *,
    scatter_gather_tensors_in_pipeline: bool = False,
    tensor_axis: Optional[str] = None,
) -> Any:
    """Shift gradients one stage backward (i → i−1); the last stage
    receives zeros. (reference: p2p_communication.py:263-311)."""
    p = axis_size(axis_name or parallel_state.PIPE_AXIS)
    return _transfer(
        input_tensor_grad,
        _bwd_perm(p, wrap=False),
        axis_name,
        scatter_gather_tensors_in_pipeline,
        tensor_axis,
    )


recv_backward = send_backward


def send_forward_recv_backward(
    output_tensor: Any,
    input_tensor_grad: Any,
    axis_name: Optional[str] = None,
    **kw,
):
    """Both directions in one step (reference: p2p_communication.py:314-404
    batches the isend/irecv pairs; XLA fuses the two ppermutes the same
    way). Returns (received_forward, received_backward)."""
    return (
        send_forward(output_tensor, axis_name, **kw),
        send_backward(input_tensor_grad, axis_name, **kw),
    )


def send_backward_recv_forward(
    input_tensor_grad: Any,
    output_tensor: Any,
    axis_name: Optional[str] = None,
    **kw,
):
    fwd, bwd = send_forward_recv_backward(
        output_tensor, input_tensor_grad, axis_name, **kw
    )
    return bwd, fwd


def ring_forward(tree: Any, axis_name: Optional[str] = None, **kw) -> Any:
    """Forward shift with wrap-around (P−1 → 0): the circular-pipeline
    transfer used by the interleaved schedule, where crossing the wrap
    advances the virtual chunk index."""
    p = axis_size(axis_name or parallel_state.PIPE_AXIS)
    return _transfer(
        tree,
        _fwd_perm(p, wrap=True),
        axis_name,
        kw.get("scatter_gather_tensors_in_pipeline", False),
        kw.get("tensor_axis"),
    )


def ring_backward(tree: Any, axis_name: Optional[str] = None, **kw) -> Any:
    p = axis_size(axis_name or parallel_state.PIPE_AXIS)
    return _transfer(
        tree,
        _bwd_perm(p, wrap=True),
        axis_name,
        kw.get("scatter_gather_tensors_in_pipeline", False),
        kw.get("tensor_axis"),
    )
