"""The three pipeline schedules, as SPMD scan-over-ppermute programs.

TPU-native redesign of the reference's schedule trio
(reference: apex/transformer/pipeline_parallel/schedules/ — dispatcher
`__init__.py:16-34`, `fwd_bwd_no_pipelining.py:29`, 1F1B
`fwd_bwd_pipelining_without_interleaving.py:22-170`, interleaved
`fwd_bwd_pipelining_with_interleaving.py:41-308`). The reference runs a
*per-rank asymmetric* program: warmup = P−rank−1 forwards, a steady
1F1B phase of paired send_forward_recv_backward, and a cooldown of
backwards, all over NCCL P2P. Single-controller JAX cannot (and should
not) express per-rank control flow; instead each schedule here is one
SPMD program in which every stage runs the same `lax.scan` and
activations hop stages via `lax.ppermute`:

* tick ``t``: stage ``s`` computes microbatch ``t−s`` (when valid) and
  the permute hands its output to ``s+1`` — exactly the reference's
  pipeline diagram, with warmup/steady/cooldown appearing as the
  triangular valid-regions of the scan rather than as python phases;
* training runs the TRUE 1F1B: ONE non-differentiated scan interleaves
  a forward and a backward unit per tick (`_one_pass_interleaved`),
  building gradients inside the scan via per-tick `jax.vjp` — stage
  inputs wait in an O(P)-slot ring, activation cotangents ride a
  reverse ppermute, and live activations are bounded by the pipeline
  depth, not the microbatch count (differentiating the forward scan —
  the previous design — saved the carry at every tick: O(M));
* `forward_only` keeps the plain forward scan, whose transpose is
  never taken;
* the interleaved schedule is the same program over a *circular*
  pipeline: each stage holds ``vp`` model chunks, the permute wraps
  P−1 → 0, and crossing the wrap advances the chunk index — same unit
  ordering as the reference's `num_warmup` doubling / chunk-id
  scheduling, derived from the closed-form tick formula instead of
  bookkeeping. The linear schedule is its vp = 1 degenerate case.

All schedule functions share one signature (the reference's share theirs
via `forward_step_func`):

    schedule(stage_fn, loss_fn, params, inputs, targets, ...)
      stage_fn(stage_params, x) -> y        uniform stage body (x, y same
                                            shape — the reference has the
                                            same constraint, tensor_shape)
      loss_fn(y_last, target) -> scalar     applied on the final stage
      params:  leaves stacked over stages — local shard inside shard_map
               has leading dim 1 (non-interleaved) or vp (interleaved);
               no leading axis for no-pipelining
      inputs:  (M, micro_batch, ...) microbatched inputs, replicated
               across the pipe axis
      targets: (M, ...) per-microbatch targets

    returns (per_microbatch_losses, grads) — grads of mean loss w.r.t.
    params (None when forward_only), loss replicated on every stage.

Shared non-stage parameters (the reference's pre_process/post_process
stages: embedding on the first stage, tied LM head on the last —
schedules/common.py build_model) ride the optional ``extra_params`` /
``pre_fn`` arguments: ``pre_fn(extra, microbatch_input)`` produces the
stage-0 activation (embedding lookup) and ``loss_fn`` becomes
``loss_fn(extra, y_last, target)`` (head + loss). The return value is
then ``(losses, (stage_grads, extra_grads))`` with extra grads summed
over the pipe axis — the reference's embedding-group allreduce.

Pipelined schedules must run inside shard_map with the ``pipe`` axis
bound; `forward_backward_no_pipelining` runs anywhere.
"""

import warnings
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import core as _jax_core

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size, pcast_varying


def _start_timer(timers, forward_only, tracer=None, microbatches=0):
    """Observability hook (rocm_apex_tpu.monitor): every schedule takes
    ``timers=`` (a `transformer._timers.Timers`) and times the whole
    schedule call under ``pipeline/forward`` / ``pipeline/fwd-bwd``.
    Called eagerly the stop syncs on the losses (a value fetch — true
    device wall time); called under jit the outputs are tracers, so the
    stop records trace/build time only and the in-graph phase
    attribution comes from the ``pp_fwd``/``pp_bwd``/``pp_comm``/
    ``pp_head`` named scopes instead (visible to `profiler.op_stats` —
    one fused scan admits no host-side phase timers).

    ``tracer=`` (a `monitor.Tracer`) records the same region as a span
    on the host timeline (and a `jax.profiler.TraceAnnotation` scope,
    so a live device capture shows the schedule boundary); the shared
    disabled tracer makes the default free."""
    name = "pipeline/forward" if forward_only else "pipeline/fwd-bwd"
    span = None
    if tracer is not None and tracer.enabled:
        span = tracer.span(name, track="pipeline",
                           microbatches=int(microbatches))
        span.__enter__()
    if timers is None:
        return None, span
    t = timers(name)
    t.start()
    return t, span


def _finish_timer(obs, out):
    t, span = obs
    if t is not None:
        leaves = [
            x for x in jax.tree_util.tree_leaves(out) if x is not None
        ]
        sync = None
        if leaves and not any(
            isinstance(x, _jax_core.Tracer) for x in leaves
        ):
            sync = leaves[0]
        t.stop(sync_on=sync)
    if span is not None:
        span.__exit__(None, None, None)
    return out


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _replicate_masked(x, maskf, axis):
    """Broadcast masked values across the axis:
    out = psum(where(maskf, x, 0)).

    Explicit VJP because the raw psum's transpose depends on shard_map
    replication tracking: with check_rep=False it degenerates to a psum
    of cotangents and every gradient through the loss replication comes
    back axis-size times too large. The true transpose of "replicate
    from the masked rank" keeps the cotangent only where the mask is
    set — correct under either check_rep setting.

    Masking is a select, not a multiply: non-exit ranks run the head on
    zero activation buffers, and a NaN/Inf produced there would survive
    ``NaN * 0`` and poison the psum for every rank. ``where`` discards
    the non-exit value outright."""
    return jax.lax.psum(jnp.where(maskf != 0, x, jnp.zeros_like(x)), axis)


def _replicate_masked_fwd(x, maskf, axis):
    return (
        jax.lax.psum(jnp.where(maskf != 0, x, jnp.zeros_like(x)), axis),
        maskf,
    )


def _replicate_masked_bwd(axis, maskf, ct):
    return (
        jnp.where(maskf != 0, ct, jnp.zeros_like(ct)),
        jnp.zeros_like(maskf),
    )


_replicate_masked.defvjp(_replicate_masked_fwd, _replicate_masked_bwd)


def _pcast_varying(x, axis):
    """Make `x` varying over `axis` by adding a varying zero.

    Idempotent, and — unlike a raw `pcast(to='varying')`, whose
    transpose is a psum over the axis — the add's transpose passes the
    cotangent through per-rank, so no hidden collective appears in the
    backward (the schedules do their cross-stage grad sums explicitly).
    (compat.pcast_varying is identity on jax without the replication
    type system, where nothing needs marking.)"""
    z = pcast_varying(jnp.zeros((), jnp.result_type(x)), axis)
    return x + z


def _stage0_inputs(pre_fn, extra, inputs, axis):
    """(M, ...) stage-0 activations: every microbatch embedded ONCE
    before the scan (instead of once per tick inside it). SPMD runs the
    embedding on every rank; only stage 0 consumes the result, and the
    unused copies carry zero cotangents through the stage-0 select."""
    if pre_fn is None:
        return inputs, jax.eval_shape(lambda x: x[0], inputs)
    x0_all = _pcast_varying(
        jax.vmap(lambda xi: pre_fn(extra, xi))(inputs), axis
    )
    return x0_all, jax.eval_shape(lambda x: x[0], x0_all)


def _head_losses(loss_fn, has_extra, extra, y_buf, targets, axis, is_last):
    """(M,) per-microbatch losses: the post_process head applied ONCE
    per microbatch after the scan (not per tick), and ONLY on the exit
    stage. The `cond` (not a select) matters twice over: non-exit ranks
    skip the head's M vmapped applications entirely, and — since
    `cond`'s VJP differentiates only the taken branch — a user loss_fn
    that produces Inf/NaN on zero activation buffers cannot leak NaN
    into non-exit gradients via the 0·Inf of a masked-output transpose.
    The predicate depends only on the pipe rank, so any collective
    inside loss_fn (e.g. the vocab-parallel CE's tensor-axis psum) sees
    a uniform decision within its device group.

    NOTE: the predicate VARIES over the pipe axis, so this `cond` (and
    the per-tick head in `_one_pass_interleaved`) is only legal under
    `shard_map(..., check_rep=False)` — every current caller. A future
    caller with replication checking enabled would see this rejected;
    it would need `check_rep=False` or a select-based head."""

    def one(y, t):
        loss = loss_fn(extra, y, t) if has_extra else loss_fn(y, t)
        return loss.astype(jnp.float32)

    m = y_buf.shape[0]

    def _real():
        return _pcast_varying(jax.vmap(one)(y_buf, targets), axis)

    def _zero():
        # the zero branch must carry the same varying-over-axis type as
        # the real branch or cond rejects the branch pair
        return _pcast_varying(jnp.zeros((m,), jnp.float32), axis)

    return jax.lax.cond(is_last, _real, _zero)


__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
]

StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]
LossFn = Callable[[jnp.ndarray, Any], jnp.ndarray]


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: Optional[int] = None,
):
    """Pick the schedule (reference: schedules/__init__.py:16-34)."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = (
            parallel_state.get_pipeline_model_parallel_world_size()
        )
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def _maybe_checkpoint(fn: StageFn, on: bool) -> StageFn:
    return jax.checkpoint(fn) if on else fn


def forward_backward_no_pipelining(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    inputs: jnp.ndarray,
    targets: Any,
    *,
    forward_only: bool = False,
    checkpoint_stages: bool = False,
    axis_name: Optional[str] = None,
    extra_params: Any = None,
    pre_fn=None,
    timers=None,
    tracer=None,
    **unused_kw,
):
    """Sequential microbatch loop with gradient accumulation.

    reference: fwd_bwd_no_pipelining.py:29-84 — grads accumulate across
    the microbatch loop and sync once (the reference suppresses DDP
    hooks until the last microbatch; here accumulation is explicit and
    the caller psums afterwards). Loss is divided by the number of
    microbatches, as the reference does inside forward_step
    (schedules/common.py:158-166).
    """
    del axis_name
    m = inputs.shape[0]
    body = _maybe_checkpoint(stage_fn, checkpoint_stages)
    has_extra = extra_params is not None
    tmr = _start_timer(timers, forward_only, tracer, m)

    def one_loss(p, extra, x, t):
        with jax.named_scope("pp_fwd"):
            x0 = pre_fn(extra, x) if pre_fn is not None else x
            y = body(p, x0)
        with jax.named_scope("pp_head"):
            return loss_fn(extra, y, t) if has_extra else loss_fn(y, t)

    if forward_only:
        losses = jax.lax.map(
            lambda xt: one_loss(params, extra_params, xt[0], xt[1]),
            (inputs, targets),
        )
        return _finish_timer(tmr, (losses, None))

    argnums = (0, 1) if has_extra else 0

    def step(acc, xt):
        x, t = xt
        accp, acce = acc
        loss, g_all = jax.value_and_grad(one_loss, argnums=argnums)(
            params, extra_params, x, t
        )
        g, ge = g_all if has_extra else (g_all, None)
        accp = jax.tree_util.tree_map(lambda a, b: a + b / m, accp, g)
        if has_extra:
            acce = jax.tree_util.tree_map(lambda a, b: a + b / m, acce, ge)
        return (accp, acce), loss

    zero = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )
    zero_e = (
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), extra_params
        )
        if has_extra
        else None
    )
    (grads, egrads), losses = jax.lax.scan(
        step, (zero, zero_e), (inputs, targets)
    )
    if has_extra:
        return _finish_timer(tmr, (losses, (grads, egrads)))
    return _finish_timer(tmr, (losses, grads))


def _tree_idx(tree, i):
    return jax.tree_util.tree_map(lambda a: a[i], tree)


def _one_pass_1f1b(
    stage_fn, loss_fn, local_params, inputs, targets, axis,
    extra, pre_fn, has_extra,
):
    """True 1F1B with O(P) live activations: ONE non-differentiated
    scan interleaving a forward and a backward unit per tick.

    Differentiating a forward scan (the previous implementation) saves
    the carried activation at EVERY tick for the transpose — O(M)
    memory, defeating 1F1B's point. The linear pipeline is exactly the
    vp = 1 case of the circular one (`_one_pass_interleaved`: tick
    algebra degenerates to forward of microbatch t−s and backward of
    t−(2(P−1)−s); the ring's wrap edges carry only data masked off by
    the entry/exit selects), so it delegates there with a singleton
    chunk axis. Gradients accumulate in fp32 and are cast to the param
    dtype; returns (losses (M,), grads, extra_grads | None).
    """
    stacked = jax.tree_util.tree_map(lambda x: x[None], local_params)
    losses, grads, egrads = _one_pass_interleaved(
        stage_fn, loss_fn, stacked, inputs, targets, axis,
        extra, pre_fn, has_extra, 1,
    )
    grads = jax.tree_util.tree_map(lambda g: jnp.squeeze(g, 0), grads)
    return losses, grads, egrads


def forward_backward_pipelining_without_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    inputs: jnp.ndarray,
    targets: Any,
    *,
    forward_only: bool = False,
    checkpoint_stages: bool = True,
    axis_name: Optional[str] = None,
    extra_params: Any = None,
    pre_fn=None,
    timers=None,
    tracer=None,
    **unused_kw,
):
    """The 1F1B linear pipeline.

    reference: fwd_bwd_pipelining_without_interleaving.py:22-170. Tick
    ``t`` has stage ``s`` working on microbatch ``t−s``; with M
    microbatches the forward spans M+P−1 ticks. Training runs the
    one-pass interleaved schedule (`_one_pass_1f1b` — O(P) live
    activations, gradients built inside the scan); `forward_only`
    keeps the plain forward scan. ``checkpoint_stages`` is accepted
    for API compatibility: the one-pass backward always rematerializes
    the stage from its saved input, which is the same recompute the
    checkpointed transpose performed — passing ``False`` with training
    enabled cannot disable the recompute, and warns once.
    """
    if not checkpoint_stages and not forward_only:
        warnings.warn(
            "checkpoint_stages=False has no effect on the training "
            "path: the one-pass 1F1B backward always rematerializes "
            "each stage from its saved input (O(P) live activations). "
            "There is no store-all-activations fast path.",
            stacklevel=2,
        )
    axis = axis_name or parallel_state.PIPE_AXIS
    p = axis_size(axis)
    m = inputs.shape[0]
    ticks = m + p - 1
    rank = jax.lax.axis_index(axis)
    is_first = rank == 0
    is_last = rank == p - 1
    # checkpoint_stages never wraps here: training runs the one-pass
    # backward (always remats), and the forward_only scan below is
    # never differentiated, so jax.checkpoint would be a no-op
    body = stage_fn
    perm = [(i, i + 1) for i in range(p - 1)]

    local_params = jax.tree_util.tree_map(
        lambda x: jnp.squeeze(x, 0) if x.shape[:1] == (1,) else x, params
    )
    has_extra = extra_params is not None

    def run(local_params, extra):
        # pre_process: every microbatch embedded once, on stage 0 only
        x0_all, a0 = _stage0_inputs(pre_fn, extra, inputs, axis)

        def tick(carry, t):
            act_recv, y_buf = carry
            mb_in = jnp.clip(t, 0, m - 1)
            x = jnp.where(is_first, x0_all[mb_in], act_recv)
            with jax.named_scope("pp_fwd"):
                y = body(local_params, x)
            # Output collection on the last stage: tick t completes
            # microbatch t-(P-1). The head/loss is NOT applied here —
            # outputs buffer up and post_process runs once after the
            # scan (the where gates cotangents of invalid ticks to zero)
            mb_out = t - (p - 1)
            valid = (mb_out >= 0) & is_last
            mb_out_c = jnp.clip(mb_out, 0, m - 1)
            y_buf = y_buf.at[mb_out_c].set(
                jnp.where(valid, y, y_buf[mb_out_c])
            )
            with jax.named_scope("pp_comm"):
                sent = jax.lax.ppermute(y, axis, perm)
            return (sent, y_buf), None

        act0 = pcast_varying(jnp.zeros(a0.shape, a0.dtype), axis)
        ybuf0 = pcast_varying(jnp.zeros((m,) + a0.shape, a0.dtype), axis)
        (_, y_buf), _ = jax.lax.scan(tick, (act0, ybuf0), jnp.arange(ticks))
        # post_process on the last stage, once per microbatch
        loss_buf = _head_losses(
            loss_fn, has_extra, extra, y_buf, targets, axis, is_last
        )
        # Replicate the last stage's losses to every stage so the caller
        # sees one logical value (reference keeps losses on the last
        # stage only and broadcasts out-of-band).
        loss_buf = _replicate_masked(
            loss_buf, is_last.astype(loss_buf.dtype), axis
        )
        return jnp.mean(loss_buf), loss_buf

    tmr = _start_timer(timers, forward_only, tracer, m)
    if forward_only:
        _, losses = run(local_params, extra_params)
        return _finish_timer(tmr, (losses, None))
    losses, grads, egrads = _one_pass_1f1b(
        stage_fn, loss_fn, local_params, inputs, targets, axis,
        extra_params, pre_fn, has_extra,
    )
    grads = jax.tree_util.tree_map(
        lambda g, x: g[None] if x.shape[:1] == (1,) else g, grads, params
    )
    if has_extra:
        # egrads are per-stage partials summed over the axis inside
        # _one_pass_1f1b — the reference's embedding-group allreduce
        # (parallel_state embedding group = first + last stage)
        return _finish_timer(tmr, (losses, (grads, egrads)))
    return _finish_timer(tmr, (losses, grads))


def _one_pass_interleaved(
    stage_fn, loss_fn, params, inputs, targets, axis,
    extra, pre_fn, has_extra, vp,
):
    """One-pass interleaved 1F1B: the circular pipeline with gradients
    built inside a single non-differentiated scan (the `_one_pass_1f1b`
    scheme generalized to vp model chunks per rank).

    Geometry (global stage ``g = v·P + s``, ``G = vp·P``,
    ``L = P·vp``): forward of unit (m, v) runs on rank s at
    ``t_f = (m//P)·L + v·P + m%P + s`` (the round-robin order of the
    forward-only schedule) and its backward at
    ``t_b = t_f + 2·(G−1−g)``, i.e. ``t_b − 2(G−1) + s =
    (m//P)·L + m%P − v·P`` — decoded per tick by the same mod-L
    arithmetic. Cotangents ride ONE reverse ring permute
    ``i → (i−1) mod P``: a step within a chunk moves g+1 → g on the
    next rank down, and the wrap P−1 ← 0 decrements the chunk — the
    mirror image of the forward's wrap-around hand-off.

    Stage inputs wait in a ``2(G−1)+1``-slot ring keyed by forward
    tick (one unit per rank per tick, lifetime ≤ 2(G−1)); the exit
    unit (g = G−1) backwards the tick it forwards, so live activations
    are bounded by the schedule depth O(P·vp) — the interleaved
    1F1B's documented in-flight profile — instead of the O(M·vp)
    carry history of a differentiated scan.
    """
    p = axis_size(axis)
    m = inputs.shape[0]
    rank = jax.lax.axis_index(axis)
    is_first = rank == 0
    is_last = rank == p - 1
    L = p * vp
    G = vp * p
    ring = [(i, (i + 1) % p) for i in range(p)]
    rring = [(i, (i - 1) % p) for i in range(p)]
    nslots = 2 * (G - 1) + 1
    ticks = ((m - 1) // p) * L + (m - 1) % p + 2 * (G - 1) + 1

    in0 = jax.eval_shape(lambda x: x[0], inputs)
    a0 = in0 if pre_fn is None else jax.eval_shape(pre_fn, extra, in0)

    def varying(x):
        return jax.tree_util.tree_map(lambda v: _pcast_varying(v, axis), x)

    def zeros_of(shape_tree, dtype=None):
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, dtype or s.dtype), shape_tree
        )

    def chunk_at(tree, v):
        return jax.tree_util.tree_map(
            lambda x: jax.lax.dynamic_index_in_dim(x, v, 0, keepdims=False),
            tree,
        )

    def decode_bwd(t):
        """tick -> (m_b, v_b, valid): invert t_b's round-robin form."""
        r = t - 2 * (G - 1) + rank
        rnd = jnp.floor_divide(r, L)
        rr = r - rnd * L  # in [0, L)
        # rr = m%p - v*p (v=0 branch) or L + m%p - v*p (v>0 branch)
        in_v0 = rr < p
        v_pos = jnp.floor_divide(L - rr + p - 1, p)
        v_b = jnp.where(in_v0, 0, v_pos)
        mp = jnp.where(in_v0, rr, v_pos * p - (L - rr))
        rnd_b = jnp.where(in_v0, rnd, rnd + 1)
        m_b = rnd_b * p + mp
        # r itself may be negative for early microbatches of higher
        # chunks (m%p - v*p < 0); the mb bound is the real validity
        valid = (m_b >= 0) & (m_b < m) & (v_b < vp)
        return m_b, v_b, valid

    def tick(carry, t):
        act_recv, ct_recv, x_buf, g_acc, eg_acc, losses = carry

        # ---- forward unit (current schedule's decomposition) -----------
        r = t - rank
        rnd, rr = r // L, r % L
        v_f = rr // p
        m_f = rnd * p + rr % p
        fwd_valid = (r >= 0) & (m_f >= 0) & (m_f < m)
        v_fc = jnp.clip(v_f, 0, vp - 1)
        m_fc = jnp.clip(m_f, 0, m - 1)
        chunk = chunk_at(params, v_fc)
        inp_j = _tree_idx(inputs, m_fc)
        is_entry = is_first & (v_fc == 0)
        if pre_fn is None:
            x0 = _pcast_varying(inp_j, axis)
        else:
            # embedding only on the entry rank's valid v=0 ticks: the
            # cond skips a full vocab-gather per tick on every other
            # rank (its result would be discarded by the select below)
            x0 = jax.lax.cond(
                is_entry & fwd_valid,
                lambda: _pcast_varying(pre_fn(extra, inp_j), axis),
                lambda: _pcast_varying(
                    jnp.zeros(a0.shape, a0.dtype), axis
                ),
            )
        x_in = jnp.where(is_entry, x0, act_recv)
        with jax.named_scope("pp_fwd"):
            y = stage_fn(chunk, x_in)

        # exit-unit post_process (global stage G-1)
        is_exit = is_last & (v_fc == vp - 1) & fwd_valid
        tgt_j = _tree_idx(targets, m_fc)
        ct1 = _pcast_varying(jnp.asarray(1.0 / m, jnp.float32), axis)

        def _head():
            if has_extra:
                def lf(e, yy):
                    return loss_fn(e, yy, tgt_j).astype(jnp.float32)

                loss, pull = jax.vjp(lf, extra, y)
                de, dy = pull(ct1)
                eg2 = jax.tree_util.tree_map(
                    lambda a, d: a + d.astype(jnp.float32), eg_acc, de
                )
                return varying((loss, dy)), eg2

            def lf(yy):
                return loss_fn(yy, tgt_j).astype(jnp.float32)

            loss, pull = jax.vjp(lf, y)
            (dy,) = pull(ct1)
            return varying((loss, dy)), eg_acc

        def _nohead():
            return (
                varying(
                    (
                        jnp.zeros((), jnp.float32),
                        jnp.zeros(y.shape, y.dtype),
                    )
                ),
                eg_acc,
            )

        with jax.named_scope("pp_head"):
            (loss_j, dy), eg_acc = jax.lax.cond(is_exit, _head, _nohead)
        losses = losses.at[m_fc].set(
            jnp.where(is_exit, loss_j, losses[m_fc])
        )

        # ---- backward unit --------------------------------------------
        m_b, v_b, bwd_valid = decode_bwd(t)
        v_bc = jnp.clip(v_b, 0, vp - 1)
        m_bc = jnp.clip(m_b, 0, m - 1)
        g_b = v_bc * p + rank
        t_f_b = t - 2 * (G - 1 - g_b)
        slot_b = jnp.clip(t_f_b, 0, None) % nslots
        bwd_is_exit = is_last & (v_bc == vp - 1)
        x_saved = jnp.where(bwd_is_exit, x_in, x_buf[slot_b])
        ct_in = jnp.where(bwd_is_exit, dy.astype(y.dtype), ct_recv)
        bchunk = chunk_at(params, v_bc)
        with jax.named_scope("pp_bwd"):
            _, pull = jax.vjp(stage_fn, bchunk, x_saved)
            dp_j, dx_j = pull(ct_in)
        g_acc = jax.tree_util.tree_map(
            lambda a, d: jax.lax.dynamic_update_index_in_dim(
                a,
                jax.lax.dynamic_index_in_dim(a, v_bc, 0, keepdims=False)
                + jnp.where(bwd_valid, d.astype(jnp.float32), 0.0),
                v_bc,
                0,
            ),
            g_acc,
            dp_j,
        )

        if has_extra and pre_fn is not None:
            inp_b = _tree_idx(inputs, m_bc)

            def _pre_bwd():
                _, pullE = jax.vjp(lambda e: pre_fn(e, inp_b), extra)
                (deE,) = pullE(dx_j)
                return jax.tree_util.tree_map(
                    lambda a, d: a + d.astype(jnp.float32), eg_acc, deE
                )

            eg_acc = jax.lax.cond(
                is_first & (v_bc == 0) & bwd_valid,
                _pre_bwd,
                lambda: eg_acc,
            )

        # ---- buffer + ring transfers (slots keyed by forward tick) ----
        slot_f = t % nslots
        x_buf = x_buf.at[slot_f].set(
            jnp.where(
                fwd_valid & ~(is_last & (v_fc == vp - 1)), x_in,
                x_buf[slot_f],
            )
        )
        with jax.named_scope("pp_comm"):
            act_send = jax.lax.ppermute(y, axis, ring)
            ct_send = jax.lax.ppermute(
                jnp.where(bwd_valid, dx_j, jnp.zeros_like(dx_j)),
                axis, rring,
            )
        return (act_send, ct_send, x_buf, g_acc, eg_acc, losses), None

    act0 = varying(jnp.zeros(a0.shape, a0.dtype))
    ct0 = varying(jnp.zeros(a0.shape, a0.dtype))
    xbuf0 = varying(jnp.zeros((nslots,) + a0.shape, a0.dtype))
    g0 = varying(zeros_of(params, jnp.float32))
    eg0 = varying(zeros_of(extra, jnp.float32)) if has_extra else ()
    losses0 = varying(jnp.zeros((m,), jnp.float32))

    (_, _, _, g_acc, eg_acc, losses), _ = jax.lax.scan(
        tick,
        (act0, ct0, xbuf0, g0, eg0, losses0),
        jnp.arange(ticks),
    )
    grads = jax.tree_util.tree_map(
        lambda g, pp: g.astype(pp.dtype), g_acc, params
    )
    losses = _replicate_masked(losses, is_last.astype(losses.dtype), axis)
    if has_extra:
        egrads = jax.tree_util.tree_map(
            lambda g, e: jax.lax.psum(g, axis).astype(e.dtype),
            eg_acc,
            extra,
        )
        return losses, grads, egrads
    return losses, grads, None


def forward_backward_pipelining_with_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    params: Any,
    inputs: jnp.ndarray,
    targets: Any,
    *,
    forward_only: bool = False,
    checkpoint_stages: bool = True,
    axis_name: Optional[str] = None,
    extra_params: Any = None,
    pre_fn=None,
    timers=None,
    tracer=None,
    **unused_kw,
):
    """Interleaved virtual stages as a circular pipeline.

    reference: fwd_bwd_pipelining_with_interleaving.py:41-308. Each stage
    holds ``vp`` model chunks (params leaves: (vp, ...) locally); global
    stage ``g = v·P + s``. Work unit (microbatch m, chunk v) runs on
    stage s at tick

        t(m, v, s) = (m // P)·P·vp + v·P + (m % P) + s

    which is exactly the reference's round-robin chunk order (rounds of
    P microbatches sweep all chunks before the next round). Consecutive
    global stages differ by one tick, so a single wrap-around ring
    permute carries every transfer, including the chunk hand-off
    P−1 → 0. Requires M % P == 0, like the reference
    (fwd_bwd_pipelining_with_interleaving.py asserts the same).
    ``checkpoint_stages=False`` with training enabled warns, as in the
    linear schedule: the one-pass backward always rematerializes.
    """
    if not checkpoint_stages and not forward_only:
        warnings.warn(
            "checkpoint_stages=False has no effect on the training "
            "path: the one-pass interleaved backward always "
            "rematerializes each chunk from its saved input.",
            stacklevel=2,
        )
    axis = axis_name or parallel_state.PIPE_AXIS
    p = axis_size(axis)
    m = inputs.shape[0]
    if m % p != 0:
        raise ValueError(
            f"interleaved schedule requires num_microbatches ({m}) divisible "
            f"by pipeline size ({p})"
        )
    vp_sizes = {
        leaf.shape[0] for leaf in jax.tree_util.tree_leaves(params)
    }
    if len(vp_sizes) != 1:
        raise ValueError(
            f"all param leaves must share the leading (vp) axis; got sizes "
            f"{sorted(vp_sizes)}"
        )
    vp = vp_sizes.pop()
    ticks = m * vp + p - 1
    rank = jax.lax.axis_index(axis)
    body = stage_fn  # same no-op rationale as the linear schedule
    ring = [(i, (i + 1) % p) for i in range(p)]
    round_len = p * vp

    has_extra = extra_params is not None
    is_first = rank == 0
    is_last = rank == p - 1

    def run(params, extra):
        x0_all, a0 = _stage0_inputs(pre_fn, extra, inputs, axis)

        def tick(carry, t):
            act_recv, y_buf = carry
            r = t - rank
            rnd, rr = r // round_len, r % round_len
            v = rr // p
            mb = rnd * p + rr % p
            valid = (r >= 0) & (mb >= 0) & (mb < m)
            v_c = jnp.clip(v, 0, vp - 1)
            mb_c = jnp.clip(mb, 0, m - 1)
            chunk = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(x, v_c, 0, keepdims=False),
                params,
            )
            is_entry = is_first & (v_c == 0)
            x = jnp.where(is_entry, x0_all[mb_c], act_recv)
            with jax.named_scope("pp_fwd"):
                y = body(chunk, x)
            is_exit = is_last & (v_c == vp - 1) & valid
            y_buf = y_buf.at[mb_c].set(jnp.where(is_exit, y, y_buf[mb_c]))
            with jax.named_scope("pp_comm"):
                sent = jax.lax.ppermute(y, axis, ring)
            return (sent, y_buf), None

        act0 = pcast_varying(jnp.zeros(a0.shape, a0.dtype), axis)
        ybuf0 = pcast_varying(jnp.zeros((m,) + a0.shape, a0.dtype), axis)
        (_, y_buf), _ = jax.lax.scan(tick, (act0, ybuf0), jnp.arange(ticks))
        loss_buf = _head_losses(
            loss_fn, has_extra, extra, y_buf, targets, axis, is_last
        )
        loss_buf = _replicate_masked(
            loss_buf, is_last.astype(loss_buf.dtype), axis
        )
        return jnp.mean(loss_buf), loss_buf

    tmr = _start_timer(timers, forward_only, tracer, m)
    if forward_only:
        _, losses = run(params, extra_params)
        return _finish_timer(tmr, (losses, None))
    losses, grads, egrads = _one_pass_interleaved(
        stage_fn, loss_fn, params, inputs, targets, axis,
        extra_params, pre_fn, has_extra, vp,
    )
    if has_extra:
        return _finish_timer(tmr, (losses, (grads, egrads)))
    return _finish_timer(tmr, (losses, grads))
