"""Pipeline parallelism: schedules, p2p transfer, microbatch calculus.

TPU-native rebuild of the reference's pipeline layer
(reference: apex/transformer/pipeline_parallel/, SURVEY.md §2.5). The
reference drives per-rank asymmetric 1F1B schedules with batched NCCL
isend/irecv between neighbouring pipeline processes; on TPU the whole
pipeline is ONE SPMD program: stage transfer is `lax.ppermute` over the
``pipe`` mesh axis, the microbatch loop is `lax.scan`, and the backward
pipeline (the reference's cooldown phase of hand-ordered backward_steps)
falls out of autodiff — the transpose of a ppermute-scan *is* the
reverse pipeline. Memory behaviour equivalent to 1F1B comes from
`jax.checkpoint` on the stage body rather than from interleaving
forward/backward by hand; XLA's scheduler overlaps the permute traffic
with stage compute.
"""

from rocm_apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
)
from rocm_apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
from rocm_apex_tpu.transformer.pipeline_parallel.microbatches import (  # noqa: F401
    ConstantNumMicroBatches,
    NumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "p2p_communication",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
    "NumMicroBatchesCalculator",
    "build_num_microbatches_calculator",
]
