"""Model-parallel-aware loss scaling."""

from rocm_apex_tpu.transformer.amp.grad_scaler import (  # noqa: F401
    GradScaler,
    sync_found_inf,
)

__all__ = ["GradScaler", "sync_found_inf"]
