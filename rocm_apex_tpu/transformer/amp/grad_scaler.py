"""Loss scaler with model-parallel overflow synchronization.

TPU-native rebuild of the reference's transformer GradScaler
(reference: apex/transformer/amp/grad_scaler.py:8-106), which subclasses
`torch.cuda.amp.GradScaler` to all-reduce ``found_inf`` with MAX over
the model-parallel group in `_maybe_opt_step:25-36` and `update:38-106`.
That sync is what makes dynamic loss scaling correct under TP/PP: if ANY
model-parallel shard overflows, every shard must skip the same step and
halve the same scale, or replicas diverge.

Here the sync is a `lax.pmax` of the overflow flag over the ``tensor``
and ``pipe`` mesh axes (those that are actually bound), folded in front
of the base scaler's update. The whole thing stays inside jit.
"""

from typing import Sequence

import jax
import jax.numpy as jnp

from rocm_apex_tpu.amp.scaler import LossScaler, ScalerState
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = ["GradScaler", "sync_found_inf"]

_MODEL_AXES = (parallel_state.TENSOR_AXIS, parallel_state.PIPE_AXIS)


def sync_found_inf(
    found_inf: jnp.ndarray, axis_names: Sequence[str] = _MODEL_AXES
) -> jnp.ndarray:
    """MAX-reduce the overflow flag over whichever model axes are bound
    (reference: grad_scaler.py:25-36)."""
    out = jnp.asarray(found_inf)
    for ax in axis_names:
        try:
            axis_size(ax)
        except NameError:
            continue
        out = jax.lax.pmax(out.astype(jnp.int32), ax) > 0
    return out


class GradScaler(LossScaler):
    """`LossScaler` whose update first syncs found_inf across model axes.

    Drop-in for `rocm_apex_tpu.amp.LossScaler` inside TP/PP train steps;
    constructor matches the reference's
    (init_scale, growth_factor, backoff_factor, growth_interval)
    vocabulary via the base class's (init_scale, scale_factor,
    scale_window).
    """

    def __init__(
        self,
        init_scale: float = 2.0**16,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        growth_interval: int = 2000,
        enabled: bool = True,
        axis_names: Sequence[str] = _MODEL_AXES,
    ):
        if growth_factor <= 1.0:
            raise ValueError("growth_factor must be > 1.0")
        if not (0.0 < backoff_factor < 1.0):
            raise ValueError("backoff_factor must be in (0, 1)")
        if abs(backoff_factor * growth_factor - 1.0) > 1e-6:
            # The base scaler uses one symmetric factor (reference amp
            # scaler semantics, scaler.py:47-63); asymmetric pairs are a
            # torch-GradScaler generalization we map onto it.
            raise ValueError(
                "GradScaler requires backoff_factor == 1/growth_factor "
                f"(got {backoff_factor} vs 1/{growth_factor})"
            )
        super().__init__(
            loss_scale="dynamic" if enabled else 1.0,
            init_scale=init_scale,
            scale_factor=growth_factor,
            scale_window=growth_interval,
        )
        self.axis_names = tuple(axis_names)

    def update(self, state: ScalerState, found_inf):
        return super().update(state, sync_found_inf(found_inf, self.axis_names))
