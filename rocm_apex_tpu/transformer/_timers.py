"""Named timers with device synchronization.

Reference: apex/transformer/pipeline_parallel/_timers.py:1-83
(`_Timer` with `torch.cuda.synchronize()` around start/stop, `Timers`
registry with `log`). On this platform synchronization means a value
fetch (see bench.py note: `block_until_ready` alone does not sync the
tunnel transport), so `stop` optionally takes an array to fetch.
"""

import time
from typing import Optional

import jax
import numpy as np

__all__ = ["Timers"]


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0

    def start(self):
        assert not self.started_, f"timer {self.name} already started"
        self.started_ = True
        self.start_time = time.perf_counter()

    def stop(self, sync_on=None):
        assert self.started_, f"timer {self.name} is not started"
        if sync_on is not None:
            np.asarray(jax.device_get(sync_on))  # true device sync
        self.elapsed_ += time.perf_counter() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        was_started = self.started_
        if was_started:
            self.stop()
        out = self.elapsed_
        if reset:
            self.reset()
        if was_started:
            self.start()
        return out


class Timers:
    """Registry (reference _timers.py Timers.__call__/log)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(
        self,
        names,
        normalizer: float = 1.0,
        reset: bool = True,
        printer=print,
    ):
        assert normalizer > 0.0
        parts = ["time (ms)"]
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        printer(" | ".join(parts))

    def write(self, names, writer, iteration, normalizer=1.0, reset=False):
        """Tensorboard-style hook (reference _timers.py write)."""
        assert normalizer > 0.0
        for name in names:
            if name in self.timers:
                value = self.timers[name].elapsed(reset=reset) / normalizer
                writer.add_scalar(f"{name}-time", value, iteration)
