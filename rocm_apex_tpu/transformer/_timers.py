"""Named timers with device synchronization.

Reference: apex/transformer/pipeline_parallel/_timers.py:1-83
(`_Timer` with `torch.cuda.synchronize()` around start/stop, `Timers`
registry with `log`). On this platform synchronization means a value
fetch (see bench.py note: `block_until_ready` alone does not sync the
tunnel transport), so `stop` optionally takes an array to fetch.
"""

import time
from typing import Optional

import jax
import numpy as np

__all__ = ["Timers"]


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0

    def start(self):
        assert not self.started_, f"timer {self.name} already started"
        self.started_ = True
        self.start_time = time.perf_counter()

    def stop(self, sync_on=None):
        assert self.started_, f"timer {self.name} is not started"
        if sync_on is not None:
            np.asarray(jax.device_get(sync_on))  # true device sync
        self.elapsed_ += time.perf_counter() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True, sync_on=None) -> float:
        was_started = self.started_
        if was_started:
            self.stop(sync_on=sync_on)
        out = self.elapsed_
        if reset:
            self.reset()
        if was_started:
            self.start()
        return out


class Timers:
    """Registry (reference _timers.py Timers.__call__/log).

    Both sinks — `log` (stdout) and `write` (TensorBoard-style
    ``add_scalar``) — RESET the timers they report by default. The
    reference shipped an asymmetry (log reset=True, write reset=False)
    that double-counted every window in TensorBoard while stdout showed
    per-window numbers; one default means the two sinks can never
    disagree about what a value covers. Pass ``reset=False`` explicitly
    for cumulative reporting. ``sync_on`` on either sink gives a timer
    that is STILL RUNNING the true-device-sync stop treatment (a value
    fetch — `_Timer.stop`) before it is read."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(
        self,
        names,
        normalizer: float = 1.0,
        reset: bool = True,
        printer=print,
        sync_on=None,
    ):
        assert normalizer > 0.0
        parts = ["time (ms)"]
        for name in names:
            if name in self.timers:
                ms = (
                    self.timers[name].elapsed(reset=reset, sync_on=sync_on)
                    * 1000.0
                    / normalizer
                )
                parts.append(f"{name}: {ms:.2f}")
        printer(" | ".join(parts))

    def write(
        self, names, writer, iteration, normalizer=1.0, reset=True,
        sync_on=None,
    ):
        """Tensorboard-style hook (reference _timers.py write), with
        `log`'s defaults and sync semantics (see class docstring)."""
        assert normalizer > 0.0
        for name in names:
            if name in self.timers:
                value = (
                    self.timers[name].elapsed(reset=reset, sync_on=sync_on)
                    / normalizer
                )
                writer.add_scalar(f"{name}-time", value, iteration)
