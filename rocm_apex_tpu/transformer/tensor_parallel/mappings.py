"""The four tensor-parallel collective autograd primitives.

TPU-native rebuild of the reference's mappings
(reference: apex/transformer/tensor_parallel/mappings.py:23-159). The
reference implements each primitive as a torch.autograd.Function over an
NCCL process group; here each is a `jax.custom_vjp` over a named mesh
axis, used inside `shard_map`:

    copy    : identity fwd / psum bwd        (mappings.py:77-90)
    reduce  : psum fwd / identity bwd        (mappings.py:93-106)
    scatter : split-last-dim fwd / all_gather bwd   (mappings.py:109-122)
    gather  : all_gather fwd / split-last-dim bwd   (mappings.py:125-138)

XLA compiles the psum/all_gather to ICI collectives; there is no process
group object — the axis NAME is the group.
"""

from functools import partial

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
]


def _axis(axis_name):
    return parallel_state.TENSOR_AXIS if axis_name is None else axis_name


def _psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def _split_last(x, axis_name):
    """This rank's 1/N chunk of the last dim (reference mappings.py:36-52)."""
    n = axis_size(axis_name)
    chunk = x.shape[-1] // n
    if chunk * n != x.shape[-1]:
        raise ValueError(
            f"last dim {x.shape[-1]} not divisible by axis size {n}"
        )
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=x.ndim - 1)

def _gather_last(x, axis_name):
    """Concatenate the last dim across the axis (reference mappings.py:55-72)."""
    return jax.lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _split_dim(x, axis_name, dim):
    dim = dim % x.ndim
    n = axis_size(axis_name)
    chunk = x.shape[dim] // n
    if chunk * n != x.shape[dim]:
        raise ValueError(
            f"dim {dim} of size {x.shape[dim]} not divisible by axis "
            f"size {n}"
        )
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=dim)


def _gather_dim(x, axis_name, dim):
    return jax.lax.all_gather(x, axis_name, axis=dim % x.ndim, tiled=True)


# -- copy: identity fwd / allreduce bwd --------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name=None):
    """Input to a column-parallel layer: identity forward, grad-psum
    backward (reference mappings.py:77-90)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (_psum(g, _axis(axis_name)),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# -- reduce: allreduce fwd / identity bwd ------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name=None):
    """Output of a row-parallel layer: psum forward, identity backward
    (reference mappings.py:93-106)."""
    return _psum(x, _axis(axis_name))


def _reduce_fwd(x, axis_name):
    return _psum(x, _axis(axis_name)), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# -- scatter: split fwd / gather bwd -----------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name=None):
    """Split the last dim, keep this rank's chunk (reference
    mappings.py:109-122)."""
    return _split_last(x, _axis(axis_name))


def _scatter_fwd(x, axis_name):
    return _split_last(x, _axis(axis_name)), None


def _scatter_bwd(axis_name, _, g):
    return (_gather_last(g, _axis(axis_name)),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


# -- gather: gather fwd / split bwd ------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name=None):
    """All-gather the last dim (reference mappings.py:125-138)."""
    return _gather_last(x, _axis(axis_name))


def _gather_fwd(x, axis_name):
    return _gather_last(x, _axis(axis_name)), None


def _gather_bwd(axis_name, _, g):
    return (_split_last(g, _axis(axis_name)),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# -- sequence-parallel region mappings ---------------------------------
#
# Capability the reference lacks (SURVEY.md §5: no sequence parallelism);
# included because it falls out of the same design: activations sharded
# along the sequence dim between transformer-layer regions, with
# reduce_scatter/all_gather replacing the plain psum at region edges
# (Korthikanti et al., "Reducing Activation Recomputation"). ``dim``
# selects the sharded dimension: 0 (the Megatron [s, b, h] convention)
# by default, 1 for this package's [b, s, h] activations. For the
# ring-overlapped fusion of these edges with the adjacent matmuls see
# `rocm_apex_tpu.ops.collective_matmul`.


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_sequence_parallel_region(x, axis_name=None, dim=0):
    return _split_dim(x, _axis(axis_name), dim)


def _sp_scatter_fwd(x, axis_name, dim):
    return _split_dim(x, _axis(axis_name), dim), None


def _sp_scatter_bwd(axis_name, dim, _, g):
    return (_gather_dim(g, _axis(axis_name), dim),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_from_sequence_parallel_region(
    x, axis_name=None, dim=0, tensor_parallel_output_grad=True
):
    """All-gather the sequence shards. ``tensor_parallel_output_grad``
    picks the transpose by what CONSUMES the gathered tensor (the
    Megatron flag of the same name): True when it feeds tensor-parallel
    computation (a column-parallel matmul — each rank's cotangent is a
    distinct partial, so the backward reduce-scatters); False when it
    feeds the replicated stream (the LM-head input — the cotangent is
    already full and identical on every rank, so the backward just
    takes this rank's slice; a reduce-scatter there would overcount
    by the axis size)."""
    return _gather_dim(x, _axis(axis_name), dim)


def _sp_gather_fwd(x, axis_name, dim, tensor_parallel_output_grad):
    return _gather_dim(x, _axis(axis_name), dim), None


def _sp_gather_bwd(axis_name, dim, tensor_parallel_output_grad, _, g):
    if tensor_parallel_output_grad:
        return (
            jax.lax.psum_scatter(
                g, _axis(axis_name), scatter_dimension=dim % g.ndim,
                tiled=True,
            ),
        )
    return (_split_dim(g, _axis(axis_name), dim),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(x, axis_name=None, dim=0):
    return jax.lax.psum_scatter(
        x, _axis(axis_name), scatter_dimension=dim % x.ndim, tiled=True
    )


def _sp_rs_fwd(x, axis_name, dim):
    return (
        jax.lax.psum_scatter(
            x, _axis(axis_name), scatter_dimension=dim % x.ndim, tiled=True
        ),
        None,
    )


def _sp_rs_bwd(axis_name, dim, _, g):
    return (_gather_dim(g, _axis(axis_name), dim),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
