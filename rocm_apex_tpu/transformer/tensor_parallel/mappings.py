"""The four tensor-parallel collective autograd primitives.

TPU-native rebuild of the reference's mappings
(reference: apex/transformer/tensor_parallel/mappings.py:23-159). The
reference implements each primitive as a torch.autograd.Function over an
NCCL process group; here each is a `jax.custom_vjp` over a named mesh
axis, used inside `shard_map`:

    copy    : identity fwd / psum bwd        (mappings.py:77-90)
    reduce  : psum fwd / identity bwd        (mappings.py:93-106)
    scatter : split-last-dim fwd / all_gather bwd   (mappings.py:109-122)
    gather  : all_gather fwd / split-last-dim bwd   (mappings.py:125-138)

XLA compiles the psum/all_gather to ICI collectives; there is no process
group object — the axis NAME is the group.
"""

from functools import partial

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.utils.compat import axis_size

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
]


def _axis(axis_name):
    return parallel_state.TENSOR_AXIS if axis_name is None else axis_name


def _psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def _split_last(x, axis_name):
    """This rank's 1/N chunk of the last dim (reference mappings.py:36-52)."""
    n = axis_size(axis_name)
    chunk = x.shape[-1] // n
    if chunk * n != x.shape[-1]:
        raise ValueError(
            f"last dim {x.shape[-1]} not divisible by axis size {n}"
        )
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=x.ndim - 1)

def _gather_last(x, axis_name):
    """Concatenate the last dim across the axis (reference mappings.py:55-72)."""
    return jax.lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _split_first(x, axis_name):
    n = axis_size(axis_name)
    chunk = x.shape[0] // n
    if chunk * n != x.shape[0]:
        raise ValueError(f"first dim {x.shape[0]} not divisible by axis size {n}")
    rank = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=0)


def _gather_first(x, axis_name):
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)


# -- copy: identity fwd / allreduce bwd --------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name=None):
    """Input to a column-parallel layer: identity forward, grad-psum
    backward (reference mappings.py:77-90)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (_psum(g, _axis(axis_name)),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# -- reduce: allreduce fwd / identity bwd ------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name=None):
    """Output of a row-parallel layer: psum forward, identity backward
    (reference mappings.py:93-106)."""
    return _psum(x, _axis(axis_name))


def _reduce_fwd(x, axis_name):
    return _psum(x, _axis(axis_name)), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# -- scatter: split fwd / gather bwd -----------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name=None):
    """Split the last dim, keep this rank's chunk (reference
    mappings.py:109-122)."""
    return _split_last(x, _axis(axis_name))


def _scatter_fwd(x, axis_name):
    return _split_last(x, _axis(axis_name)), None


def _scatter_bwd(axis_name, _, g):
    return (_gather_last(g, _axis(axis_name)),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


# -- gather: gather fwd / split bwd ------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name=None):
    """All-gather the last dim (reference mappings.py:125-138)."""
    return _gather_last(x, _axis(axis_name))


def _gather_fwd(x, axis_name):
    return _gather_last(x, _axis(axis_name)), None


def _gather_bwd(axis_name, _, g):
    return (_split_last(g, _axis(axis_name)),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# -- sequence-parallel region mappings ---------------------------------
#
# Capability the reference lacks (SURVEY.md §5: no sequence parallelism);
# included because it falls out of the same design: activations sharded
# along the sequence (first) dim between transformer-layer regions, with
# reduce_scatter/all_gather replacing the plain psum at region edges
# (Korthikanti et al., "Reducing Activation Recomputation").


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis_name=None):
    return _split_first(x, _axis(axis_name))


def _sp_scatter_fwd(x, axis_name):
    return _split_first(x, _axis(axis_name)), None


def _sp_scatter_bwd(axis_name, _, g):
    return (_gather_first(g, _axis(axis_name)),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_sequence_parallel_region(x, axis_name=None):
    return _gather_first(x, _axis(axis_name))


def _sp_gather_fwd(x, axis_name):
    return _gather_first(x, _axis(axis_name)), None


def _sp_gather_bwd(axis_name, _, g):
    return (jax.lax.psum_scatter(g, _axis(axis_name), scatter_dimension=0, tiled=True),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis_name=None):
    return jax.lax.psum_scatter(x, _axis(axis_name), scatter_dimension=0, tiled=True)


def _sp_rs_fwd(x, axis_name):
    return (
        jax.lax.psum_scatter(x, _axis(axis_name), scatter_dimension=0, tiled=True),
        None,
    )


def _sp_rs_bwd(axis_name, _, g):
    return (_gather_first(g, _axis(axis_name)),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
