"""Tensor (intra-layer) model parallelism over the ``tensor`` mesh axis.

Public surface mirrors the reference package
(reference: apex/transformer/tensor_parallel/__init__.py), rebuilt on
shard_map + XLA collectives.
"""

from rocm_apex_tpu.ops.linear_xentropy import (
    vocab_parallel_linear_cross_entropy,
)
from rocm_apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from rocm_apex_tpu.transformer.tensor_parallel.data import broadcast_data
from rocm_apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from rocm_apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from rocm_apex_tpu.transformer.tensor_parallel.memory import (
    MemoryBuffer,
    RingMemBuffer,
    allocate_mem_buff,
)
from rocm_apex_tpu.transformer.tensor_parallel.random import (
    CheckpointPolicy,
    RngStateTracker,
    checkpoint,
    get_cuda_rng_tracker,
    get_rng_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_prng_keys,
    model_parallel_seed,
)
from rocm_apex_tpu.transformer.utils import (
    VocabUtility,
    divide,
    ensure_divisibility,
    gather_split_1d_tensor,
    split_tensor_along_last_dim,
    split_tensor_into_1d_equal_chunks,
)

__all__ = [
    "vocab_parallel_cross_entropy",
    "vocab_parallel_linear_cross_entropy",
    "broadcast_data",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "copy_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "MemoryBuffer",
    "RingMemBuffer",
    "allocate_mem_buff",
    "CheckpointPolicy",
    "RngStateTracker",
    "checkpoint",
    "get_rng_tracker",
    "get_cuda_rng_tracker",
    "model_parallel_seed",
    "model_parallel_cuda_manual_seed",
    "model_parallel_prng_keys",
    "VocabUtility",
    "divide",
    "ensure_divisibility",
    "split_tensor_along_last_dim",
    "split_tensor_into_1d_equal_chunks",
    "gather_split_1d_tensor",
]
