"""Cross-rank broadcast of a keyed batch dict.

TPU-native rebuild of `broadcast_data`
(reference: apex/transformer/tensor_parallel/data.py:77-113). The
reference sends size metadata then one flattened payload from TP rank 0
to the other TP ranks with NCCL broadcast. Under shard_map the same
semantic is one masked psum: every rank contributes zeros except rank 0.
In the common single-controller case where the batch is already
replicated this compiles away; it matters when each TP rank loads
different data (e.g. per-host loaders) and must agree.
"""

from typing import Dict, List

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state

__all__ = ["broadcast_data"]


def _check_data_types(keys: List[str], data: Dict[str, jnp.ndarray], target_dtype):
    """Reference data.py:17-26."""
    for key in keys:
        if data[key].dtype != target_dtype:
            raise ValueError(
                f"{key} has data type {data[key].dtype} which "
                f"is different than {target_dtype}"
            )


def broadcast_data(
    keys: List[str],
    data: Dict[str, jnp.ndarray],
    dtype,
    axis_name: str = None,
) -> Dict[str, jnp.ndarray]:
    """Broadcast each `data[key]` from rank 0 of the TP axis.

    Must run inside shard_map with the axis bound. Shapes must already
    agree across ranks (the reference broadcasts the size metadata too —
    data.py:27-55 — which a single-controller SPMD program guarantees
    statically).
    """
    axis_name = parallel_state.TENSOR_AXIS if axis_name is None else axis_name
    _check_data_types(keys, data, dtype)
    rank = jax.lax.axis_index(axis_name)
    is_src = (rank == 0)
    out = {}
    for key in keys:
        x = data[key]
        # Masked psum == broadcast-from-0 (one ICI collective for all
        # practical payloads; the reference packs keys into one flat
        # buffer for the same latency reason, data.py:88-106).
        contrib = jnp.where(is_src, x, jnp.zeros_like(x))
        summed = jax.lax.psum(contrib, axis_name)
        out[key] = summed.astype(dtype)
    return out
