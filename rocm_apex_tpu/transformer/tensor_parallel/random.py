"""RNG state management + activation checkpointing.

TPU-native rebuild of the reference's CudaRNGStatesTracker + checkpoint
(reference: apex/transformer/tensor_parallel/random.py:113-293). The
reference must snapshot/restore device RNG states and replay them inside
recomputation so dropout masks match between the checkpointed forward and
the recomputed forward (CheckpointFunction:224-289). JAX's PRNG is
functional, so *replay is free*: `jax.checkpoint` re-traces the same
function with the same keys and regenerates bit-identical randomness.
What remains of the reference's machinery:

* seed bookkeeping — `model_parallel_prng_keys` reproduces the seed
  offsets of `model_parallel_cuda_manual_seed` (random.py:193-221):
  tensor-parallel seed = seed + 2718 + tp_rank, data-parallel seed =
  seed (identical across TP ranks);
* a named-key tracker for code structured around the reference API
  (`get_rng_tracker().fork()`), implemented as explicit key state;
* `checkpoint` — thin wrapper over `jax.checkpoint` (the TPU-idiomatic
  rematerialization), with the reference's
  `distribute_saved_activations` flag accepted (XLA + sharding
  annotations already partition saved activations; see
  `jax.checkpoint_policies.save_and_offload_only_these_names` for the
  offload analogue).
"""

import contextlib
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state

__all__ = [
    "RngStateTracker",
    "get_rng_tracker",
    "get_cuda_rng_tracker",
    "model_parallel_seed",
    "model_parallel_cuda_manual_seed",
    "model_parallel_prng_keys",
    "checkpoint",
    "CheckpointPolicy",
    "_MODEL_PARALLEL_RNG_TRACKER_NAME",
]

# Name of the model-parallel fork (reference random.py:110).
_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


def model_parallel_prng_keys(seed: int, tp_rank) -> Dict[str, jax.Array]:
    """Derive the default and model-parallel PRNG keys.

    Seed arithmetic matches the reference (random.py:193-221):
    ``offset = seed + 2718``, ``tensor_model_parallel_seed = offset +
    tp_rank``, ``data_parallel_seed = seed``.
    """
    data_parallel_key = jax.random.PRNGKey(seed)
    tensor_key = jax.random.fold_in(jax.random.PRNGKey(seed + 2718), tp_rank)
    return {
        "default": data_parallel_key,
        _MODEL_PARALLEL_RNG_TRACKER_NAME: tensor_key,
    }


class RngStateTracker:
    """Named PRNG key states with fork semantics.

    Reference: CudaRNGStatesTracker (random.py:113-187). `fork(name)`
    yields a fresh subkey from the named stream and advances the stream —
    the functional analogue of "swap device RNG state in, run, swap out".
    Host-level state: use outside jit (key material is then threaded into
    jitted functions as arguments).
    """

    def __init__(self):
        self._states: Dict[str, jax.Array] = {}

    def reset(self):
        self._states = {}

    def get_states(self) -> Dict[str, jax.Array]:
        return dict(self._states)

    def set_states(self, states: Dict[str, jax.Array]):
        self._states = dict(states)

    def add(self, name: str, seed):
        """Register a stream (reference random.py:141-159). `seed` may be
        an int or a PRNGKey."""
        if name in self._states:
            raise RuntimeError(f"rng state {name} already exists")
        key = seed if isinstance(seed, jax.Array) else jax.random.PRNGKey(seed)
        self._states[name] = key

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Yield a subkey from the named stream and advance it
        (reference random.py:161-187)."""
        if name not in self._states:
            raise RuntimeError(f"rng state {name} is not added")
        key, sub = jax.random.split(self._states[name])
        self._states[name] = key
        yield sub


_RNG_TRACKER = RngStateTracker()


def get_rng_tracker() -> RngStateTracker:
    """Reference: get_cuda_rng_tracker (random.py:188-190)."""
    return _RNG_TRACKER


# Reference-spelling alias so downstream Megatron-style code ports 1:1.
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_seed(seed: int, tp_rank: Optional[int] = None) -> None:
    """Initialize the global tracker (reference:
    model_parallel_cuda_manual_seed, random.py:193-221)."""
    if tp_rank is None:
        tp_rank = 0
    keys = model_parallel_prng_keys(seed, tp_rank)
    _RNG_TRACKER.reset()
    for name, key in keys.items():
        _RNG_TRACKER.add(name, key)


model_parallel_cuda_manual_seed = model_parallel_seed


class CheckpointPolicy:
    """Named remat policies for the `checkpoint` wrapper."""

    NOTHING_SAVEABLE = jax.checkpoint_policies.nothing_saveable
    DOTS_SAVEABLE = jax.checkpoint_policies.dots_saveable
    DOTS_WITH_NO_BATCH_DIMS = jax.checkpoint_policies.dots_with_no_batch_dims_saveable


def checkpoint(
    function: Callable,
    *args,
    distribute_saved_activations: bool = False,
    policy=None,
):
    """Activation checkpointing: recompute `function` in the backward.

    Reference: CheckpointFunction/checkpoint (random.py:224-293). The
    reference saves RNG states and replays them during recompute; JAX
    remat re-traces with the same functional keys, so randomness is
    bit-identical with no bookkeeping. `distribute_saved_activations`
    (reference random.py:248-255 partitions the saved input across TP
    ranks) is subsumed by sharding annotations on the inputs; accepted
    and ignored.
    """
    del distribute_saved_activations
    return jax.checkpoint(function, policy=policy)(*args)
