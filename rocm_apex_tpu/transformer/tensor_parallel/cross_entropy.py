"""Vocab-parallel softmax cross-entropy.

TPU-native rebuild of the reference's two-allreduce parallel CE
(reference: apex/transformer/tensor_parallel/cross_entropy.py:23-103):

    1. local max        → pmax over the tensor axis
    2. local sum-exp    → psum
    3. target-logit gather with vocab-range masking → psum

The backward matches the reference's saved-softmax gradient
(cross_entropy.py:76-100) via custom_vjp: d logits = softmax - onehot.
"""

from functools import partial

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer.utils import VocabUtility

__all__ = ["vocab_parallel_cross_entropy"]


def _fwd_impl(vocab_parallel_logits, target, axis_name):
    tp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    partition_vocab_size = vocab_parallel_logits.shape[-1]
    start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
        partition_vocab_size, rank, tp
    )

    # 1. global max for stability (reference :30-35)
    logits_max = jax.lax.pmax(
        jnp.max(vocab_parallel_logits, axis=-1), axis_name
    )
    logits = vocab_parallel_logits - logits_max[..., None]

    # 3. this rank's slice of the target logit, masked outside the local
    # vocab range (reference :37-56)
    local_target = target - start
    in_range = (local_target >= 0) & (local_target < partition_vocab_size)
    local_target_clamped = jnp.clip(local_target, 0, partition_vocab_size - 1)
    predicted = jnp.take_along_axis(
        logits, local_target_clamped[..., None], axis=-1
    )[..., 0]
    predicted = jnp.where(in_range, predicted, 0.0)
    predicted = jax.lax.psum(predicted, axis_name)

    # 2. global sum-exp (reference :58-63)
    exp_logits = jnp.exp(logits)
    sum_exp = jax.lax.psum(jnp.sum(exp_logits, axis=-1), axis_name)

    loss = jnp.log(sum_exp) - predicted
    softmax = exp_logits / sum_exp[..., None]
    residuals = (softmax, in_range, local_target_clamped)
    return loss, residuals


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def vocab_parallel_cross_entropy(vocab_parallel_logits, target, axis_name=None):
    """Per-token CE loss from vocab-sharded logits.

    Args:
      vocab_parallel_logits: fp32 ``(..., vocab/tp)`` local logits.
      target: integer ``(...)`` global token ids.
      axis_name: TP mesh axis (default: parallel_state tensor axis).
        Must be bound (shard_map).

    Returns the un-reduced loss, shape ``(...)`` — same contract as the
    reference (cross_entropy.py:101-103: "The losses are not reduced").
    """
    axis_name = parallel_state.TENSOR_AXIS if axis_name is None else axis_name
    loss, _ = _fwd_impl(vocab_parallel_logits, target, axis_name)
    return loss


def _ce_fwd(vocab_parallel_logits, target, axis_name):
    axis = parallel_state.TENSOR_AXIS if axis_name is None else axis_name
    loss, residuals = _fwd_impl(vocab_parallel_logits, target, axis)
    return loss, residuals


def _ce_bwd(axis_name, residuals, g):
    softmax, in_range, local_target_clamped = residuals
    # grad = (softmax - onehot_local_target) * g  (reference :76-100)
    onehot = jax.nn.one_hot(
        local_target_clamped, softmax.shape[-1], dtype=softmax.dtype
    ) * in_range[..., None].astype(softmax.dtype)
    grad = (softmax - onehot) * g[..., None]
    return (grad, None)


vocab_parallel_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
