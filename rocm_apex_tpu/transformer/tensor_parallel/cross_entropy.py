"""Vocab-parallel softmax cross-entropy.

TPU-native rebuild of the reference's two-allreduce parallel CE
(reference: apex/transformer/tensor_parallel/cross_entropy.py:23-103):

    1. local max        → pmax over the tensor axis
    2. local sum-exp    → psum
    3. target-logit gather with vocab-range masking → psum

The backward matches the reference's saved-softmax gradient
(cross_entropy.py:76-100) via custom_vjp: d logits = softmax - onehot.
"""

from functools import partial

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer.utils import VocabUtility
from rocm_apex_tpu.utils.compat import axis_size

__all__ = ["vocab_parallel_cross_entropy"]


def _fwd_impl(vocab_parallel_logits, target, axis_name):
    # accept compute-dtype (bf16) logits and upcast here: the exp-sum
    # over the vocab must run in fp32, but the caller casting the whole
    # logits tensor first would materialize an fp32 copy in HBM; this
    # convert fuses into the max/exp pipeline. Residuals are the
    # ORIGINAL logits (already live as the primal input — zero extra
    # memory) plus the O(b·s) fp32 (max, sum_exp) row statistics; the
    # backward recomputes probabilities in fp32 like ops/xentropy.py.
    # Saving an O(b·s·v) bf16 softmax instead would zero the gradient
    # of confidently-predicted tokens (p > ~0.998 rounds to 1.0).
    logits_in = vocab_parallel_logits
    logits_f32 = vocab_parallel_logits.astype(jnp.float32)
    tp = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    partition_vocab_size = logits_f32.shape[-1]
    start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
        partition_vocab_size, rank, tp
    )

    # 1. global max for stability (reference :30-35)
    logits_max = jax.lax.pmax(jnp.max(logits_f32, axis=-1), axis_name)
    logits = logits_f32 - logits_max[..., None]

    # 3. this rank's slice of the target logit, masked outside the local
    # vocab range (reference :37-56)
    local_target = target - start
    in_range = (local_target >= 0) & (local_target < partition_vocab_size)
    local_target_clamped = jnp.clip(local_target, 0, partition_vocab_size - 1)
    predicted = jnp.take_along_axis(
        logits, local_target_clamped[..., None], axis=-1
    )[..., 0]
    predicted = jnp.where(in_range, predicted, 0.0)
    predicted = jax.lax.psum(predicted, axis_name)

    # 2. global sum-exp (reference :58-63)
    sum_exp = jax.lax.psum(jnp.sum(jnp.exp(logits), axis=-1), axis_name)

    loss = jnp.log(sum_exp) - predicted
    residuals = (
        logits_in, logits_max, sum_exp, in_range, local_target_clamped
    )
    return loss, residuals


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def vocab_parallel_cross_entropy(vocab_parallel_logits, target, axis_name=None):
    """Per-token CE loss from vocab-sharded logits.

    Args:
      vocab_parallel_logits: ``(..., vocab/tp)`` local logits in the
        compute dtype (bf16/fp32); softmax statistics run in fp32
        internally.
      target: integer ``(...)`` global token ids.
      axis_name: TP mesh axis (default: parallel_state tensor axis).
        Must be bound (shard_map).

    Returns the un-reduced loss, shape ``(...)`` — same contract as the
    reference (cross_entropy.py:101-103: "The losses are not reduced").
    """
    axis_name = parallel_state.TENSOR_AXIS if axis_name is None else axis_name
    loss, _ = _fwd_impl(vocab_parallel_logits, target, axis_name)
    return loss


def _ce_fwd(vocab_parallel_logits, target, axis_name):
    axis = parallel_state.TENSOR_AXIS if axis_name is None else axis_name
    loss, residuals = _fwd_impl(vocab_parallel_logits, target, axis)
    return loss, residuals


def _ce_bwd(axis_name, residuals, g):
    logits_in, logits_max, sum_exp, in_range, local_target_clamped = (
        residuals
    )
    # grad = (softmax - onehot_local_target) * g  (reference :76-100);
    # probabilities recomputed in fp32 from the saved row statistics
    sm = jnp.exp(
        logits_in.astype(jnp.float32) - logits_max[..., None]
    ) / sum_exp[..., None]
    onehot = jax.nn.one_hot(
        local_target_clamped, sm.shape[-1], dtype=jnp.float32
    ) * in_range[..., None].astype(jnp.float32)
    grad = (sm - onehot) * g[..., None].astype(jnp.float32)
    return (grad.astype(logits_in.dtype), None)


vocab_parallel_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
