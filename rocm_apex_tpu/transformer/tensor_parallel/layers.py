"""Tensor-parallel layers: vocab-parallel embedding, column/row-parallel
linear.

TPU-native rebuild of the reference's Megatron-style TP layers
(reference: apex/transformer/tensor_parallel/layers.py:127-477). Flax
modules holding the *local shard* of each weight, meant to run inside
`shard_map` over the ``tensor`` mesh axis; the collective edges come from
``mappings``. Differences from the reference, by design:

* weights use the JAX ``(in, out)`` convention, not torch's ``(out, in)``;
* partitioned init = fold the TP rank into the PRNG key (the functional
  equivalent of ``_initialize_affine_weight_gpu``'s per-rank RNG fork,
  reference layers.py:78-124) — no master-weight scatter is needed since
  every rank derives its shard deterministically;
* the async-allreduce fused autograd function
  (reference layers.py:206-240) maps onto two mechanisms: XLA's
  latency-hiding scheduler overlaps the backward psum with the weight-
  gradient matmul on its own, and the ``sequence_parallel`` +
  ``collective_matmul`` fields below replace the blocking TP-edge
  collectives with the ppermute-chunked rings of
  `rocm_apex_tpu.ops.collective_matmul` (arXiv 2305.06942).
  ``no_async_tensor_model_parallel_allreduce=True`` — the reference's
  opt-out of comm/compute overlap — disables the collective-matmul
  path (see docs/migration.md);
* ``use_cpu_initialization`` is meaningless (init is a traced function).

With ``sequence_parallel=True`` (Korthikanti et al. semantics) the
activations OUTSIDE the column→row pair are sharded along the
rows/sequence axis (``-2``) of the tensor axis: ColumnParallelLinear
takes the local sequence shard and all-gathers it into the matmul
(``gather_output`` must be False), RowParallelLinear reduce-scatters
its output back to a shard (``input_is_parallel`` must be True), so
everything between the pair (layernorm, dropout, residual) holds
``1/tp`` of the rows. ``collective_matmul=True`` fuses those edge
collectives into the matmuls as rings.

For the GSPMD path (pjit + sharding annotations instead of shard_map) use
the same modules with ``world_size=1`` and annotate the full weights —
see ``rocm_apex_tpu.models.gpt``.
"""

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocm_apex_tpu.ops.collective_matmul import (
    all_gather_matmul,
    matmul_reduce_scatter,
)
from rocm_apex_tpu.transformer import parallel_state
from rocm_apex_tpu.transformer.tensor_parallel import mappings
from rocm_apex_tpu.transformer.utils import VocabUtility, divide

__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
]

Initializer = Callable[..., jnp.ndarray]


def _axis_rank(axis_name: str):
    """Rank on `axis_name`, or None when the axis is not bound (tp=1 /
    GSPMD usage outside shard_map)."""
    try:
        return jax.lax.axis_index(axis_name)
    except NameError:
        return None


def _sharded_init(init_fn: Initializer, axis_name: str) -> Initializer:
    """Per-rank partitioned init: fold the TP rank into the key so each
    shard draws independent values (reference layers.py:105-124 forks the
    CUDA RNG per rank for the same purpose)."""

    def wrapped(key, shape, dtype):
        rank = _axis_rank(axis_name)
        if rank is not None:
            key = jax.random.fold_in(key, rank)
        return init_fn(key, shape, dtype)

    return wrapped


def _resolve_world_size(world_size: Optional[int]) -> int:
    if world_size is not None:
        return world_size
    if parallel_state.model_parallel_is_initialized():
        return parallel_state.get_tensor_model_parallel_world_size()
    return 1


def _require_axis(axis_name: str, tp: int, cls: str) -> None:
    """tp>1 outside shard_map would silently compute on 1/tp of the
    weight (or die in a collective with a bare NameError) — fail fast
    with a clear message instead."""
    if _axis_rank(axis_name) is None:
        raise ValueError(
            f"{cls} with world_size={tp} must run inside shard_map with "
            f"axis {axis_name!r} bound"
        )


class VocabParallelEmbedding(nn.Module):
    """Embedding sharded along the vocabulary dimension.

    Reference: apex/transformer/tensor_parallel/layers.py:127-205. Out-of
    -range ids are masked locally; the partial lookups are summed with a
    psum (layers.py:179-205).

    Attributes:
      num_embeddings: global vocab size.
      embedding_dim: hidden size.
      init_method: weight initializer (reference default: xavier normal).
      params_dtype: weight storage dtype.
      dtype: compute/output dtype.
      world_size: TP degree; defaults to the active parallel_state.
      axis_name: mesh axis to reduce over.
    """

    num_embeddings: int
    embedding_dim: int
    init_method: Initializer = nn.initializers.normal(stddev=0.02)
    params_dtype: jnp.dtype = jnp.float32
    dtype: jnp.dtype = jnp.float32
    world_size: Optional[int] = None
    axis_name: str = parallel_state.TENSOR_AXIS

    def setup(self):
        tp = _resolve_world_size(self.world_size)
        per_partition = divide(self.num_embeddings, tp)
        self.weight = self.param(
            "weight",
            _sharded_init(self.init_method, self.axis_name),
            (per_partition, self.embedding_dim),
            self.params_dtype,
        )

    def __call__(self, ids: jnp.ndarray) -> jnp.ndarray:
        tp = _resolve_world_size(self.world_size)
        per_partition = divide(self.num_embeddings, tp)
        if tp == 1:
            return jnp.take(self.weight, ids, axis=0).astype(self.dtype)

        _require_axis(self.axis_name, tp, "VocabParallelEmbedding")
        rank = _axis_rank(self.axis_name)
        start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition, rank, tp
        )
        # Mask ids outside [start, end), clamp the local index, zero the
        # masked rows, then sum partial embeddings across TP
        # (reference layers.py:179-205).
        local = ids - start
        in_range = (local >= 0) & (local < per_partition)
        local = jnp.clip(local, 0, per_partition - 1)
        out = jnp.take(self.weight, local, axis=0).astype(self.dtype)
        out = jnp.where(in_range[..., None], out, 0)
        return mappings.reduce_from_tensor_model_parallel_region(
            out, self.axis_name
        )

    def attend(self, hidden: jnp.ndarray) -> jnp.ndarray:
        """Project hidden states onto the (local slice of the) vocabulary
        with the tied embedding weight: the Megatron
        ``parallel_lm_logits`` head (reference:
        apex/transformer/testing/standalone_gpt.py output layer — logits
        stay vocab-parallel, to be consumed by
        vocab_parallel_cross_entropy)."""
        tp = _resolve_world_size(self.world_size)
        if tp > 1:
            _require_axis(self.axis_name, tp, "VocabParallelEmbedding")
            hidden = mappings.copy_to_tensor_model_parallel_region(
                hidden, self.axis_name
            )
        return jnp.dot(
            hidden,
            self.weight.astype(hidden.dtype).T,
            preferred_element_type=hidden.dtype,
        )

    def attend_loss(
        self,
        hidden: jnp.ndarray,
        labels: jnp.ndarray,
        loss_mask: Optional[jnp.ndarray] = None,
        reduction: Optional[str] = None,
        smoothing: float = 0.0,
        padding_idx: Optional[int] = None,
        chunk_size: Optional[int] = None,
    ) -> jnp.ndarray:
        """`attend` fused with cross-entropy: the ``(rows, vocab)``
        logits never materialize (ops/linear_xentropy.py — the chunked
        Liger-style head). ``reduction=None`` returns per-row fp32
        losses shaped like ``labels`` (``loss_mask`` must then be
        applied by the caller); ``reduction='mean'`` returns the
        `gpt_loss_fn`-style masked mean scalar, whose gradients finish
        inside the forward pass (no recompute matmul). The tensor
        gradient of the tied ``weight`` flows through the fused op, and
        the hidden gradient is psum'd over the tensor axis internally
        — no `copy_to_tensor_model_parallel_region` wrapper needed."""
        from rocm_apex_tpu.ops.linear_xentropy import (
            linear_cross_entropy_loss,
            linear_cross_entropy_mean,
            vocab_parallel_linear_cross_entropy,
        )

        if reduction not in (None, "mean"):
            raise ValueError(f"unknown reduction {reduction!r}")
        tp = _resolve_world_size(self.world_size)
        w = self.weight.astype(hidden.dtype)
        if tp == 1:
            if reduction == "mean":
                return linear_cross_entropy_mean(
                    hidden, w, labels, loss_mask,
                    smoothing, padding_idx, chunk_size,
                )
            return linear_cross_entropy_loss(
                hidden, w, labels, smoothing, padding_idx, chunk_size
            )
        _require_axis(self.axis_name, tp, "VocabParallelEmbedding")
        losses = vocab_parallel_linear_cross_entropy(
            hidden, w, labels, self.axis_name,
            smoothing, padding_idx, chunk_size,
        )
        if reduction is None:
            return losses
        # tp>1 mean: the scalar-cotangent forward-gradient trick needs
        # a replicated weight, so reduce the per-row fused losses the
        # gpt_loss_fn way instead
        if loss_mask is not None:
            m = jax.lax.stop_gradient(loss_mask).astype(jnp.float32)
            return jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(losses)


class ColumnParallelLinear(nn.Module):
    """Linear with the output dimension sharded: Y = XA + b, A split
    column-wise; each rank computes its slice of Y.

    Reference: apex/transformer/tensor_parallel/layers.py:243-362.
    ``gather_output`` all-gathers Y at the end (layers.py:252-255);
    ``skip_bias_add`` returns the bias instead of adding it so a later
    kernel can fuse it (layers.py:258-262).

    Returns ``(output, output_bias)`` exactly like the reference; when
    ``skip_bias_add=False`` output_bias is None.

    ``sequence_parallel``: the input is the local rows-shard of the
    activation (sharded on axis ``-2`` over the tensor axis); the
    forward all-gathers it into the matmul and the backward reduce-
    scatters the input grad — the Megatron sequence-parallel region
    entry. Requires ``gather_output=False``. ``collective_matmul``
    replaces the blocking gather with the ppermute-chunked ring of
    `ops.collective_matmul.all_gather_matmul` (the gathered activation
    never materializes); ``collective_matmul_chunk`` sets the ring
    piece size in rows (None = one piece per shard; a non-tiling chunk
    falls back to the plain collective). ``comm_dtype="int8"``
    quantizes each ring hop's payload to int8 with per-row fp32 scale
    sidecars (ops/quantized_collectives.py conventions); the backward
    rings quantize at the same dtype, and the plain/degradation paths
    stay full-precision.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    gather_output: bool = True
    init_method: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()
    skip_bias_add: bool = False
    params_dtype: jnp.dtype = jnp.float32
    dtype: jnp.dtype = jnp.float32
    world_size: Optional[int] = None
    axis_name: str = parallel_state.TENSOR_AXIS
    sequence_parallel: bool = False
    collective_matmul: bool = False
    collective_matmul_chunk: Optional[int] = None
    comm_dtype: str = "fp32"
    # The reference's opt-out of its fused async comm/compute overlap
    # (layers.py:206-240, 296-300): here it disables the collective-
    # matmul ring, restoring the blocking lax collective at this edge
    # (XLA still overlaps the backward psum on its own).
    no_async_tensor_model_parallel_allreduce: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        tp = _resolve_world_size(self.world_size)
        if tp > 1:
            _require_axis(self.axis_name, tp, "ColumnParallelLinear")
        if self.sequence_parallel and self.gather_output:
            raise ValueError(
                "sequence_parallel=True shards the rows the caller "
                "sees; it requires gather_output=False"
            )
        out_per_partition = divide(self.output_size, tp)
        kernel = self.param(
            "kernel",
            _sharded_init(self.init_method, self.axis_name),
            (self.input_size, out_per_partition),
            self.params_dtype,
        )
        bias = (
            self.param(
                "bias",
                _sharded_init(self.bias_init, self.axis_name),
                (out_per_partition,),
                self.params_dtype,
            )
            if self.use_bias
            else None
        )

        if tp > 1 and self.sequence_parallel:
            # region entry: the sequence shard gathers INTO the matmul
            # (ring when collective_matmul); the backward is the
            # transposed reduce-scatter, so no copy/psum wrapper
            if (
                self.collective_matmul
                and not self.no_async_tensor_model_parallel_allreduce
            ):
                y = all_gather_matmul(
                    x.astype(self.dtype),
                    kernel.astype(self.dtype),
                    self.axis_name,
                    self.collective_matmul_chunk,
                    self.comm_dtype,
                )
            else:
                xg = mappings.gather_from_sequence_parallel_region(
                    x, self.axis_name, dim=-2
                )
                y = jnp.dot(
                    xg.astype(self.dtype),
                    kernel.astype(self.dtype),
                    preferred_element_type=self.dtype,
                )
        else:
            if tp > 1:
                x = mappings.copy_to_tensor_model_parallel_region(
                    x, self.axis_name
                )
            y = jnp.dot(
                x.astype(self.dtype),
                kernel.astype(self.dtype),
                preferred_element_type=self.dtype,
            )
        out_bias = None
        if bias is not None:
            if self.skip_bias_add:
                out_bias = bias.astype(self.dtype)
            else:
                y = y + bias.astype(self.dtype)
        if self.gather_output and tp > 1:
            y = mappings.gather_from_tensor_model_parallel_region(y, self.axis_name)
            if out_bias is not None:
                out_bias = mappings.gather_from_tensor_model_parallel_region(
                    out_bias, self.axis_name
                )
        return y, out_bias


class RowParallelLinear(nn.Module):
    """Linear with the input dimension sharded: Y = XA + b, A split
    row-wise; partial products are psum-reduced.

    Reference: apex/transformer/tensor_parallel/layers.py:365-477.
    ``input_is_parallel`` skips the input scatter when the producer was a
    ColumnParallelLinear with gather_output=False (layers.py:378-381).
    Bias is added after the reduction, once (layers.py:461-470).

    ``sequence_parallel``: the output psum becomes a reduce-scatter
    over the rows axis (``-2``) — the Megatron sequence-parallel
    region exit; the caller receives the local rows-shard and the
    bias is added once per row on the shard. Requires
    ``input_is_parallel=True``. ``collective_matmul`` fuses the
    reduce-scatter into the matmul as the ppermute-chunked ring of
    `ops.collective_matmul.matmul_reduce_scatter` (the full-rows
    pre-reduce product never materializes). ``comm_dtype="int8"``
    quantizes the rotating ring payloads as in ColumnParallelLinear.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = False
    init_method: Initializer = nn.initializers.lecun_normal()
    bias_init: Initializer = nn.initializers.zeros_init()
    skip_bias_add: bool = False
    params_dtype: jnp.dtype = jnp.float32
    dtype: jnp.dtype = jnp.float32
    world_size: Optional[int] = None
    axis_name: str = parallel_state.TENSOR_AXIS
    sequence_parallel: bool = False
    collective_matmul: bool = False
    collective_matmul_chunk: Optional[int] = None
    comm_dtype: str = "fp32"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        tp = _resolve_world_size(self.world_size)
        if tp > 1:
            _require_axis(self.axis_name, tp, "RowParallelLinear")
        if self.sequence_parallel and not self.input_is_parallel:
            raise ValueError(
                "sequence_parallel=True requires input_is_parallel=True "
                "(the producer must be a ColumnParallelLinear with "
                "gather_output=False)"
            )
        in_per_partition = divide(self.input_size, tp)
        kernel = self.param(
            "kernel",
            _sharded_init(self.init_method, self.axis_name),
            (in_per_partition, self.output_size),
            self.params_dtype,
        )
        # Bias is replicated, not sharded: plain init (reference
        # layers.py:431-439).
        bias = (
            self.param("bias", self.bias_init, (self.output_size,), self.params_dtype)
            if self.use_bias
            else None
        )

        if tp > 1 and not self.input_is_parallel:
            x = mappings.scatter_to_tensor_model_parallel_region(x, self.axis_name)
        if tp > 1 and self.sequence_parallel and self.collective_matmul:
            # region exit: partial products consumed piecewise by the
            # rotating accumulator ring — the full-rows pre-reduce
            # product never materializes
            y = matmul_reduce_scatter(
                x.astype(self.dtype),
                kernel.astype(self.dtype),
                self.axis_name,
                self.collective_matmul_chunk,
                self.comm_dtype,
            )
        else:
            y = jnp.dot(
                x.astype(self.dtype),
                kernel.astype(self.dtype),
                preferred_element_type=self.dtype,
            )
            if tp > 1 and self.sequence_parallel:
                y = mappings.reduce_scatter_to_sequence_parallel_region(
                    y, self.axis_name, dim=-2
                )
            elif tp > 1:
                y = mappings.reduce_from_tensor_model_parallel_region(
                    y, self.axis_name
                )
        if tp > 1 and self.sequence_parallel and bias is not None:
            # the replicated bias lands on shard-local rows: its grad
            # is a partial row sum — identity fwd, psum bwd
            bias = mappings.copy_to_tensor_model_parallel_region(
                bias, self.axis_name
            )
        out_bias = None
        if bias is not None:
            if self.skip_bias_add:
                out_bias = bias.astype(self.dtype)
            else:
                y = y + bias.astype(self.dtype)
        return y, out_bias
