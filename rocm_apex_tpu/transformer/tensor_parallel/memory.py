"""Reusable flat memory buffers.

Reference: apex/transformer/tensor_parallel/memory.py:34-136
(MemoryBuffer + RingMemBuffer). The reference preallocates one big
device tensor and hands out zero-copy views to avoid allocator churn for
checkpointed activations. XLA owns TPU memory — buffers are assigned at
compile time and donation reuses them — so this is API-parity
scaffolding: `get()` returns reshaped slices of one array, and code
structured around ring buffers ports unchanged. Inside jit the whole
structure fuses away.
"""

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["MemoryBuffer", "RingMemBuffer", "allocate_mem_buff"]


class MemoryBuffer:
    """Contiguous pre-sized buffer with bump allocation
    (reference memory.py:34-118)."""

    def __init__(self, name: str, numel: int, dtype, track_usage: bool = False):
        self.name = name
        self.numel = numel
        self.dtype = dtype
        self.data = jnp.zeros((numel,), dtype=dtype)
        self._start = 0
        self.track_usage = track_usage
        self.in_use_value = 0.0
        self.total_value = 0.0

    def reset(self):
        self._start = 0

    def is_in_use(self) -> bool:
        return self._start > 0

    def numel_in_use(self) -> int:
        return self._start

    def add(self, shape: Tuple[int, ...]) -> jnp.ndarray:
        """Bump-allocate a view of `shape` (reference memory.py:77-93)."""
        numel = int(np.prod(shape))
        if self._start + numel > self.numel:
            raise RuntimeError(
                f"MemoryBuffer {self.name}: out of space "
                f"({self._start}+{numel} > {self.numel})"
            )
        view = self.data[self._start : self._start + numel].reshape(shape)
        self._start += numel
        if self.track_usage:
            self.in_use_value += float(numel)
            self.total_value += float(self.numel)
        return view

    def get_data(self) -> jnp.ndarray:
        return self.data

    def print_average_usage(self):
        if self.track_usage and self.total_value:
            print(
                f" > usage of {self.name} memory buffer: "
                f"{self.in_use_value * 100.0 / self.total_value:.2f} %"
            )


class RingMemBuffer:
    """Ring of `num_buffers` MemoryBuffers (reference memory.py:121-136)."""

    def __init__(self, name: str, num_buffers: int, numel: int, dtype, track_usage=False):
        self.num_buffers = num_buffers
        self.buffers = [
            MemoryBuffer(f"{name} {i}", numel, dtype, track_usage)
            for i in range(num_buffers)
        ]
        self._index = -1

    def get_next_buffer(self) -> MemoryBuffer:
        self._index = (self._index + 1) % self.num_buffers
        buff = self.buffers[self._index]
        if buff.is_in_use():
            raise RuntimeError("buffer is already in use")
        return buff


def allocate_mem_buff(name: str, numel: int, dtype, track_usage: bool = False):
    """Reference memory.py:24-31."""
    return MemoryBuffer(name, numel, dtype, track_usage)
