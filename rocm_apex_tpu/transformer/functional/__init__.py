"""Functional fused ops for the transformer layer.

Reference: apex/transformer/functional/ (fused_softmax.py).
"""

from rocm_apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    FusedScaleMaskSoftmax,
    ScaledMaskedSoftmax,
    ScaledUpperTriangMaskedSoftmax,
)

__all__ = [
    "FusedScaleMaskSoftmax",
    "ScaledMaskedSoftmax",
    "ScaledUpperTriangMaskedSoftmax",
]
