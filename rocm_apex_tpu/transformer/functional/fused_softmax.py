"""FusedScaleMaskSoftmax: kernel-eligibility dispatch + fallback.

Rebuild of the reference module
(reference: apex/transformer/functional/fused_softmax.py —
`ScaledUpperTriangMaskedSoftmax:21`, `ScaledMaskedSoftmax:67` autograd
wrappers over the megatron kernels, and `FusedScaleMaskSoftmax:95`
whose `is_kernel_available:155-174` gates on fp16/bf16 dtype and
16 < seq_k <= 2048 divisibility before falling back to
`forward_torch_softmax:184`).

The Pallas kernels (ops/softmax.py) have no 2048 ceiling, so the
eligibility check shrinks to "floating input + kernel enabled"; the
reference's constraint surface is kept as attributes so callers can
still reason about it, and the jnp fallback reproduces
forward_torch_softmax exactly (mask fill with -10000.0).
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from rocm_apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from rocm_apex_tpu.transformer.enums import AttnMaskType

__all__ = [
    "ScaledUpperTriangMaskedSoftmax",
    "ScaledMaskedSoftmax",
    "FusedScaleMaskSoftmax",
]


def ScaledUpperTriangMaskedSoftmax(x, scale: float = 1.0):
    """(b, sq, sk) causal scaled softmax (reference fused_softmax.py:21-64;
    kernel csrc/megatron/scaled_upper_triang_masked_softmax*)."""
    return scaled_upper_triang_masked_softmax(x, scale)


def ScaledMaskedSoftmax(x, mask, scale: float = 1.0):
    """(b, n, sq, sk) scaled softmax with bool padding mask
    (True = masked) (reference fused_softmax.py:67-92)."""
    return scaled_masked_softmax(x, mask, scale)


class FusedScaleMaskSoftmax:
    """Dispatching softmax (reference fused_softmax.py:95-199).

    Constructor mirrors the reference: input/softmax fp16|bf16 flags,
    attn_mask_type, masked-softmax fusion toggle, optional mask_func
    for the fallback, softmax_in_fp32, scale.
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = True,
        attn_mask_type: AttnMaskType = AttnMaskType.causal,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """The reference gates on dtype + 16 < sk <= 2048 + divisibility
        (fused_softmax.py:155-174); the Pallas kernels only need a
        floating input and the fusion toggle."""
        return bool(self.scaled_masked_softmax_fusion and sk > 1)

    def __call__(self, x, mask=None):
        b, np_, sq, sk = x.shape
        scale = self.scale if self.scale is not None else 1.0
        if self.is_kernel_available(mask, b, np_, sq, sk):
            if self.attn_mask_type == AttnMaskType.causal:
                assert sq == sk, "causal mask is only for self attention"
                probs = scaled_upper_triang_masked_softmax(
                    x.reshape(-1, sq, sk), scale
                )
                return probs.reshape(b, np_, sq, sk)
            if mask is not None:
                return scaled_masked_softmax(x, mask, scale)
            # no mask: plain scaled softmax via the masked kernel
            zeros = jnp.zeros((b, 1, sq, sk), bool)
            return scaled_masked_softmax(x, zeros, scale)
        return self.forward_jnp_softmax(x, mask)

    def forward_jnp_softmax(self, x, mask):
        """forward_torch_softmax semantics (reference
        fused_softmax.py:184-199): optional fp32 upcast, scale,
        mask_func (default fill -10000.0), softmax, cast back."""
        orig = x.dtype
        if self.input_in_float16 and self.softmax_in_fp32:
            x = x.astype(jnp.float32)
        if self.scale is not None:
            x = x * self.scale
        if self.attn_mask_type == AttnMaskType.causal:
            sq, sk = x.shape[-2], x.shape[-1]
            causal = ~jnp.tril(jnp.ones((sq, sk), bool))
            mask = causal if mask is None else (mask | causal)
        if mask is not None:
            fill = self.mask_func or (
                lambda x, m: jnp.where(m, -10000.0, x)
            )
            x = fill(x, mask)
        probs = jax.nn.softmax(x, axis=-1)
        if self.input_in_float16 and self.softmax_in_fp32:
            probs = probs.astype(orig)
        return probs
