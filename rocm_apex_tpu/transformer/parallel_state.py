"""Model-parallel state: the TPU-native "mpu".

Redesign of the reference's process-group manager
(reference: apex/transformer/parallel_state.py:25-396). The reference
builds NCCL process groups (data-parallel, tensor-MP, pipeline-MP,
model = TP×PP, embedding) with TP-fastest rank mapping
(initialize_model_parallel:58-167). On TPU there are no process groups:
a single `jax.sharding.Mesh` with named axes ``('data', 'pipe', 'tensor')``
plays that role, and "groups" become mesh axes that collectives
(`psum`/`all_gather`/`ppermute`) name directly. XLA lays the axes onto
ICI; the TP axis is innermost so TP collectives ride the fastest links —
the same locality goal as the reference's TP-fastest rank mapping.

Single-controller JAX has no "current rank" at trace time; rank-dependent
logic lives either (a) inside `shard_map` via `lax.axis_index(axis)` or
(b) in schedule construction via the explicit ``rank=`` arguments the
getters accept (mirroring the reference API, which reads the implicit
process rank).

Axis names: ``data`` (DP), ``pipe`` (PP), ``tensor`` (TP). An optional
``expert`` axis and ``context`` axis are supported for EP/SP meshes —
capability the reference lacks (SURVEY.md §5 "long-context: limited") but
that falls out of the mesh design.
"""

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "initialize_model_parallel",
    "model_parallel_is_initialized",
    "destroy_model_parallel",
    "get_mesh",
    "get_data_parallel_axis_name",
    "get_tensor_model_parallel_axis_name",
    "get_pipeline_model_parallel_axis_name",
    "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
    "get_data_parallel_world_size",
    "get_tensor_model_parallel_rank",
    "get_pipeline_model_parallel_rank",
    "get_data_parallel_rank",
    "is_pipeline_first_stage",
    "is_pipeline_last_stage",
    "get_virtual_pipeline_model_parallel_rank",
    "set_virtual_pipeline_model_parallel_rank",
    "get_virtual_pipeline_model_parallel_world_size",
    "get_pipeline_model_parallel_split_rank",
    "get_num_layers",
    "get_rank_info",
]

DATA_AXIS = "data"
PIPE_AXIS = "pipe"
TENSOR_AXIS = "tensor"
CONTEXT_AXIS = "context"
EXPERT_AXIS = "expert"

# Module-level state, mirroring the reference's group globals
# (reference: parallel_state.py:25-50).
_MESH: Optional[Mesh] = None
_TENSOR_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_DATA_PARALLEL_WORLD_SIZE: Optional[int] = None
_CONTEXT_PARALLEL_WORLD_SIZE: int = 1
_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_PIPELINE_MODEL_PARALLEL_SPLIT_RANK: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    *,
    context_parallel_size_: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build and install the global device mesh.

    Validation and factoring semantics follow the reference
    (reference: parallel_state.py:58-167): world size must be divisible by
    tp*pp (*cp here), data-parallel size is the quotient, and virtual
    pipelining requires pp ≥ 2.

    Returns the `jax.sharding.Mesh` with axes (data, pipe, [context,]
    tensor), TP innermost.
    """
    global _MESH, _TENSOR_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_WORLD_SIZE, _DATA_PARALLEL_WORLD_SIZE
    global _CONTEXT_PARALLEL_WORLD_SIZE
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK

    if devices is None:
        devices = jax.devices()
    world_size = len(devices)
    tp, pp, cp = (
        tensor_model_parallel_size_,
        pipeline_model_parallel_size_,
        context_parallel_size_,
    )
    model_size = tp * pp * cp
    if world_size % model_size != 0:
        raise RuntimeError(
            f"world size ({world_size}) is not divisible by tensor parallel "
            f"size ({tp}) x pipeline parallel size ({pp}) x context parallel "
            f"size ({cp})"
        )
    dp = world_size // model_size

    if virtual_pipeline_model_parallel_size_ is not None:
        if pp <= 2:
            raise RuntimeError(
                "pipeline-model-parallel size should be greater than 2 with "
                "interleaved schedule"
            )
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = 0
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = (
            virtual_pipeline_model_parallel_size_
        )
    else:
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = pipeline_model_parallel_split_rank_

    # Mesh layout (data, pipe, [context,] tensor): TP innermost = adjacent
    # devices, matching the reference's TP-contiguous rank mapping
    # (parallel_state.py:117-135) and putting TP traffic on the shortest
    # ICI paths.
    dev_array = np.asarray(devices).reshape(dp, pp, cp, tp)
    axis_names: Tuple[str, ...]
    if cp > 1:
        axis_names = (DATA_AXIS, PIPE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)
    else:
        dev_array = dev_array.reshape(dp, pp, tp)
        axis_names = (DATA_AXIS, PIPE_AXIS, TENSOR_AXIS)

    _MESH = Mesh(dev_array, axis_names)
    _TENSOR_MODEL_PARALLEL_WORLD_SIZE = tp
    _PIPELINE_MODEL_PARALLEL_WORLD_SIZE = pp
    _DATA_PARALLEL_WORLD_SIZE = dp
    _CONTEXT_PARALLEL_WORLD_SIZE = cp
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def destroy_model_parallel():
    """Reset all state (reference: parallel_state.py:373-396)."""
    global _MESH, _TENSOR_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_WORLD_SIZE, _DATA_PARALLEL_WORLD_SIZE
    global _CONTEXT_PARALLEL_WORLD_SIZE
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _MESH = None
    _TENSOR_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _DATA_PARALLEL_WORLD_SIZE = None
    _CONTEXT_PARALLEL_WORLD_SIZE = 1
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = None


def _require_init():
    if _MESH is None:
        raise RuntimeError(
            "model parallel state is not initialized; call "
            "parallel_state.initialize_model_parallel(...) first"
        )


def get_mesh() -> Mesh:
    _require_init()
    return _MESH


def get_data_parallel_axis_name() -> str:
    return DATA_AXIS


def get_tensor_model_parallel_axis_name() -> str:
    return TENSOR_AXIS


def get_pipeline_model_parallel_axis_name() -> str:
    return PIPE_AXIS


def get_tensor_model_parallel_world_size() -> int:
    _require_init()
    return _TENSOR_MODEL_PARALLEL_WORLD_SIZE


def get_pipeline_model_parallel_world_size() -> int:
    _require_init()
    return _PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def get_data_parallel_world_size() -> int:
    _require_init()
    return _DATA_PARALLEL_WORLD_SIZE


def get_context_parallel_world_size() -> int:
    _require_init()
    return _CONTEXT_PARALLEL_WORLD_SIZE


# -- rank helpers -------------------------------------------------------
#
# Inside shard_map these return traced values via lax.axis_index; in
# schedule-construction code pass `rank=` explicitly.


def get_tensor_model_parallel_rank(rank: Optional[int] = None):
    if rank is not None:
        return rank
    return jax.lax.axis_index(TENSOR_AXIS)


def get_pipeline_model_parallel_rank(rank: Optional[int] = None):
    if rank is not None:
        return rank
    return jax.lax.axis_index(PIPE_AXIS)


def get_data_parallel_rank(rank: Optional[int] = None):
    if rank is not None:
        return rank
    return jax.lax.axis_index(DATA_AXIS)


def is_pipeline_first_stage(rank: Optional[int] = None, ignore_virtual: bool = False):
    """First-stage predicate (reference: parallel_state.py:277-292).

    With virtual pipelining, only virtual chunk 0 on stage 0 is "first"
    unless ignore_virtual.
    """
    if not ignore_virtual:
        vp = _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
        if vp is not None and get_virtual_pipeline_model_parallel_rank() != 0:
            return False
    r = get_pipeline_model_parallel_rank(rank)
    return r == 0


def is_pipeline_last_stage(rank: Optional[int] = None, ignore_virtual: bool = False):
    if not ignore_virtual:
        vp = _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
        if vp is not None and get_virtual_pipeline_model_parallel_rank() != (vp - 1):
            return False
    r = get_pipeline_model_parallel_rank(rank)
    last = get_pipeline_model_parallel_world_size() - 1
    return r == last


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int):
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = rank


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def get_num_layers(num_layers: int, is_encoder_and_decoder_model: bool = False) -> int:
    """Layers per pipeline stage (reference: parallel_state.py:313-345)."""
    _require_init()
    pp = get_pipeline_model_parallel_world_size()
    if pp > 1:
        if is_encoder_and_decoder_model:
            split = _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
            if split is None:
                raise RuntimeError(
                    "pipeline_model_parallel_split_rank must be set for "
                    "encoder-decoder models with pipeline parallelism"
                )
            num_ranks_in_encoder = split
            num_ranks_in_decoder = pp - split
            if num_layers % num_ranks_in_encoder != 0:
                raise RuntimeError(
                    f"num_layers ({num_layers}) must be divisible by number of "
                    f"ranks given to encoder ({num_ranks_in_encoder})"
                )
            return num_layers // num_ranks_in_encoder
        if num_layers % pp != 0:
            raise RuntimeError(
                f"num_layers ({num_layers}) must be divisible by pipeline "
                f"model parallel size ({pp})"
            )
        return num_layers // pp
    return num_layers


def get_rank_info() -> str:
    """(tp, pp, dp) sizes + process index for rank-aware logging
    (reference: parallel_state.py:169-186)."""
    if model_parallel_is_initialized():
        return (
            f"tp{_TENSOR_MODEL_PARALLEL_WORLD_SIZE}-"
            f"pp{_PIPELINE_MODEL_PARALLEL_WORLD_SIZE}-"
            f"dp{_DATA_PARALLEL_WORLD_SIZE}-proc{jax.process_index()}"
        )
    return "(-, -, -)"
