"""Shared test utilities: mesh bring-up + toy pipeline models.

Reference: apex/transformer/testing/commons.py —
`initialize_distributed:70-123` (env-driven process-group setup) and
the one-linear-layer `MyModel:31-60` used to validate the pipeline
schedules before the full GPT.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from rocm_apex_tpu.transformer import parallel_state

__all__ = ["initialize_mesh", "MyLayer", "MyModel"]


def initialize_mesh(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    devices=None,
):
    """Mesh bring-up for tests (the analogue of the reference's
    `initialize_distributed`, commons.py:70-123 — env-var process
    groups become one mesh construction)."""
    parallel_state.destroy_model_parallel()
    return parallel_state.initialize_model_parallel(
        tensor_model_parallel_size,
        pipeline_model_parallel_size,
        virtual_pipeline_model_parallel_size,
        devices=devices,
    )


def MyLayer(hidden_size: int, pre_process: bool = False,
            post_process: bool = False):
    """One toy stage: tanh(x @ w + b) — the stage_fn form the pipeline
    schedules consume (reference MyModel implements set_input_tensor
    for the same purpose, commons.py:31-60)."""
    del pre_process, post_process  # stage position is implicit in SPMD

    def init(key):
        kw, kb = jax.random.split(key)
        return {
            "w": jax.random.normal(kw, (hidden_size, hidden_size))
            / jnp.sqrt(hidden_size),
            "b": jnp.zeros((hidden_size,)),
        }

    def apply(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    return init, apply


def MyModel(hidden_size: int, n_stages: int, key=None):
    """Stage-stacked toy model for the schedules: returns
    (stacked_params, stage_fn)."""
    key = key if key is not None else jax.random.PRNGKey(0)
    init, apply = MyLayer(hidden_size)
    params = [init(jax.random.fold_in(key, i)) for i in range(n_stages)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params)
    return stacked, apply
