"""Megatron-style argument system — the full schema.

Rebuild of the reference's de-facto config surface (reference:
apex/transformer/testing/arguments.py, 806 LoC): every flag group
(network size, logging, regularization, training, initialization,
learning rate, checkpointing, mixed precision, distributed, validation,
data, autoresume, biencoder, vit), the deprecated-flag rejections, the
``--checkpoint-activations`` migration, and the full post-parse
validation web — so downstream Megatron-style launch scripts parse
unchanged.

TPU adaptations (each marked at its flag):
* ``world_size`` defaults to `jax.device_count()` when WORLD_SIZE is
  unset (single-controller JAX has no torch.distributed env);
* ``params_dtype`` is a jnp dtype;
* CUDA-only knobs (NCCL backend names, contiguous DDP buffers, CUDA
  empty-cache levels, tensorboard plumbing) are accepted-unused for
  script compatibility;
* validation failures raise ``ValueError`` with the reference's
  message text (the reference uses bare asserts).
"""

import argparse
import os

__all__ = ["parse_args"]


def _fail(cond, message):
    if not cond:
        raise ValueError(message)


def parse_args(extra_args_provider=None, defaults=None,
               ignore_unknown_args=False, args=None):
    """Parse all arguments (reference arguments.py:23-260)."""
    parser = argparse.ArgumentParser(
        description="rocm_apex_tpu Arguments", allow_abbrev=False
    )
    _add_network_size_args(parser)
    _add_regularization_args(parser)
    _add_training_args(parser)
    _add_initialization_args(parser)
    _add_learning_rate_args(parser)
    _add_checkpointing_args(parser)
    _add_mixed_precision_args(parser)
    _add_distributed_args(parser)
    _add_validation_args(parser)
    _add_data_args(parser)
    _add_autoresume_args(parser)
    _add_biencoder_args(parser)
    _add_vit_args(parser)
    _add_logging_args(parser)
    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    # ---- distributed sizes (reference :55-88). WORLD_SIZE wins when
    # set (launcher compatibility); otherwise the visible device count.
    import jax

    parsed.rank = int(os.getenv("RANK", "0"))
    parsed.world_size = int(
        os.environ.get("WORLD_SIZE", jax.device_count())
    )
    parsed.tensor_model_parallel_size = min(
        parsed.tensor_model_parallel_size, parsed.world_size
    )
    _fail(
        parsed.world_size % parsed.tensor_model_parallel_size == 0,
        "world size ({}) is not divisible by tensor model parallel size "
        "({})".format(parsed.world_size, parsed.tensor_model_parallel_size),
    )
    parsed.pipeline_model_parallel_size = min(
        parsed.pipeline_model_parallel_size,
        parsed.world_size // parsed.tensor_model_parallel_size,
    )
    model_parallel_size = (
        parsed.pipeline_model_parallel_size
        * parsed.tensor_model_parallel_size
    )
    _fail(
        parsed.world_size % model_parallel_size == 0,
        "world size is not divisible by tensor parallel size ({}) times "
        "pipeline parallel size ({})".format(
            parsed.tensor_model_parallel_size,
            parsed.pipeline_model_parallel_size,
        ),
    )
    parsed.data_parallel_size = parsed.world_size // model_parallel_size
    if parsed.pipeline_model_parallel_size > 1:
        if parsed.pipeline_model_parallel_split_rank is not None:
            _fail(
                parsed.pipeline_model_parallel_split_rank
                < parsed.pipeline_model_parallel_size,
                "split rank needs to be less than pipeline model parallel "
                "size ({})".format(parsed.pipeline_model_parallel_size),
            )

    # ---- deprecated arguments (reference :90-106)
    _fail(
        parsed.batch_size is None,
        "--batch-size argument is no longer valid, use "
        "--micro-batch-size instead",
    )
    del parsed.batch_size
    _fail(
        parsed.warmup is None,
        "--warmup argument is no longer valid, use "
        "--lr-warmup-fraction instead",
    )
    del parsed.warmup
    _fail(
        parsed.model_parallel_size is None,
        "--model-parallel-size is no longer valid, use "
        "--tensor-model-parallel-size instead",
    )
    del parsed.model_parallel_size
    if parsed.checkpoint_activations:
        parsed.activations_checkpoint_method = "uniform"
    del parsed.checkpoint_activations

    # ---- input defaults (reference :108-120): fill only unset args
    if defaults:
        for k, v in defaults.items():
            if getattr(parsed, k, None) is None:
                setattr(parsed, k, v)

    # ---- batch size (reference :122-130)
    _fail(parsed.micro_batch_size is not None, "micro_batch_size argument is None")
    _fail(parsed.micro_batch_size > 0, "micro batch size must be positive")
    if parsed.global_batch_size is None:
        parsed.global_batch_size = (
            parsed.micro_batch_size * parsed.data_parallel_size
        )
    _fail(parsed.global_batch_size > 0, "global batch size must be positive")

    # ---- virtual pipeline (reference :131-142)
    if parsed.num_layers_per_virtual_pipeline_stage is not None:
        _fail(parsed.num_layers is not None, "num_layers argument is None")
        _fail(
            parsed.pipeline_model_parallel_size > 2,
            "pipeline-model-parallel size should be greater than 2 with "
            "interleaved schedule",
        )
        _fail(
            parsed.num_layers
            % parsed.num_layers_per_virtual_pipeline_stage
            == 0,
            "number of layers is not divisible by number of layers per "
            "virtual pipeline stage",
        )
        parsed.virtual_pipeline_model_parallel_size = (
            parsed.num_layers // parsed.pipeline_model_parallel_size
        ) // parsed.num_layers_per_virtual_pipeline_stage
        # beyond the reference: a per-stage chunk larger than
        # layers-per-pipeline-stage silently derives vp == 0 there and
        # crashes downstream; fail at parse time instead
        _fail(
            parsed.virtual_pipeline_model_parallel_size >= 1,
            "number of layers is not divisible by number of model chunks",
        )
    else:
        parsed.virtual_pipeline_model_parallel_size = None

    # ---- parameters dtype (reference :144-162; jnp, not torch)
    import jax.numpy as jnp

    _fail(
        not (parsed.fp16 and parsed.bf16),
        "cannot specify both fp16 and bf16",
    )
    parsed.params_dtype = jnp.float32
    if parsed.fp16:
        parsed.params_dtype = jnp.float16
    if parsed.bf16:
        parsed.params_dtype = jnp.bfloat16
        # bfloat16 requires gradient accumulation and all-reduce in fp32
        parsed.accumulate_allreduce_grads_in_fp32 = True

    # the reference's contiguous-buffer constraints are CUDA-DDP
    # bookkeeping; the flags exist (accepted-unused) but XLA owns
    # buffers, so no constraint web is enforced here
    if parsed.DDP_impl == "torch":
        parsed.use_contiguous_buffers_in_local_ddp = False

    if parsed.dataloader_type is None:
        parsed.dataloader_type = "single"
    parsed.consumed_train_samples = 0
    parsed.consumed_valid_samples = 0

    # ---- iteration- vs sample-based training (reference :181-210)
    if parsed.train_iters:
        _fail(parsed.train_samples is None, "expected iteration-based training")
        _fail(
            parsed.lr_decay_samples is None,
            "expected iteration-based learning rate decay",
        )
        _fail(
            parsed.lr_warmup_samples == 0,
            "expected iteration-based learning rate warmup",
        )
        _fail(
            parsed.rampup_batch_size is None,
            "expected no batch-size rampup for iteration-based training",
        )
        if parsed.lr_warmup_fraction is not None:
            _fail(
                parsed.lr_warmup_iters == 0,
                "can only specify one of lr-warmup-fraction and "
                "lr-warmup-iters",
            )
    if parsed.train_samples:
        _fail(parsed.train_iters is None, "expected sample-based training")
        _fail(
            parsed.lr_decay_iters is None,
            "expected sample-based learning rate decay",
        )
        _fail(
            parsed.lr_warmup_iters == 0,
            "expected sample-based learnig rate warmup",
        )
        if parsed.lr_warmup_fraction is not None:
            _fail(
                parsed.lr_warmup_samples == 0,
                "can only specify one of lr-warmup-fraction and "
                "lr-warmup-samples",
            )

    # ---- required arguments (reference :212-216)
    for req_arg in (
        "num_layers", "hidden_size", "num_attention_heads",
        "max_position_embeddings",
    ):
        _fail(
            getattr(parsed, req_arg) is not None,
            "{} argument is None".format(req_arg),
        )

    # ---- derived network sizes (reference :218-224)
    if parsed.ffn_hidden_size is None:
        parsed.ffn_hidden_size = 4 * parsed.hidden_size
    if parsed.kv_channels is None:
        _fail(
            parsed.hidden_size % parsed.num_attention_heads == 0,
            "hidden size is not divisible by the number of attention heads",
        )
        parsed.kv_channels = (
            parsed.hidden_size // parsed.num_attention_heads
        )

    # ---- sequence lengths (reference :226-236)
    if parsed.seq_length is not None:
        _fail(
            parsed.encoder_seq_length is None,
            "--seq-length is exclusive of --encoder-seq-length",
        )
        parsed.encoder_seq_length = parsed.seq_length
    else:
        _fail(
            parsed.encoder_seq_length is not None,
            "either --seq-length or --encoder-seq-length must be provided",
        )
        parsed.seq_length = parsed.encoder_seq_length
    if parsed.seq_length is not None:
        _fail(
            parsed.max_position_embeddings >= parsed.seq_length,
            "max position embeddings must cover the sequence length",
        )
    if parsed.decoder_seq_length is not None:
        _fail(
            parsed.max_position_embeddings >= parsed.decoder_seq_length,
            "max position embeddings must cover the decoder sequence length",
        )
    if parsed.lr is not None:
        _fail(parsed.min_lr <= parsed.lr, "min-lr must not exceed lr")
    if parsed.save is not None:
        _fail(
            parsed.save_interval is not None,
            "--save requires --save-interval",
        )

    # ---- mixed precision checks (reference :241-246)
    if parsed.fp16_lm_cross_entropy:
        _fail(
            parsed.fp16,
            "lm cross entropy in fp16 only support in fp16 mode.",
        )
    if parsed.fp32_residual_connection:
        _fail(
            parsed.fp16 or parsed.bf16,
            "residual connection in fp32 only supported when using fp16 "
            "or bf16.",
        )

    # ---- activation checkpointing (reference :247-257)
    if parsed.distribute_checkpointed_activations:
        _fail(
            parsed.tensor_model_parallel_size > 1,
            "can distribute checkpointed activations only across tensor "
            "model parallel groups",
        )
        _fail(
            parsed.activations_checkpoint_method is not None,
            "for distribute-checkpointed-activations to work you need to "
            "use a activation-checkpoint method ",
        )
        _fail(
            parsed.num_layers_per_virtual_pipeline_stage is None,
            "currently distributed checkpoint activations only supported "
            "for nointerleaved pipeline parallelism",
        )
    return parsed


def _add_network_size_args(p):
    g = p.add_argument_group("network size")
    g.add_argument("--num-layers", type=int, default=None)
    g.add_argument("--hidden-size", type=int, default=None)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--num-attention-heads", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--apply-residual-connection-post-layernorm",
                   action="store_true")
    g.add_argument("--openai-gelu", action="store_true")
    g.add_argument("--onnx-safe", type=bool, required=False,
                   help="accepted for parity (no ONNX exporter here)")
    g.add_argument("--bert-no-binary-head", action="store_false",
                   dest="bert_binary_head")


def _add_logging_args(p):
    g = p.add_argument_group("logging")
    g.add_argument("--log-params-norm", action="store_true")
    g.add_argument("--log-num-zeros-in-grad", action="store_true")
    g.add_argument("--tensorboard-log-interval", type=int, default=1)
    g.add_argument("--tensorboard-queue-size", type=int, default=1000)
    g.add_argument("--log-timers-to-tensorboard", action="store_true")
    g.add_argument("--log-batch-size-to-tensorboard", action="store_true")
    g.add_argument("--no-log-learnig-rate-to-tensorboard",
                   action="store_false",
                   dest="log_learning_rate_to_tensorboard")
    g.add_argument("--no-log-loss-scale-to-tensorboard",
                   action="store_false",
                   dest="log_loss_scale_to_tensorboard")
    g.add_argument("--log-validation-ppl-to-tensorboard",
                   action="store_true")
    g.add_argument("--log-memory-to-tensorboard", action="store_true")


def _add_regularization_args(p):
    g = p.add_argument_group("regularization")
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)


def _add_training_args(p):
    g = p.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=None)
    g.add_argument("--batch-size", type=int, default=None,
                   help="Old batch size parameter, do not use. "
                   "Use --micro-batch-size instead")
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)
    g.add_argument("--checkpoint-activations", action="store_true",
                   help="deprecated: migrates to "
                   "--activations-checkpoint-method uniform")
    g.add_argument("--distribute-checkpointed-activations",
                   action="store_true")
    g.add_argument("--activations-checkpoint-method", type=str,
                   default=None, choices=["uniform", "block"])
    g.add_argument("--activations-checkpoint-num-layers", type=int,
                   default=1)
    g.add_argument("--train-iters", type=int, default=None)
    g.add_argument("--train-samples", type=int, default=None)
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--exit-duration-in-mins", type=int, default=None)
    g.add_argument("--tensorboard-dir", type=str, default=None)
    g.add_argument("--no-masked-softmax-fusion", action="store_false",
                   dest="masked_softmax_fusion")
    g.add_argument("--no-bias-gelu-fusion", action="store_false",
                   dest="bias_gelu_fusion",
                   help="accepted for parity; XLA fuses bias+gelu")
    g.add_argument("--no-bias-dropout-fusion", action="store_false",
                   dest="bias_dropout_fusion",
                   help="accepted for parity; XLA fuses bias+dropout")
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "sgd"])
    g.add_argument("--dataloader-type", type=str, default=None,
                   choices=["single", "cyclic"])
    g.add_argument("--no-async-tensor-model-parallel-allreduce",
                   action="store_false",
                   dest="async_tensor_model_parallel_allreduce",
                   help="disable the collective-matmul ring at the TP "
                        "boundaries (ops/collective_matmul); the plain "
                        "backward-psum overlap is XLA's either way — "
                        "see docs/migration.md")
    g.add_argument("--sequence-parallel", action="store_true",
                   help="shard the activations between TP boundaries "
                        "over the sequence (GPTConfig.sequence_parallel)")
    g.add_argument("--collective-matmul", action="store_true",
                   help="fuse the sequence-parallel boundary "
                        "collectives into ppermute-ring matmuls")


def _add_initialization_args(p):
    g = p.add_argument_group("initialization")
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--init-method-xavier-uniform", action="store_true")


def _add_learning_rate_args(p):
    g = p.add_argument_group("learning rate")
    g.add_argument("--lr", type=float, default=None)
    g.add_argument("--lr-decay-style", type=str, default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-decay-samples", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--lr-warmup-iters", type=int, default=0)
    g.add_argument("--lr-warmup-samples", type=int, default=0)
    g.add_argument("--warmup", type=int, default=None,
                   help="Old lr warmup argument, do not use. Use one of "
                   "the --lr-warmup-* arguments above")
    g.add_argument("--min-lr", type=float, default=0.0)
    g.add_argument("--override-lr-scheduler", action="store_true")
    g.add_argument("--use-checkpoint-lr-scheduler", action="store_true")


def _add_checkpointing_args(p):
    g = p.add_argument_group("checkpointing")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--no-save-optim", action="store_true", default=None)
    g.add_argument("--no-save-rng", action="store_true", default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--no-load-optim", action="store_true", default=None)
    g.add_argument("--no-load-rng", action="store_true", default=None)
    g.add_argument("--finetune", action="store_true")


def _add_mixed_precision_args(p):
    g = p.add_argument_group("mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2**32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--fp32-residual-connection", action="store_true")
    g.add_argument("--no-query-key-layer-scaling", action="store_false",
                   dest="apply_query_key_layer_scaling")
    g.add_argument("--attention-softmax-in-fp32", action="store_true")
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    g.add_argument("--fp16-lm-cross-entropy", action="store_true")


def _add_distributed_args(p):
    g = p.add_argument_group("distributed")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-split-rank", type=int,
                   default=None)
    g.add_argument("--model-parallel-size", type=int, default=None,
                   help="Old model parallel argument, do not use. Use "
                   "--tensor-model-parallel-size instead.")
    g.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                   default=None)
    g.add_argument("--distributed-backend", default="xla",
                   choices=["xla", "nccl", "gloo"],
                   help="accepted for parity; collectives are XLA's")
    g.add_argument("--DDP-impl", default="local",
                   choices=["local", "torch"],
                   help="accepted for parity")
    g.add_argument("--no-contiguous-buffers-in-local-ddp",
                   action="store_false",
                   dest="use_contiguous_buffers_in_local_ddp",
                   help="accepted for parity; XLA owns buffers")
    g.add_argument("--no-scatter-gather-tensors-in-pipeline",
                   action="store_false",
                   dest="scatter_gather_tensors_in_pipeline")
    g.add_argument("--local_rank", type=int, default=None)
    g.add_argument("--lazy-mpu-init", type=bool, required=False)
    g.add_argument("--use-cpu-initialization", action="store_true",
                   default=None,
                   help="accepted for parity; initialization is functional")
    g.add_argument("--empty-unused-memory-level", default=0, type=int,
                   choices=[0, 1, 2],
                   help="accepted for parity; no CUDA caches to empty")
    g.add_argument("--use-ring-exchange-p2p", action="store_true")


def _add_validation_args(p):
    g = p.add_argument_group("validation")
    g.add_argument("--eval-iters", type=int, default=100)
    g.add_argument("--eval-interval", type=int, default=1000)


def _add_data_args(p):
    g = p.add_argument_group("data and dataloader")
    g.add_argument("--data-path", nargs="*", default=None)
    g.add_argument("--split", type=str, default="969, 30, 1")
    g.add_argument("--vocab-file", type=str, default=None)
    g.add_argument("--merge-file", type=str, default=None)
    g.add_argument("--vocab-extra-ids", type=int, default=0)
    g.add_argument("--seq-length", type=int, default=None)
    g.add_argument("--encoder-seq-length", type=int, default=None)
    g.add_argument("--decoder-seq-length", type=int, default=None)
    g.add_argument("--retriever-seq-length", type=int, default=256)
    g.add_argument("--sample-rate", type=float, default=1.0)
    g.add_argument("--mask-prob", type=float, default=0.15)
    g.add_argument("--short-seq-prob", type=float, default=0.1)
    g.add_argument("--mmap-warmup", action="store_true")
    g.add_argument("--num-workers", type=int, default=2)
    g.add_argument("--tokenizer-type", type=str, default=None,
                   choices=["BertWordPieceLowerCase", "BertWordPieceCase",
                            "GPT2BPETokenizer"])
    g.add_argument("--data-impl", type=str, default="infer",
                   choices=["lazy", "cached", "mmap", "infer"])
    g.add_argument("--reset-position-ids", action="store_true")
    g.add_argument("--reset-attention-mask", action="store_true")
    g.add_argument("--eod-mask-loss", action="store_true")


def _add_autoresume_args(p):
    g = p.add_argument_group("autoresume")
    g.add_argument("--adlr-autoresume", action="store_true")
    g.add_argument("--adlr-autoresume-interval", type=int, default=1000)


def _add_biencoder_args(p):
    g = p.add_argument_group("biencoder")
    g.add_argument("--ict-head-size", type=int, default=None)
    g.add_argument("--biencoder-projection-dim", type=int, default=0)
    g.add_argument("--biencoder-shared-query-context-model",
                   action="store_true")
    g.add_argument("--ict-load", type=str, default=None)
    g.add_argument("--bert-load", type=str, default=None)
    g.add_argument("--titles-data-path", type=str, default=None)
    g.add_argument("--query-in-block-prob", type=float, default=0.1)
    g.add_argument("--use-one-sent-docs", action="store_true")
    g.add_argument("--evidence-data-path", type=str, default=None)
    g.add_argument("--retriever-report-topk-accuracies", nargs="+",
                   type=int, default=[])
    g.add_argument("--retriever-score-scaling", action="store_true")
    g.add_argument("--block-data-path", type=str, default=None)
    g.add_argument("--embedding-path", type=str, default=None)
    g.add_argument("--indexer-batch-size", type=int, default=128)
    g.add_argument("--indexer-log-interval", type=int, default=1000)


def _add_vit_args(p):
    g = p.add_argument_group("vit")
    g.add_argument("--num-classes", type=int, default=1000)
    g.add_argument("--img-dim", type=int, default=224)
    g.add_argument("--num-channels", type=int, default=3)
    g.add_argument("--patch-dim", type=int, default=16)
